#!/usr/bin/env python
"""CI probe for the live scrape endpoint (ISSUE 13 satellite).

Spins an in-process 2-rank CPU gateway pool with the metrics endpoint
on, runs one tenant cell through it (so the stage histograms and the
latency ring hold real data), then:

- ``GET /healthz`` must return 200 JSON;
- ``GET /metrics`` (pool-token-gated) must return 200 with exposition
  text that parses (``metrics.validate_prometheus_text``) and carries
  the latency-observatory series (``nbd_stage_seconds``);
- an ungated ``GET /metrics`` must be refused (401);
- ``GET /latency.json`` must return the summary + at least one stage
  record, and is written to ``--out`` for the CI artifact upload.

Exit 0 on success, 1 with the failures listed otherwise.  Run it the
way CI does::

    JAX_PLATFORMS=cpu python tools/nbd_metrics_check.py --out /tmp/latency.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _get(url: str, timeout: float = 10.0) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="/tmp/latency.json",
                   help="where to write the /latency.json payload")
    p.add_argument("--workers", type=int, default=2)
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from nbdistributed_tpu.gateway.client import TenantClient
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon
    from nbdistributed_tpu.observability.metrics import \
        validate_prometheus_text

    failures: list[str] = []
    print(f"[metrics-check] starting {args.workers}-rank cpu pool "
          "with an ephemeral metrics port", flush=True)
    # metrics_port=-1 = "bind an ephemeral OS-assigned port" (0 means
    # off, matching the knob) — pre-claiming a port and re-binding it
    # would be a TOCTOU race a busy CI runner can lose.
    gw = GatewayDaemon(args.workers, backend="cpu",
                       metrics_port=-1)
    try:
        base = f"http://127.0.0.1:{gw._metrics_httpd.port}"
        client = TenantClient("127.0.0.1", gw.tenant_port, "ci-probe",
                              pool_token=gw.pool_token)
        try:
            res = client.execute("rank + 1", timeout=120.0)
            if res.get("status") != "ok":
                failures.append(f"probe cell failed: {res}")
        finally:
            client.close()

        code, body = _get(f"{base}/healthz")
        if code != 200:
            failures.append(f"/healthz returned {code}")
        else:
            h = json.loads(body)
            print(f"[metrics-check] /healthz: {h}", flush=True)
            if h.get("dead"):
                failures.append(f"/healthz reports dead ranks: {h}")

        code, _ = _get(f"{base}/metrics")
        if code != 401:
            failures.append(
                f"ungated /metrics returned {code}, expected 401")

        code, body = _get(f"{base}/metrics?token={gw.pool_token}")
        if code != 200:
            failures.append(f"/metrics returned {code}")
        else:
            text = body.decode("utf-8")
            errs = validate_prometheus_text(text)
            failures += [f"/metrics: {e}" for e in errs]
            for series in ("nbd_stage_seconds", "nbd_cell_e2e_seconds",
                           "nbd_flight_ring_utilization",
                           "nbd_wire_messages_total"):
                if series not in text:
                    failures.append(
                        f"/metrics is missing the {series} series")
            print(f"[metrics-check] /metrics: {len(text.splitlines())} "
                  "lines, parse "
                  + ("clean" if not errs else f"FAILED ({len(errs)})"),
                  flush=True)

        code, body = _get(f"{base}/latency.json?token={gw.pool_token}")
        if code != 200:
            failures.append(f"/latency.json returned {code}")
        else:
            lat = json.loads(body)
            n = (lat.get("summary") or {}).get("count", 0)
            if not n:
                failures.append("/latency.json holds no stage records "
                                "after a completed cell")
            with open(args.out, "w") as f:
                json.dump(lat, f, indent=1)
            print(f"[metrics-check] /latency.json: {n} record(s) → "
                  f"{args.out}", flush=True)
    finally:
        gw.close()

    if failures:
        print("[metrics-check] FAILED:", flush=True)
        for f in failures:
            print(f"  - {f}", flush=True)
        return 1
    print("[metrics-check] OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
