#!/usr/bin/env python
"""Sweep stale nbdistributed_tpu session run dirs from the tmp root.

Run-dir siblings under ``<tmpdir>/nbd_runs`` accumulate one per
session (flight rings, postmortem bundles, the session manifest).  A
sibling is stale — and swept — when its manifest (or the directory,
when no manifest exists) is older than the TTL AND none of its
recorded worker pids are alive.  The current session's run dir
(``NBD_RUN_DIR``) and anything with a live pid are never touched.

The in-notebook equivalent is ``%dist_gc [--dry-run]``; this CLI is
for cron / CI cleanup outside any kernel:

    python tools/nbd_gc.py --dry-run
    python tools/nbd_gc.py --ttl-s 3600
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nbdistributed_tpu.resilience import session  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--root", default=None,
                   help="runs root (default: <tmpdir>/nbd_runs)")
    p.add_argument("--ttl-s", type=float, default=None,
                   help="stale age in seconds (default: NBD_GC_TTL_S, "
                        "else 6h)")
    p.add_argument("--dry-run", action="store_true",
                   help="list candidates without removing anything")
    args = p.parse_args(argv)
    res = session.gc_runs(args.root, ttl_s=args.ttl_s,
                          dry_run=args.dry_run)
    verb = "would sweep" if args.dry_run else "swept"
    print(f"{verb} {len(res['swept'])} stale run dir(s) under "
          f"{res['root']} (ttl {res['ttl_s']:.0f}s); "
          f"kept {len(res['kept'])}")
    for d in res["swept"]:
        print(f"  - {d}")
    if args.dry_run:
        for d in res["kept"]:
            why = res.get("kept_why", {}).get(d)
            print(f"  = kept {d}" + (f" — {why}" if why else ""))
    for e in res["errors"]:
        print(f"  ! {e}", file=sys.stderr)
    return 1 if res["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
