"""Tunnel timing-health preflight: print raw sample distributions.

Run at the START of a live window (tpu_watch.sh step 0).  For the
bench's pinned GQA shape it prints every raw wall-time sample for:

- chained-scan programs at n=2 and n=18 (6 fresh-input repeats each,
  XLA reference and the Pallas flash kernel), and
- 3 same-input repeats (result-cache probe: near-zero times here mean
  the tunnel serves repeated program+input pairs from a cache).

The 2026-08-01 window (BENCH_ATTEMPTS_r05.md) showed second-scale
one-off spikes and result-cache hits that single-shot timings cannot
survive; this preflight makes each window's noise profile part of the
record, so any later number that looks odd can be read against the
window's actual timing health.  No repo state is touched; output is
stderr-style plain lines, one JSON summary line at the end.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from nbdistributed_tpu.ops import attention_reference as ref
from nbdistributed_tpu.ops import flash_attention as flash
from nbdistributed_tpu.ops.timing import FRESH_FACTOR, chain_program
from nbdistributed_tpu.utils import knobs

SMOKE = bool(knobs.get_raw("NBD_PROBE_CPU_SMOKE"))
if SMOKE:
    B, S, H, Hkv, D = 1, 128, 2, 1, 64   # CPU-feasible harness check
else:
    B, S, H, Hkv, D = 4, 2048, 8, 2, 128


def probe(name: str, f, q, k, v, out: dict) -> None:
    # chain_program + FRESH_FACTOR come from ops/timing.py — the SAME
    # protocol constants the bench flash cell and tune_flash use, so
    # this noise profile is evidence about the programs they time.
    for n in (2, 18):
        g = chain_program(lambda qc: f(qc, k, v), n)
        t0 = time.time()
        float(g(q).sum())
        print(f"[probe] {name} n={n} compile+first: "
              f"{time.time() - t0:.3f}s", flush=True)
        fresh = []
        for i in range(6):
            qi = q * (1.0 + (i + 1) * FRESH_FACTOR)
            t0 = time.time()
            float(g(qi).sum())
            fresh.append(round((time.time() - t0) * 1e3, 2))
        same = []
        qi = q * (1.0 + FRESH_FACTOR)   # repeats fresh sample i=0
        for _ in range(3):
            t0 = time.time()
            float(g(qi).sum())
            same.append(round((time.time() - t0) * 1e3, 2))
        print(f"[probe] {name} n={n} fresh ms: {fresh}", flush=True)
        print(f"[probe] {name} n={n} same-input ms: {same}", flush=True)
        out[f"{name}_n{n}"] = {"fresh_ms": fresh, "same_input_ms": same}


def main() -> int:
    if jax.default_backend() != "tpu" and not SMOKE:
        print("probe_timing.py needs a live TPU (the pinned shape is "
              f"minutes/call on CPU; backend={jax.default_backend()})",
              file=sys.stderr)
        return 1
    out: dict = {"device": str(jax.devices()[0]),
                 "shape": f"B{B} S{S} H{H} Hkv{Hkv} D{D} bf16 causal"}
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, Hkv, D),
                          jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, Hkv, D),
                          jnp.bfloat16)
    probe("xla_ref", lambda a, b, c: ref(a, b, c, causal=True),
          q, k, v, out)
    if jax.default_backend() == "tpu":   # interpret mode: minutes/call
        probe("flash", lambda a, b, c: flash(a, b, c, True),
              q, k, v, out)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
