#!/usr/bin/env python
"""Perf-regression sentinel CLI (ISSUE 18).

Scores a pinned loadgen report (and, when available, the serving
observatory's stage summary) against the checked-in baseline file and
exits nonzero on regression — the CI gate that keeps the serving fast
path honest:

    # gate a fresh run against the checked-in contract:
    python tools/nbd_perfwatch.py --report /tmp/load.json \\
        --stages /tmp/latency.json --diff /tmp/perfwatch.json

    # seed / re-seed the baseline from a known-good run:
    python tools/nbd_perfwatch.py --report /tmp/load.json --update

    # the CI gate: spin the same 2-decode-rank CPU pool as the
    # loadgen smoke, drive it, and score the result in one shot
    # (--report/--stages become OUTPUT paths for artifact upload):
    JAX_PLATFORMS=cpu python tools/nbd_perfwatch.py --smoke \\
        --report /tmp/load.json --diff /tmp/perfwatch.json

The scoring contract lives in
:mod:`nbdistributed_tpu.observability.perfbase`: each watched metric
carries a direction and a noise band IN the baseline file, so the
checked-in artifact is the whole contract and ``--update`` preserves
hand-tuned bands.  ``--diff`` writes the machine-readable verdict
(one dict per metric) for CI artifact upload; the same content is
printed human-readably either way.

``NBD_PERFWATCH_BASELINE`` moves the baseline file for local
experiments; ``NBD_PERFWATCH_BAND_SCALE`` (or ``--band-scale``)
widens every band uniformly on noisy runners.  Exit code: 0 = within
bands (or just seeded), 1 = regression, 2 = could not run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nbdistributed_tpu.observability import perfbase  # noqa: E402
from nbdistributed_tpu.utils import knobs  # noqa: E402


def _load_json(path: str, what: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except Exception as e:
        raise SystemExit(f"cannot read {what} {path!r}: "
                         f"{type(e).__name__}: {e}")


# The smoke pool mirrors tests/integration/test_serving_fast.py::
# test_loadgen_smoke_two_ranks — 3 ranks, 2 of them decoding the tiny
# model over paged KV — so the checked-in baseline and the CI gate
# measure the exact same machine shape.
_SMOKE_SPEC = (
    "import jax as _j, jax.numpy as _jn\n"
    "from nbdistributed_tpu.models import tiny_config, init_params\n"
    "cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
    "params = init_params(_j.random.PRNGKey(0), cfg)\n")


def _run_smoke(report_path: str,
               stages_path: str | None) -> tuple[dict, dict | None]:
    """Spin the 2-decode-rank CPU pool, run the deterministic loadgen
    schedule against it, and return (report, stage_summary) — writing
    both to disk for CI artifact upload."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from nbdistributed_tpu.gateway.client import TenantClient
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon
    from nbdistributed_tpu.serving_fast import LoadConfig, run_load
    from nbdistributed_tpu.serving_fast.loadgen import ClientTransport

    print("[perfwatch] starting 3-rank cpu pool "
          "(2 decode ranks, paged KV)", file=sys.stderr, flush=True)
    gw = GatewayDaemon(3, backend="cpu", attach_timeout=240.0)
    stages = None
    try:
        client = TenantClient(gw.tenant_host, gw.tenant_port,
                              "perfwatch", pool_token=gw.pool_token)
        try:
            client.serve_start(_SMOKE_SPEC, max_batch=2, max_len=48,
                               pad_to=4, steps=2, queue_depth=8,
                               inflight=64, decode_ranks=2,
                               kv_block_tokens=8, timeout=600)
            cfg = LoadConfig(rps=3.0, duration_s=6.0, seed=1,
                             prompt_len=(2, 5), max_new=(4, 4),
                             drain_s=120.0)
            report = run_load(ClientTransport(client), cfg)
            lat = (client.serve_status() or {}).get("lat") or {}
            if "stages" in (lat.get("summary") or {}):
                stages = lat["summary"]
        finally:
            client.close(detach=True)
    finally:
        gw.close()

    with open(report_path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    if stages_path and stages is not None:
        with open(stages_path, "w", encoding="utf-8") as f:
            json.dump(stages, f, indent=2, sort_keys=True)
            f.write("\n")
    print(f"[perfwatch] smoke: offered={report.get('offered')} "
          f"completed={report.get('completed')} "
          f"tok/s={report.get('tokens_per_s')} → {report_path}",
          file=sys.stderr, flush=True)
    return report, stages


def _stage_summary(doc: dict | None) -> dict | None:
    """Accept either a bare ``ServingObservatory.summary()`` block or
    a whole ``/latency.json`` payload carrying one at
    ``serving.summary`` / ``lat.summary`` — whichever artifact the
    caller happened to save."""
    if not isinstance(doc, dict):
        return None
    if "stages" in doc:
        return doc
    for key in ("serving", "lat"):
        inner = doc.get(key)
        if isinstance(inner, dict) and "stages" in (
                inner.get("summary") or {}):
            return inner["summary"]
    return None


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="score a loadgen report against the checked-in "
                    "perf baseline (exit 1 on regression)")
    p.add_argument("--report", required=True,
                   help="loadgen JSON report (tools/nbd_loadgen.py "
                        "--report)")
    p.add_argument("--stages", default=None,
                   help="serving stage summary JSON — either a bare "
                        "summary block or a saved /latency.json")
    p.add_argument("--baseline",
                   default=knobs.get_str("NBD_PERFWATCH_BASELINE",
                                         "BENCH_BASELINES.json"),
                   help="baseline file (default: "
                        "$NBD_PERFWATCH_BASELINE)")
    p.add_argument("--key", default="serving_smoke",
                   help="baseline entry to gate against")
    p.add_argument("--band-scale", type=float,
                   default=knobs.get_float("NBD_PERFWATCH_BAND_SCALE",
                                           1.0),
                   help="uniform multiplier on every noise band")
    p.add_argument("--update", action="store_true",
                   help="seed/refresh the baseline entry from this "
                        "report instead of gating (keeps hand-tuned "
                        "bands)")
    p.add_argument("--diff", default=None,
                   help="write the machine-readable score here")
    p.add_argument("--source", default="",
                   help="provenance note stored with --update "
                        "(e.g. 'ci 2-rank cpu smoke')")
    p.add_argument("--smoke", action="store_true",
                   help="spin the 2-decode-rank CPU smoke pool and "
                        "generate the report/stages in-process "
                        "(--report/--stages become output paths)")
    args = p.parse_args(argv)

    try:
        if args.smoke:
            report, stages = _run_smoke(args.report, args.stages)
        else:
            report = _load_json(args.report, "loadgen report")
            stages = (_stage_summary(_load_json(args.stages,
                                                "stage summary"))
                      if args.stages else None)
        metrics = perfbase.extract_metrics(report, stages)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2
    except Exception as e:
        print(f"perfwatch smoke failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if not metrics:
        print(f"no gated metrics found in {args.report!r} — not a "
              "pinned loadgen report?", file=sys.stderr)
        return 2

    if args.update:
        doc: dict = {"baselines": {}}
        old_bands: dict[str, float] = {}
        if os.path.exists(args.baseline):
            try:
                doc = perfbase.load_baselines(args.baseline)
            except Exception as e:
                print(f"replacing unreadable baseline: {e}",
                      file=sys.stderr)
                doc = {"baselines": {}}
            old = (doc.get("baselines") or {}).get(args.key) or {}
            old_bands = {n: m["band"] for n, m in
                         (old.get("metrics") or {}).items()
                         if "band" in m}
        doc.setdefault("baselines", {})[args.key] = \
            perfbase.make_baseline(metrics, source=args.source,
                                   bands=old_bands)
        perfbase.save_baselines(args.baseline, doc)
        n = len(doc["baselines"][args.key]["metrics"])
        print(f"NBD_PERFWATCH seeded {args.baseline} "
              f"[{args.key}]: {n} gated metrics", file=sys.stderr)
        return 0

    try:
        doc = perfbase.load_baselines(args.baseline)
    except Exception as e:
        print(f"cannot load baseline {args.baseline!r}: {e}",
              file=sys.stderr)
        return 2
    entry = (doc.get("baselines") or {}).get(args.key)
    if not entry:
        print(f"baseline {args.baseline!r} has no entry "
              f"{args.key!r} — seed one with --update",
              file=sys.stderr)
        return 2

    result = perfbase.score(entry, metrics,
                            band_scale=args.band_scale)
    result["key"] = args.key
    result["baseline_file"] = args.baseline
    result["band_scale"] = args.band_scale
    if args.diff:
        with open(args.diff, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    print(perfbase.format_diff(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
