#!/usr/bin/env python
"""Run a session-gateway daemon: one pooled worker fleet, N tenants.

The gateway owns the workers and serves a tenant plane that notebook
kernels attach to with ``%dist_attach --tenant NAME`` (the in-notebook
spawner is ``%dist_pool start``).  Admission control, per-tenant
fair-share scheduling, backpressure, and crash fencing are described
in README "Session gateway & multi-tenancy".

    python tools/nbd_gateway.py -n 4 --backend cpu
    python tools/nbd_gateway.py -n 4 --sched fair --queue-depth 32

Equivalent module form: ``python -m nbdistributed_tpu.gateway.daemon``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nbdistributed_tpu.gateway.daemon import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
