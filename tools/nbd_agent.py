#!/usr/bin/env python
"""Host agent daemon for ssh-free multi-host worker launch.

Run one per host; the coordinator's ``ProcessManager`` dials it (over
the same authenticated ``NBDA`` codec the worker control plane uses)
to spawn, death-watch, signal, and tail workers on this host::

    echo "$SECRET" > /run/nbd_agent.secret
    python tools/nbd_agent.py --bind 10.0.0.3 --port 7411 \
        --token-file /run/nbd_agent.secret --host-label hostB

Then, from the notebook::

    %dist_init --hosts hostA,hostB --coordinator-addr 10.0.0.2 \
        --agents "hostA=10.0.0.2:7411,hostB=10.0.0.3:7411"

The agent prints ``NBD_AGENT_READY host=... port=...`` on stdout once
listening.  Workers it spawns get the agent host's OWN run dir
(flight rings, stack dumps — per-host, no shared filesystem assumed)
and its ``--host-label`` as ``NBD_HOST`` for per-link fault shaping
and per-host diagnosis.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nbdistributed_tpu.manager.hostagent import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
