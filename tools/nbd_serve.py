#!/usr/bin/env python
"""Thin HTTP shim over the gateway's serving plane (``%dist_serve``).

Attaches to a live gateway pool as one tenant and exposes its
generation ingress as plain HTTP — the zero-dependency way to put
real traffic through the serving plane (load generators, curl,
another service).  Stdlib only; one process, one tenant connection,
the gateway does all admission control and durability:

    python tools/nbd_serve.py --run-dir /tmp/nbd_runs/pool-x \\
        --port 8080

    curl -s localhost:8080/v1/submit -d \\
        '{"prompt": [5, 9, 2], "max_new_tokens": 16}'
        -> {"status": "accepted", "rid": "r0", ...}
        -> {"status": "shed" | "rejected", ...}  (explicit overload)
    curl -s localhost:8080/v1/result/r0
        -> {"status": "completed", "tokens": [...], "done": true}
    curl -s 'localhost:8080/v1/stream/r0?from=4'
        -> {"tokens": [...], "offset": 4, "done": ...}  (resume from
           the caller's last acked offset — exactly-once delivery is
           the gateway journal's, not this shim's)
    curl -s localhost:8080/v1/status

The shim is deliberately stateless: a restarted shim reattaches under
its tenant name (token from the gateway manifest) and every in-flight
request's stream remains claimable by offset — the same
reattach-mid-generation contract notebook kernels get.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nbdistributed_tpu.gateway import daemon as gw_mod  # noqa: E402
from nbdistributed_tpu.gateway.client import (  # noqa: E402
    CellSubmitError, TenantClient)


def make_handler(client: TenantClient):
    class Handler(BaseHTTPRequestHandler):
        def _json(self, code: int, data: dict) -> None:
            body = json.dumps(data).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet by default
            pass

        def do_POST(self):
            if self.path.rstrip("/") != "/v1/submit":
                self._json(404, {"error": "unknown endpoint"})
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
                req = json.loads(self.rfile.read(n) or b"{}")
                verdict = client.serve_submit(
                    req.get("prompt") or (),
                    int(req.get("max_new_tokens") or 0),
                    priority=req.get("priority"))
                self._json(200, verdict)
            except CellSubmitError as e:
                # Explicit overload verdicts map to 429/503, not 500:
                # the caller is meant to back off and retry.
                code = 429 if e.verdict.get("status") == "rejected" \
                    else 503
                self._json(code, e.verdict)
            except Exception as e:
                self._json(500, {"error": f"{type(e).__name__}: {e}"})

        def do_GET(self):
            path, _, query = self.path.partition("?")
            parts = [p for p in path.split("/") if p]
            try:
                if parts[:2] == ["v1", "result"] and len(parts) == 3:
                    self._json(200, client.serve_result(parts[2]))
                elif parts[:2] == ["v1", "stream"] and len(parts) == 3:
                    frm = 0
                    for kv in query.split("&"):
                        if kv.startswith("from="):
                            frm = int(kv[5:] or 0)
                    self._json(200, client.serve_stream(parts[2], frm))
                elif parts == ["v1", "status"]:
                    self._json(200, client.serve_status())
                else:
                    self._json(404, {"error": "unknown endpoint"})
            except Exception as e:
                self._json(500, {"error": f"{type(e).__name__}: {e}"})

    return Handler


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="HTTP ingress shim for the gateway serving plane")
    p.add_argument("--run-dir", default=None,
                   help="gateway run dir (default: discovery)")
    p.add_argument("--tenant", default="serve-http",
                   help="tenant name this shim attaches under")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    args = p.parse_args(argv)

    d = gw_mod.discover_gateway(args.run_dir)
    if d is None:
        print("no live gateway pool found (start one: "
              "python tools/nbd_gateway.py -n 4)", file=sys.stderr)
        return 2
    m = gw_mod.read_gateway_manifest(d) or {}
    plane = m.get("tenant_plane") or {}
    token = ((m.get("tenants") or {}).get(args.tenant) or {}).get(
        "token")
    client = TenantClient(plane.get("host") or "127.0.0.1",
                          int(plane.get("port") or 0), args.tenant,
                          token=token,
                          pool_token=m.get("pool_token"))
    try:
        # Inside the try: a failed HTTP bind (port in use) must not
        # leak the tenant connection + reader thread without a clean
        # detach — the gateway would see a LOST tenant instead of a
        # goodbye (lifecycle-lint shutdown discipline).
        srv = ThreadingHTTPServer((args.host, args.port),
                                  make_handler(client))
        try:
            print(f"NBD_SERVE_HTTP ready on {args.host}:"
                  f"{srv.server_port} -> pool {d} "
                  f"(tenant {args.tenant!r})", flush=True)
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            srv.server_close()
    finally:
        client.close(detach=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
