#!/usr/bin/env python
"""Closed-loop load generator for the serving plane (ISSUE 17).

Drives the serving fast path at a configured request rate and scores
SLO pass/fail from what the CLIENT observed, emitting the pinned
machine-readable report (:mod:`nbdistributed_tpu.serving_fast.loadgen`
— bench.py, CI, and the unit tests run the same core).  Two transports:

    # against the HTTP shim (tools/nbd_serve.py):
    python tools/nbd_loadgen.py --url http://localhost:8080 \\
        --rps 8 --duration 15 --slo-ttft-ms 2000 --slo-tpot-ms 500 \\
        --report /tmp/load.json

    # directly against a gateway pool (no shim):
    python tools/nbd_loadgen.py --run-dir /tmp/nbd_runs/pool-x

Arrival process, rate, duration, and seed default from the
``NBD_LOADGEN_*`` knobs; the schedule is a pure function of the seed,
so two runs with the same flags offer bit-identical work.  Exit code:
0 = SLO pass (or no targets set and nothing hung), 1 = SLO fail,
2 = could not run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nbdistributed_tpu.serving_fast.loadgen import (  # noqa: E402
    HTTPTransport, ClientTransport, LoadConfig, run_load,
    validate_report)
from nbdistributed_tpu.utils import knobs  # noqa: E402


def _span(s: str) -> tuple[int, int]:
    """``"lo:hi"`` or ``"n"`` -> inclusive (lo, hi)."""
    lo, _, hi = s.partition(":")
    return (int(lo), int(hi or lo))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="closed-loop load generator for the serving plane")
    p.add_argument("--url", default=None,
                   help="HTTP shim base URL (tools/nbd_serve.py)")
    p.add_argument("--run-dir", default=None,
                   help="attach directly to this gateway pool "
                        "(default: discovery) when --url is not given")
    p.add_argument("--tenant", default="loadgen",
                   help="tenant name for direct attachment")
    p.add_argument("--rps", type=float,
                   default=knobs.get_float("NBD_LOADGEN_RPS", 4.0))
    p.add_argument("--duration", type=float,
                   default=knobs.get_float("NBD_LOADGEN_DURATION_S",
                                           15.0))
    p.add_argument("--arrival",
                   choices=["poisson", "uniform"],
                   default=knobs.get_str("NBD_LOADGEN_ARRIVAL",
                                         "poisson"))
    p.add_argument("--seed", type=int,
                   default=knobs.get_int("NBD_LOADGEN_SEED", 0))
    p.add_argument("--prompt-len", type=_span, default=(4, 16),
                   metavar="LO:HI",
                   help="prompt length range in tokens")
    p.add_argument("--max-new", type=_span, default=(4, 16),
                   metavar="LO:HI",
                   help="output budget range in tokens")
    p.add_argument("--vocab", type=int, default=50,
                   help="token ids are drawn from [1, vocab)")
    p.add_argument("--priority", type=int, default=0)
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="p99 TTFT target (milliseconds)")
    p.add_argument("--slo-tpot-ms", type=float, default=None,
                   help="p99 TPOT target (milliseconds)")
    p.add_argument("--drain", type=float, default=60.0,
                   help="seconds to wait for in-flight requests after "
                        "the offered window (then they count as hung)")
    p.add_argument("--report", default=None,
                   help="write the JSON report here (default: stdout)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the human summary line")
    args = p.parse_args(argv)

    cfg = LoadConfig(
        rps=args.rps, duration_s=args.duration, arrival=args.arrival,
        seed=args.seed, prompt_len=args.prompt_len,
        max_new=args.max_new, vocab=args.vocab,
        priority=args.priority, slo_ttft_p99_ms=args.slo_ttft_ms,
        slo_tpot_p99_ms=args.slo_tpot_ms, drain_s=args.drain)

    client = None
    try:
        if args.url:
            transport = HTTPTransport(args.url)
        else:
            from nbdistributed_tpu.gateway import daemon as gw_mod
            from nbdistributed_tpu.gateway.client import TenantClient
            d = gw_mod.discover_gateway(args.run_dir)
            if d is None:
                print("no live gateway pool found (and no --url)",
                      file=sys.stderr)
                return 2
            m = gw_mod.read_gateway_manifest(d) or {}
            plane = m.get("tenant_plane") or {}
            token = ((m.get("tenants") or {}).get(args.tenant)
                     or {}).get("token")
            client = TenantClient(
                plane.get("host") or "127.0.0.1",
                int(plane.get("port") or 0), args.tenant,
                token=token, pool_token=m.get("pool_token"))
            transport = ClientTransport(client)
        report = run_load(transport, cfg)
    except Exception as e:
        print(f"loadgen failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    finally:
        if client is not None:
            try:
                client.close(detach=True)
            except Exception:
                pass

    validate_report(report)
    out = json.dumps(report, indent=2, sort_keys=True)
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            f.write(out + "\n")
    else:
        print(out)
    if not args.quiet:
        c = report["client"]
        ttft = (c["ttft_ms"] or {}).get("p99")
        tpot = (c["tpot_ms"] or {}).get("p99")
        print(f"NBD_LOADGEN offered={report['offered']} "
              f"completed={report['completed']} "
              f"shed_rate={report['shed_rate']} "
              f"tok/s={report['tokens_per_s']} "
              f"p99_ttft_ms={ttft} p99_tpot_ms={tpot} "
              f"slo_pass={report['slo']['pass']}",
              file=sys.stderr, flush=True)
    return 0 if report["slo"]["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
