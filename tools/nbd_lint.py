#!/usr/bin/env python
"""Checkout-local launcher for ``nbd-lint`` (the console script ships
via pyproject; CI and developers in a raw checkout run this file:
``python tools/nbd_lint.py --self``)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from nbdistributed_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
