"""Generate examples/01_parallelism.ipynb — a tour of the parallelism
library on a virtual 8-device mesh (single process; the cluster-driven
workflow is notebook 00)."""

import os

import nbformat as nbf

nb = nbf.v4.new_notebook()
nb.metadata["kernelspec"] = {
    "display_name": "Python 3", "language": "python", "name": "python3"}

C = []


def md(src):
    # Deterministic ids: regeneration diffs show only real changes.
    C.append(nbf.v4.new_markdown_cell(src, id=f"cell-{len(C)}"))


def code(src):
    C.append(nbf.v4.new_code_cell(src, id=f"cell-{len(C)}"))


md("""# Parallelism library tour — dp / tp / ZeRO-1 / sp / pp / ep

Every strategy in `nbdistributed_tpu.parallel`, exercised on an
**8-device virtual CPU mesh** in one process (the same code runs
unchanged on a TPU slice — only the mesh device list changes).
Notebook 00 covers the interactive multi-worker workflow; this one is
the library reference.""")

code("""\
import os
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P
print(f"{jax.device_count()} devices")""")

md("""## Data + tensor parallel training

A tiny Llama-style transformer trained over a `dp×tp` mesh: parameters
carry Megatron-style `PartitionSpec` rules, and XLA inserts the
gradient all-reduce (dp) and the per-block activation all-reduces (tp)
from the sharding lattice — nobody types a collective.""")

code("""\
from nbdistributed_tpu.models import tiny_config, init_params, loss_fn, param_shardings
from nbdistributed_tpu.parallel import mesh as mesh_mod, tensor_parallel

cfg = tiny_config(dtype=jnp.float32, use_flash=False)
mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
rules = param_shardings(cfg)
opt = optax.adamw(3e-4)

step = tensor_parallel.make_tp_train_step(
    lambda p, b: loss_fn(p, b, cfg), opt, mesh, rules, donate=False)
params = tensor_parallel.apply_shardings(
    init_params(jax.random.PRNGKey(0), cfg), mesh, rules)
opt_state = opt.init(params)
batch = mesh_mod.shard_batch(
    {"tokens": jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                  cfg.vocab_size)}, mesh)
for i in range(3):
    params, opt_state, loss = step(params, opt_state, batch)
    print(f"step {i}: loss {float(loss):.4f}")
print("wq sharding:", params["layers"]["wq"].sharding.spec)""")

md("""## ZeRO-1 — optimizer state sharded over dp

Same step definition, different optimizer-state shardings: the Adam
moments drop to `1/dp` per replica and XLA compiles the
reduce-scatter → sharded-update → all-gather schedule
(arXiv:2004.13336).""")

code("""\
from nbdistributed_tpu.parallel.zero import make_zero1_train_step

zstep, zinit = make_zero1_train_step(
    lambda p, b: loss_fn(p, b, cfg), opt, mesh, rules, params, donate=False)
zstate = zinit(params)
params, zstate, loss = zstep(params, zstate, batch)
mu = jax.tree_util.tree_leaves(zstate)[0]
print(f"loss {float(loss):.4f}; moment sharding: {mu.sharding.spec}")""")

md("""### FSDP / ZeRO-3 — weight sharding via GSPMD rules

`fsdp_param_shardings` shards every large weight over `dp` (2-D HSDP
with `tp_axis`); params, grads, and optimizer state shrink by the dp
size while XLA compiles the all-gather/reduce-scatter schedule torch
FSDP writes by hand. Same train step, same numerics.""")

code("""\
from jax.sharding import NamedSharding
from nbdistributed_tpu.models import fsdp_param_shardings, make_train_step

fsdp_mesh = mesh_mod.make_mesh({"dp": 4}, devices=jax.devices()[:4])
frules = fsdp_param_shardings(cfg)
fparams = jax.device_put(params, jax.tree_util.tree_map(
    lambda s: NamedSharding(fsdp_mesh, s), frules))
wq = fparams["layers"]["wq"]
print("wq bytes/device:", wq.addressable_shards[0].data.nbytes,
      "of", wq.nbytes, "(sharded 4-way)")
fstep = jax.jit(make_train_step(cfg, opt))
ftok = jax.device_put(
    jax.random.randint(jax.random.PRNGKey(13), (4, 32), 0, cfg.vocab_size),
    NamedSharding(fsdp_mesh, P("dp")))
_, _, floss = fstep(fparams, opt.init(fparams), {"tokens": ftok})
print(f"FSDP train step: loss {float(floss):.4f}")""")

md("""## Gradient accumulation

`accum_steps=N` scans microbatches inside the compiled step (fp32
accumulator, device-local split — no resharding): activation memory
÷ N at full-batch numerics.""")

code("""\
astep = tensor_parallel.make_tp_train_step(
    lambda p, b: loss_fn(p, b, cfg), opt, mesh, rules, donate=False,
    accum_steps=2)
params, opt_state, loss = astep(params, opt_state, batch)
print(f"accumulated step loss {float(loss):.4f}")""")

md("""## Sequence parallelism — ring and Ulysses

Long-context attention with the sequence axis sharded 8 ways. Ring
streams K/V chunks via `ppermute` with an online softmax; Ulysses
re-shards sequence↔heads with two all-to-alls and runs plain local
attention. Both are exact.""")

code("""\
from nbdistributed_tpu.ops import attention_reference
from nbdistributed_tpu.parallel.ring import ring_attention
from nbdistributed_tpu.parallel.ulysses import ulysses_attention

sp_mesh = mesh_mod.make_mesh({"sp": 8})
B, S, H, D = 1, 64 * 8, 8, 32
q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, S, H, D),
                             jnp.float32) for i in range(3))
ref = attention_reference(q, k, v, causal=True)
for name, fn in [("ring", ring_attention), ("ulysses", ulysses_attention)]:
    out = fn(q, k, v, sp_mesh, causal=True)
    err = float(jnp.max(jnp.abs(out - ref)))
    print(f"{name:8s} S={S} sharded 8-way: max |err| vs full attention = {err:.2e}")""")

md("""### Zigzag — the load-balanced causal ring

With plain chunking, causality means device 0 idles on every hop after
the first while device n-1 computes on all of them. The zigzag
schedule gives device d global chunks d **and** 2n-1-d, so every
device does equal real work per hop (the Pallas kernel skips the
masked blocks). Reorder once with `zigzag_shard`, train in that
layout, undo with `zigzag_unshard`.""")

code("""\
from nbdistributed_tpu.parallel.ring import zigzag_shard, zigzag_unshard
out_zz = ring_attention(zigzag_shard(q, 8), zigzag_shard(k, 8),
                        zigzag_shard(v, 8), sp_mesh, causal=True,
                        use_flash=True, schedule="zigzag")
err = float(jnp.max(jnp.abs(zigzag_unshard(out_zz, 8) - ref)))
print(f"zigzag   S={S} sharded 8-way: max |err| vs full attention = {err:.2e}")""")

md("""## Pipeline parallelism — GPipe over a `pp` axis

Stages live on different devices; microbatches stream through
`ppermute` hops. Exact vs running the stages sequentially.""")

code("""\
from nbdistributed_tpu.parallel import pipeline

pp_mesh = mesh_mod.make_mesh({"pp": 4}, devices=jax.devices()[:4])
Dm = 16
stages = {"w": jax.random.normal(jax.random.PRNGKey(3), (4, Dm, Dm)) * 0.3,
          "b": jnp.zeros((4, Dm))}
stage_fn = lambda pr, x: jnp.tanh(x @ pr["w"] + pr["b"])
x_in = jax.random.normal(jax.random.PRNGKey(4), (8, Dm))
out = pipeline.pipeline_forward(
    stage_fn, pipeline.shard_stage_params(stages, pp_mesh), x_in, pp_mesh,
    n_microbatches=4)
seq = x_in
for s in range(4):
    seq = stage_fn(jax.tree_util.tree_map(lambda a: a[s], stages), seq)
print("pipeline max |err| vs sequential:", float(jnp.max(jnp.abs(out - seq))))""")

md("""## Expert parallelism — MoE over an `ep` axis

Top-k routed experts, capacity-bounded dense dispatch (MXU-friendly
einsums), experts sharded across devices.""")

code("""\
from nbdistributed_tpu.models import (tiny_moe_config, init_moe_model,
                                      moe_loss_fn, moe_model_shardings)

ep_mesh = mesh_mod.make_mesh({"dp": 2, "ep": 4})
mcfg = tiny_moe_config(dtype=jnp.float32, use_flash=False)
mrules = moe_model_shardings(mcfg, tp_axis=None)
mp = tensor_parallel.apply_shardings(
    init_moe_model(jax.random.PRNGKey(5), mcfg), ep_mesh, mrules)
mtok = jax.random.randint(jax.random.PRNGKey(6), (4, 17), 0, mcfg.vocab_size)
mb = mesh_mod.shard_batch({"tokens": mtok}, ep_mesh)
mloss = float(moe_loss_fn(mp, mb, mcfg, mesh=ep_mesh))
print(f"MoE loss over dp×ep mesh: {mloss:.4f}")
print("expert weights sharding:",
      mp["layers"]["moe"]["w_up"].sharding.spec)""")

md("""### Dropless dispatch — no token ever dropped

`dispatch_mode="dropless"` runs the expert SwiGLU as
`jax.lax.ragged_dot` grouped matmuls over variable-size expert
segments.  Over the `ep` mesh it becomes the shard-capacity hybrid:
a static per-shard all-to-all feeds locally dropless segments —
per-expert slack pools across each shard's experts.""")

code("""\
import dataclasses
mcfg_ll = dataclasses.replace(mcfg, capacity_factor=float(mcfg.n_experts))
mcfg_dl = dataclasses.replace(mcfg, moe_dispatch="dropless",
                              capacity_factor=float(mcfg.n_experts))
l_dense = float(moe_loss_fn(mp, mb, mcfg_ll, mesh=ep_mesh))
l_dropless = float(moe_loss_fn(mp, mb, mcfg_dl, mesh=ep_mesh))
print(f"lossless dense {l_dense:.6f}  dropless-over-ep {l_dropless:.6f}"
      f"  equal: {abs(l_dense - l_dropless) < 1e-5}")""")

md("""### Model-integrated SP — train long context in one line

`make_train_step(cfg, opt, sp=SeqParallel(mesh))` routes every layer's
attention through the ring; everything else is position-wise, so GSPMD
keeps it sequence-sharded for free. dp/tp compose via the spec's
`dp_axis`/`tp_axis` (batch and heads stay local through the ring).""")

code("""\
from jax.sharding import NamedSharding
from nbdistributed_tpu.models import SeqParallel, make_train_step

sp_tr_mesh = mesh_mod.make_mesh({"dp": 2, "sp": 2, "tp": 2})
spec = SeqParallel(mesh=sp_tr_mesh, method="ring")
sp_step = jax.jit(make_train_step(cfg, opt, sp=spec))
p_sp = jax.device_put(params, jax.tree_util.tree_map(
    lambda s: NamedSharding(sp_tr_mesh, s), rules))
tok_sp = jax.device_put(
    jax.random.randint(jax.random.PRNGKey(8), (4, 32), 0, cfg.vocab_size),
    NamedSharding(sp_tr_mesh, P("dp", "sp")))
_, _, sp_loss = sp_step(p_sp, opt.init(p_sp), {"tokens": tok_sp})
print(f"ring-attention train step over dp×sp×tp: loss {float(sp_loss):.4f}")""")

md("""## Generation — KV-cache decode on a tp mesh

Static-shape prefill + one `lax.scan` decode loop; the cache shards
like the parameters (KV heads over tp, batch over dp). Sampling:
greedy, temperature, and static-shape `top_k` / `top_p` filters that
jit and scan.""")

code("""\
from nbdistributed_tpu.models import generate

prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 6), 0, cfg.vocab_size)
toks_greedy = generate(params, prompt, cfg, max_new_tokens=8, mesh=mesh)
print("greedy:   ", np.asarray(toks_greedy)[:, 6:])
toks = generate(params, prompt, cfg, max_new_tokens=8, temperature=0.8,
                top_k=50, top_p=0.95, key=jax.random.PRNGKey(9), mesh=mesh)
print("top-k/p:  ", np.asarray(toks)[:, 6:])""")

md("""### Sequence-parallel decode — the KV cache sharded over `sp`

Long-context serving: the cache (not the weights) outgrows one chip's
HBM first.  Each `sp` shard runs the decode kernel over its `T/n`
cache slice and shards merge by log-sum-exp — flash's inter-block
combine run across chips, one fused psum per layer per step.""")

code("""\
sp_mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2, "sp": 2})
cfg_f = dataclasses.replace(cfg, use_flash=True)
ps_sp = tensor_parallel.apply_shardings(params, sp_mesh, rules)
toks_sp = generate(ps_sp, prompt, cfg_f, max_new_tokens=8,
                   mesh=sp_mesh, max_len=32)
print("sp-sharded decode matches:",
      bool(np.array_equal(np.asarray(toks_sp),
                          np.asarray(toks_greedy))))""")

md("""## Int8 weight-only quantization

Per-output-channel scales commute with the matmul, so the dot reads
the raw int8 weights from HBM (half the bytes on the decode-dominant
streams) and rescales the activation once. Same forward/decode path;
tp shardings map onto the int8+scale pytree.""")

code("""\
from nbdistributed_tpu.models import (quantize_params, quantization_error,
                                      forward)

qparams = quantize_params(params)
errs = quantization_error(params, qparams)
print("per-weight relative quantization error:",
      {k: round(v, 4) for k, v in errs.items()})
ref = forward(params, prompt, cfg)
got = forward(qparams, prompt, cfg)
agree = float(jnp.mean(jnp.argmax(got, -1) == jnp.argmax(ref, -1)))
print(f"int8 vs bf16 top-1 agreement: {agree:.2%}")
print("int8 greedy:", np.asarray(generate(qparams, prompt, cfg, 8))[:, 6:])""")

md("""## Speculative decoding

A draft model proposes γ tokens; the target verifies them all in one
batched forward. Greedy mode reproduces the target's own decode;
`mean_acc` (accepted per round) sets the speedup.""")

code("""\
from nbdistributed_tpu.models import TransformerConfig, speculative_generate

draft_cfg = TransformerConfig(vocab_size=cfg.vocab_size, d_model=64,
                              n_layers=1, n_heads=2, n_kv_heads=2,
                              d_ff=128, dtype=jnp.float32, use_flash=False)
draft = init_params(jax.random.PRNGKey(12), draft_cfg)
sp_prompt = prompt[:1]
spec, mean_acc = speculative_generate(params, draft, sp_prompt, cfg,
                                      draft_cfg, 10, gamma=3)
ref = generate(params, sp_prompt, cfg, max_new_tokens=10)
print("speculative == target greedy:", bool((spec == ref).all()),
      f"(mean accepted/round {float(mean_acc):.2f})")
# Self-draft sanity: drafting with the target itself accepts everything.
_, acc_self = speculative_generate(params, params, sp_prompt, cfg, cfg,
                                   10, gamma=3)
print(f"self-draft mean accepted/round: {float(acc_self):.2f} (max 3)")
# Batched streams share every draft/verify forward; per-stream cache
# pointers keep diverging acceptance independent.
spec_b, _ = speculative_generate(params, draft, prompt, cfg, draft_cfg,
                                 10, gamma=3)
ref_b = generate(params, prompt, cfg, max_new_tokens=10)
print(f"batched speculative (B={prompt.shape[0]}) == batched greedy:",
      bool((spec_b == ref_b).all()))""")

md("""## 1F1B pipeline schedule — O(stages) activation memory

GPipe via autodiff stores every microbatch's residuals; the 1F1B
(PipeDream-flush) scan interleaves one forward and one backward
sub-step per tick, so the in-flight buffer is `2·stages − 1`
microbatch inputs regardless of the microbatch count — same loss,
same gradients.""")

code("""\
Dm = 16
fb_stages = {"w": jax.random.normal(jax.random.PRNGKey(20),
                                    (4, Dm, Dm)) * 0.3,
             "b": jnp.zeros((4, Dm))}
fb_stage_fn = lambda pr, h: jnp.tanh(h @ pr["w"] + pr["b"])
mse = lambda out, tgt: jnp.mean((out - tgt) ** 2)
xin = jax.random.normal(jax.random.PRNGKey(21), (16, Dm))
tgt = jax.random.normal(jax.random.PRNGKey(22), (16, Dm))
sh = pipeline.shard_stage_params(fb_stages, pp_mesh)
gp = pipeline.make_pipeline_loss(fb_stage_fn, mse, pp_mesh,
                                 n_microbatches=8)
l_ref, g_ref = jax.value_and_grad(gp)(sh, xin, tgt)
fb = pipeline.make_pipeline_1f1b(fb_stage_fn, mse, pp_mesh,
                                 n_microbatches=8)
l_fb, g_fb = fb(sh, xin, tgt)
gerr = max(float(jnp.max(jnp.abs(a - b))) for a, b in
           zip(jax.tree_util.tree_leaves(g_fb),
               jax.tree_util.tree_leaves(g_ref)))
print(f"1F1B vs GPipe grads match: "
      f"{abs(float(l_fb) - float(l_ref)) < 1e-5 and gerr < 1e-4} "
      f"(buffer {2 * 4 - 1} deep, not 8)")""")

md("""## Sparse MoE dispatch + windowed-ring hop plan

Two routing upgrades: `dispatch_mode="sparse"` replaces the quadratic
one-hot dispatch einsums with a sort/segment gather (linear in
tokens, bit-identical drops), and sliding-window ring attention prunes
whole out-of-band hops from the ring — `hop_plan` computes the
contributing steps statically.""")

code("""\
from nbdistributed_tpu.parallel import expert
from nbdistributed_tpu.parallel.ring import hop_plan

mx = jax.random.normal(jax.random.PRNGKey(23), (64, 16), jnp.float32)
mpar = expert.init_moe_params(jax.random.PRNGKey(24), 16, 32, 4,
                              dtype=jnp.float32)
yd, _ = expert.moe_ffn(mx, mpar)
ysp, _ = expert.moe_ffn(mx, mpar, dispatch_mode="sparse")
print(f"sparse MoE dispatch == dense: "
      f"{float(jnp.max(jnp.abs(ysp - yd))) < 1e-5}")
plan = hop_plan(8, 2048, 4096)   # sp=8, 2048-token chunks, 4K window
print(f"SWA ring hop plan (sp=8, S=16K, window=4K): {plan} — "
      f"{len(plan)}/8 hops pay compute+ppermute")""")

md("""## LoRA fine-tuning

Adapters mirror the targeted weights; a differentiable merge reuses
the whole stack (flash kernel, remat, every sharding rule), and the
optimizer state exists only for adapter leaves (~0.6% of full-model at
7B, rank 16).""")

code("""\
from nbdistributed_tpu.models import (ALL_TARGETS, lora_init, lora_merge,
                                      loss_fn, make_lora_train_step)

lora = lora_init(jax.random.PRNGKey(10), cfg, rank=4, targets=ALL_TARGETS)
lopt = optax.adamw(1e-2)
lstep = jax.jit(make_lora_train_step(cfg, lopt))
lstate = lopt.init(lora)
lbatch = {"tokens": jax.random.randint(jax.random.PRNGKey(11), (2, 16),
                                       0, cfg.vocab_size)}
l0 = float(loss_fn(lora_merge(params, lora), lbatch, cfg))
for _ in range(10):
    lora, lstate, _ = lstep(params, lora, lstate, lbatch)
l1 = float(loss_fn(lora_merge(params, lora), lbatch, cfg))
n_ad = sum(x.size for x in jax.tree_util.tree_leaves(lora))
n_all = sum(x.size for x in jax.tree_util.tree_leaves(params))
print(f"LoRA: {n_ad:,} adapter params ({n_ad / n_all:.1%} of model), "
      f"loss {l0:.3f} -> {l1:.3f}")""")

md("""## Packed-document training (segment ids)

`pack_tokens(return_segments=True)` emits per-window document ids;
`batch["segments"]` engages the whole contract — attention masked
across documents inside the flash kernel (both passes), RoPE restart
per document, boundary targets dropped.  Ground truth: packed logits
equal each document forwarded alone.""")

code("""\
from nbdistributed_tpu.models import forward, loss_fn, packed_positions
from nbdistributed_tpu.utils.data import pack_tokens

docs = [[(i * 11 + j) % cfg.vocab_size for j in range(n)]
        for i, n in enumerate([15, 9, 20])]
win, seg = pack_tokens(docs, 23, eos_id=0, return_segments=True)
win, seg = jnp.asarray(win), jnp.asarray(seg)
packed_loss = float(loss_fn(params, {"tokens": win, "segments": seg},
                            cfg))
lp = forward(params, win[:1], cfg, packed_positions(seg[:1]),
             segment_ids=seg[:1])
d0 = jnp.asarray([docs[0] + [0]], jnp.int32)
err = float(jnp.max(jnp.abs(lp[:, :16] - forward(params, d0, cfg))))
print(f"packed loss {packed_loss:.4f}; doc0 logits vs solo forward: "
      f"max |err| = {err:.2e}")""")

md("""## Continuous-batching serving

`DecodeServer` admits requests of any length into a fixed slot pool
whenever a slot frees; every decode step is ONE shared B-row forward
with per-slot cache pointers, so staggered requests share every
matmul.  Greedy serving is bit-identical per request to a standalone
`generate` call — occupancy is invisible to the numerics.""")

code("""\
from nbdistributed_tpu.models import DecodeServer, generate

srv = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=8)
ra = srv.submit([5, 9, 2], 6)
srv.step()                            # ra decodes alone...
rb = srv.submit([7, 1, 3, 11], 5)     # ...rb joins mid-flight
srv.run_until_done(max_steps=60)

import numpy as np
def solo(pr, n):
    out = generate(params, jnp.asarray(pr, jnp.int32)[None], cfg, n)
    return [int(t) for t in np.asarray(out)[0][len(pr):]]
print(f"staggered == solo: "
      f"{srv.outputs[ra] == solo([5, 9, 2], 6)} "
      f"{srv.outputs[rb] == solo([7, 1, 3, 11], 5)}")""")

md("""## Ring-overlapped collective matmul

The Megatron sequence-parallel block's `all_gather -> matmul` and
`matmul -> reduce_scatter`, decomposed into `ppermute` rings
interleaved with per-chunk GEMMs: the ICI transfer hides behind the
MXU by dataflow.  Exact vs the replicated MLP.""")

code("""\
from jax.sharding import PartitionSpec as OP
from nbdistributed_tpu.parallel.overlap import megatron_sp_block

tp_mesh = mesh_mod.make_mesh({"tp": 4}, devices=jax.devices()[:4])
S_, D_, F_ = 16, 8, 32
ox = jax.random.normal(jax.random.PRNGKey(30), (S_, D_))
owu = jax.random.normal(jax.random.PRNGKey(31), (D_, F_)) * 0.2
owd = jax.random.normal(jax.random.PRNGKey(32), (F_, D_)) * 0.2
ov = jax.jit(jax.shard_map(
    lambda a, b, c: megatron_sp_block(a, b, c, "tp"),
    mesh=tp_mesh,
    in_specs=(OP("tp", None), OP(None, "tp"), OP("tp", None)),
    out_specs=OP("tp", None)))(ox, owu, owd)
ref = jax.nn.gelu(ox @ owu) @ owd
print(f"ring-overlap Megatron-SP block exact: "
      f"{float(jnp.max(jnp.abs(ov - ref))) < 1e-4}")""")

nb.cells = C
out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "01_parallelism.ipynb")
nbf.write(nb, out)
print("wrote", out, "-", len(C), "cells")
