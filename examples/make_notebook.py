"""Generate examples/00_quickstart.ipynb — the acceptance-scenario demo
notebook (mirrors the role of the reference's 00_accelerate.ipynb)."""

import os

import nbformat as nbf

nb = nbf.v4.new_notebook()
nb.metadata["kernelspec"] = {
    "display_name": "Python 3", "language": "python", "name": "python3"}

C = []


def md(src):
    # Deterministic ids: regeneration diffs show only real changes.
    C.append(nbf.v4.new_markdown_cell(src, id=f"cell-{len(C)}"))


def code(src):
    C.append(nbf.v4.new_code_cell(src, id=f"cell-{len(C)}"))


md("""# Interactive distributed JAX on TPU — quick start

This notebook is the end-to-end acceptance scenario for
`nbdistributed_tpu` (the role `00_accelerate.ipynb` plays for the
reference): bring up a worker cluster from the notebook, run plain cells
on every rank with streamed per-rank output, target single ranks with
`%%rank`, and train a small transformer data-parallel — all cell by
cell, with full REPL semantics.

On a TPU host the workers each own a chip (`--backend tpu`, the
default when chips are present); everywhere else `--backend cpu` gives a
real multi-process world with cross-process gloo collectives.""")

code("%load_ext nbdistributed_tpu")

code("""\
import os
# The demo runs anywhere: pick the backend from the environment so CI
# can force cpu. On a TPU host "auto" selects the chips.
backend = os.environ.get("NBD_NOTEBOOK_BACKEND", "auto")
nw = int(os.environ.get("NBD_NOTEBOOK_WORKERS", "2"))""")

code("%dist_init -n {nw} --backend {backend} -t 300")

md("""## Every cell now runs on all workers

After `%dist_init`, plain cells are transparently dispatched to every
worker (disable with `%dist_mode -d`). Each worker has a persistent
namespace pre-seeded with `rank`, `world_size`, `jax`, `jnp`, eager
collectives (`all_reduce`, `all_gather`, `broadcast`, ...), and the
sharding toolkit (`Mesh`, `P`, `shard_map`).""")

code("""\
x = jnp.ones((100, 100)) * (rank + 1)
print(f"rank {rank}: x.sum() = {x.sum()}")
x.mean()""")

md("""### Collectives, interactively

`all_reduce` sums across the whole world — each rank contributes its
own `x`, every rank gets the same total back.""")

code("""\
total = all_reduce(x)
float(total[0, 0])  # sum over ranks of (rank+1) — identical everywhere""")

md("""## `%%rank` — target a subset

Create parameters on rank 0 only, then broadcast them to the world
(the reference README's tensor-parallel warm-up pattern).""")

code("""\
%%rank [0]
key = jax.random.PRNGKey(0)
W = jax.random.normal(key, (256, 256)) * 0.02
print("created on rank 0 only:", W.shape)""")

code("""\
if rank != 0:
    W = jnp.zeros((256, 256))
W = broadcast(W, root=0)
float(W.sum())  # identical on every rank after broadcast""")

md("""## Data-parallel training, cell by cell

A tiny Llama-style transformer from the built-in model family, trained
DDP: each rank computes grads on its own shard of the batch and
all-reduces them — the same loop structure as the reference's
Accelerate demo, but in JAX.""")

code("""\
import optax
from nbdistributed_tpu.models import tiny_config, init_params, loss_fn

cfg = tiny_config()
params = init_params(jax.random.PRNGKey(0), cfg)  # same init everywhere
opt = optax.adamw(3e-4)
opt_state = opt.init(params)

# The torch.distributed-style DDP loop: jit the local compute, keep the
# cross-process all_reduce eager between the two jitted halves (eager
# collectives cannot be traced — they move host-local values into a
# global XLA program).
@jax.jit
def local_grads(params, batch):
    return jax.value_and_grad(loss_fn)(params, batch, cfg)

@jax.jit
def apply_grads(params, opt_state, grads):
    updates, opt_state = opt.update(grads, opt_state, params)
    # Params are bfloat16 (MXU-friendly); accumulate the update in
    # float32 so tiny steps aren't rounded away.
    params = jax.tree.map(
        lambda p, u: (p.astype(jnp.float32) + u).astype(p.dtype),
        params, updates)
    return params, opt_state

def ddp_step(params, opt_state, batch):
    loss, grads = local_grads(params, batch)
    if world_size > 1:
        grads = jax.tree.map(lambda g: all_reduce(g, "mean"), grads)
        loss = all_reduce(loss, "mean")
    params, opt_state = apply_grads(params, opt_state, grads)
    return params, opt_state, loss
print("world size:", world_size)""")

code("""\
# Deterministic per-rank data sharding (the seeded batch_iterator):
# every rank builds the SAME shuffled permutation and takes its own
# rows of each global batch — the Accelerate-dataloader role, without
# a dataloader.
full_data = {"tokens": np.random.default_rng(0).integers(
    0, cfg.vocab_size, size=(64, 65)).astype("int32")}
batches = batch_iterator(full_data, batch_size=8, rank=rank,
                         world_size=world_size, seed=0, epochs=None)""")

code("""\
for step in range(5):
    batch = {k: jnp.asarray(v) for k, v in next(batches).items()}
    params, opt_state, loss = ddp_step(params, opt_state, batch)
    if rank == 0:
        print(f"step {step}: loss {float(loss):.4f}")""")

md("""### Eval

Every rank evaluates the *same* held-out batch; after DDP the params are
identical on all ranks, so the losses must agree exactly.""")

code("""\
eval_batch = {"tokens": jax.random.randint(jax.random.PRNGKey(999),
                                           (8, 64), 0, cfg.vocab_size)}
eval_loss = float(loss_fn(params, eval_batch, cfg))
print(f"rank {rank}: eval loss {eval_loss:.4f}")""")

md("""## Checkpoint / restore

`%dist_checkpoint` snapshots named namespace pytrees from every rank
(atomic per-rank dirs, bfloat16-exact); `%dist_restore` loads them
back — the save/resume loop for long interactive sessions.""")

code("""\
# Fresh checkpoint dir: a stale one from an earlier run must never be
# silently restored below.
import shutil
shutil.rmtree("/tmp/nbd_demo_ckpt", ignore_errors=True)""")

code("%dist_checkpoint /tmp/nbd_demo_ckpt params opt_state")

code("""\
# Clobber the params, then restore them.
params = None""")

code("%dist_restore /tmp/nbd_demo_ckpt")

code("""\
# Restored params must give the exact same eval loss — a silent save
# failure above would surface here as an assertion error.
restored_loss = float(loss_fn(params, eval_batch, cfg))
assert restored_loss == eval_loss, (restored_loss, eval_loss)
print(f"rank {rank}: eval after restore {restored_loss:.4f} (exact)")""")

md("""### Background (async) checkpointing

`--background` returns immediately: each array is defensively copied
on-device (safe next to donating train steps) and the device→host
drain + disk IO run on a worker thread, so the next training cell
starts at once. `%dist_checkpoint --status` polls per rank.""")

code("%dist_checkpoint /tmp/nbd_demo_ckpt_bg params opt_state --background")

code("""\
# Training continues immediately while the save drains...
batch = {k: jnp.asarray(v) for k, v in next(batches).items()}
params, opt_state, loss = ddp_step(params, opt_state, batch)
print(f"rank {rank}: trained a step during the save "
      f"(loss {float(loss):.4f})")""")

code("""\
import time
time.sleep(1.0)  # let the background write land for the poll below""")

code("%dist_checkpoint --status")

md("""## Generation

The model family includes a static-shape KV-cache decode loop (one
`lax.scan`, greedy or sampled) — here greedy continuations of a toy
prompt on every rank.""")

code("""\
from nbdistributed_tpu.models import generate
prompt = jnp.ones((1, 4), jnp.int32) * (rank + 1)
out_tokens = generate(params, prompt, cfg, max_new_tokens=8)
print(f"rank {rank}: {out_tokens[0].tolist()}")""")

md("""## Continuous-batching serving with prefix caching

`DecodeServer` (seeded in every worker namespace) serves staggered
requests from one slot-pool KV cache — every decode step is one shared
batched forward no matter how requests arrive, and greedy outputs are
bit-identical per request to standalone `generate`.  A shared system
prompt registered with `cache_prefix` is prefilled ONCE; matching
requests then admit by one HBM-to-HBM copy plus a suffix-only prefill
(causal attention + absolute RoPE make the copied KV rows exact).""")

code("""\
%%rank [0]
srv = DecodeServer(params, cfg, max_batch=2, max_len=64, pad_to=4)
system_prompt = [7, 3, 9, 1]
srv.cache_prefix(system_prompt)          # prefilled once
ra = srv.submit(system_prompt + [5], 6)  # admits via HBM copy + suffix
rb = srv.submit(system_prompt + [8, 2], 6)
srv.run_until_done()
print("request A:", srv.outputs[ra])
print("request B:", srv.outputs[rb])
solo = generate(params, jnp.asarray([system_prompt + [5]], jnp.int32),
                cfg, max_new_tokens=6)[0][5:].tolist()
assert srv.outputs[ra] == solo, "serving must match solo generate"
print("bit-identical to solo generate:", solo)""")

md("""## Quantized decode: int8 and nibble-packed int4

Decode streams every weight per token, so bytes are throughput:
`quantize_params` stores the matmul weights int8 (half the bf16
stream), `quantize_params4` nibble-packs them into uint8 at exactly
0.5 bytes/weight with per-64-input-group scales.  Both trees serve
through the same `generate`/`DecodeServer` paths via `qlinear`
dispatch.""")

code("""\
%%rank [0]
from nbdistributed_tpu.models import quantize_params, quantize_params4

def tree_mb(t):
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(t)) / 1e6

q8, q4 = quantize_params(params), quantize_params4(params)
toks8 = generate(q8, prompt, cfg, max_new_tokens=8)[0].tolist()
toks4 = generate(q4, prompt, cfg, max_new_tokens=8)[0].tolist()
print(f"fp {tree_mb(params):.1f} MB -> int8 {tree_mb(q8):.1f} MB "
      f"-> int4 {tree_mb(q4):.1f} MB")
print("int8 decode:", toks8)
print("int4 decode:", toks4)""")

md("""## Pull model state into the kernel — no pickle

`%dist_pull` / `%dist_push` carry whole params/optimizer pytrees as a
JSON tree description plus raw array buffers — model state crosses the
control plane without pickle, so hardened (`allow_pickle=False`)
deployments lose nothing.""")

code("%dist_pull params --rank 0 --as kernel_params")

md("""## Bring your HuggingFace checkpoint

Any Llama-architecture `transformers` model converts into this
framework's pytree — after which the whole TPU path applies (sharding
rules, flash kernels, the generate loop above). Here a tiny randomly
initialized HF Llama proves the round trip inside the notebook: the
converted model's greedy continuation must match HF's own
`generate`.""")

code("""\
import torch
from transformers import LlamaConfig, LlamaForCausalLM
from nbdistributed_tpu.models import params_from_hf, generate

torch.manual_seed(0)
hf_model = LlamaForCausalLM(LlamaConfig(
    vocab_size=128, hidden_size=64, intermediate_size=160,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=256)).eval()
hf_prompt = torch.tensor([[5, 9, 2, 44]])
with torch.no_grad():
    hf_tokens = hf_model.generate(hf_prompt, max_new_tokens=6,
                                  do_sample=False)[0].tolist()

jx_params, jx_cfg = params_from_hf(hf_model, dtype=jnp.float32)
jx_cfg = type(jx_cfg)(**{**jx_cfg.__dict__, "use_flash": False})
jx_tokens = generate(jx_params, jnp.asarray([[5, 9, 2, 44]], jnp.int32),
                     jx_cfg, max_new_tokens=6)[0].tolist()
assert jx_tokens == hf_tokens, (jx_tokens, hf_tokens)
print(f"rank {rank}: HF and converted tokens match: {jx_tokens}")""")

md("## Cluster status, timeline, shutdown")

code("%dist_status")

code("%timeline_show")

code("%dist_shutdown")

nb.cells = C
out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "00_quickstart.ipynb")
nbf.write(nb, out)
print("wrote", out, "-", len(C), "cells")
