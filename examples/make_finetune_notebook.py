"""Generate examples/02_finetune.ipynb — the reference's flagship user
journey (00_accelerate.ipynb cells 10/18/28/36-40): load a pretrained
checkpoint, tokenize a dataset, and fine-tune it interactively,
cell-by-cell, data-parallel across workers.

This build environment has zero network egress (no HF hub, no
datasets downloads — recorded in BASELINE.md), so the checkpoint is
constructed LOCALLY at the real SmolLM2-135M architecture and saved
with ``save_pretrained``; the load -> convert -> fine-tune path the
notebook exercises is byte-identical to pulling the same files from
the hub.  The corpus is real English text sourced locally (this
repository's own documentation), byte-tokenized."""

import nbformat as nbf

nb = nbf.v4.new_notebook()
nb.metadata["kernelspec"] = {
    "display_name": "Python 3", "language": "python", "name": "python3"}

C = []


def md(src):
    C.append(nbf.v4.new_markdown_cell(src, id=f"cell-{len(C)}"))


def code(src):
    C.append(nbf.v4.new_code_cell(src, id=f"cell-{len(C)}"))


md("""# Fine-tune a checkpoint, interactively — the accelerate journey

The reference framework's flagship demo (`00_accelerate.ipynb`) loads a
pretrained SmolLM2-135M, tokenizes a dataset, and fine-tunes it with
DDP — every step an ordinary notebook cell running on all workers.
This notebook is that journey on the TPU-native stack: HF checkpoint →
JAX pytree (`load_hf_pretrained`), local text corpus → packed token
batches (`pack_tokens` / `shard_arrays`), cell-by-cell data-parallel
fine-tuning with eager gradient `all_reduce`, and generation from the
tuned weights.

> **Checkpoint provenance**: this environment has no network egress, so
> the checkpoint is built locally at the exact SmolLM2-135M
> architecture (`LlamaForCausalLM`, 576 hidden / 30 layers / 9 heads /
> 3 KV heads, tied embeddings) and saved with `save_pretrained` — the
> directory the loader consumes is indistinguishable from a hub
> download of the same files.  See BASELINE.md for the limitation
> note.""")

code("%load_ext nbdistributed_tpu")

code("""\
import os
backend = os.environ.get("NBD_NOTEBOOK_BACKEND", "auto")
nw = int(os.environ.get("NBD_NOTEBOOK_WORKERS", "2"))
# Overridable so tests use a per-run temp dir (no /tmp litter/races).
ckpt_dir = os.environ.get("NBD_NOTEBOOK_CKPT_DIR",
                          "/tmp/nbd_smol135m_local")
ck_out = os.environ.get("NBD_NOTEBOOK_CK_OUT", "/tmp/nbd_finetune_ck")
""")

md("""## Build the local checkpoint (stands in for the hub download)

A hub pull would be `AutoModelForCausalLM.from_pretrained(
"HuggingFaceTB/SmolLM2-135M")`; offline, we construct the identical
architecture with `transformers` and `save_pretrained` it.  This runs
*before* `%dist_init`, locally in the kernel — exactly where a user
would run their download cell.""")

code("""\
import torch
from transformers import LlamaConfig, LlamaForCausalLM

torch.manual_seed(0)
hf_cfg = LlamaConfig(
    vocab_size=49152, hidden_size=576, intermediate_size=1536,
    num_hidden_layers=30, num_attention_heads=9, num_key_value_heads=3,
    max_position_embeddings=2048, rope_theta=100000.0,
    tie_word_embeddings=True)
model = LlamaForCausalLM(hf_cfg)
n_params = sum(p.numel() for p in model.parameters())
model.save_pretrained(ckpt_dir, safe_serialization=True)
del model
print(f"saved {n_params/1e6:.1f}M-param SmolLM2-135M-architecture "
      f"checkpoint to {ckpt_dir}")""")

code("%dist_init -n {nw} --backend {backend} -t 600")

md("""## Load the checkpoint on every worker

`load_hf_pretrained` converts the torch checkpoint to a JAX pytree +
`TransformerConfig` (tied embeddings become `lm_head = embed.T`); each
rank holds a full replica — data parallelism, like the reference's
Accelerate DDP.""")

code("""\
# (cells now run on the workers: define worker-side paths/imports here
# — the workers inherit the coordinator's environment)
import os
ckpt_dir = os.environ.get("NBD_NOTEBOOK_CKPT_DIR",
                          "/tmp/nbd_smol135m_local")
params, cfg = load_hf_pretrained(ckpt_dir, dtype=jnp.float32)
n = sum(x.size for x in jax.tree_util.tree_leaves(params))
print(f"rank {rank}: loaded {n/1e6:.1f}M params, "
      f"d_model={cfg.d_model}, layers={cfg.n_layers}")""")

md("""## The dataset: real local text, packed into training batches

The reference tokenizes MRPC from the hub; offline, the corpus is this
repository's own documentation (real English prose), byte-tokenized
(ids 0-255 ⊂ the model's vocabulary) and packed into fixed-length
rows.  `batch_iterator` is the shipped per-rank dataloader: every rank
builds it with the same seed and takes its own stride through an
identical permutation — the sharding Accelerate's dataloader wrapper
does.""")

code("""\
import numpy as _np
# Corpus files live at the repo root; resolve from the installed
# package so the notebook works from any working directory.
import nbdistributed_tpu as _pkg
repo = os.path.dirname(os.path.dirname(os.path.abspath(_pkg.__file__)))
corpus = ""
for f in ("README.md", "PARITY.md", "SURVEY.md"):
    p = os.path.join(repo, f)
    if os.path.exists(p):
        corpus += open(p, encoding="utf-8").read() + "\\n\\n"
ids = _np.frombuffer(corpus.encode("utf-8"), dtype=_np.uint8)
S = 128
n_rows = len(ids) // S
assert n_rows > 0, f"empty corpus — no docs found under {repo}"
data = _np.asarray(ids[:n_rows * S], dtype=_np.int32).reshape(n_rows, S)
print(f"rank {rank}: {len(ids)} bytes of local text -> "
      f"{n_rows} rows of {S}")""")

md("""## Cell-by-cell DDP fine-tuning

The local gradient step is jitted; gradients cross ranks through the
eager `all_reduce` (mean) between the two jitted halves — the
`torch.distributed` DDP pattern, XLA-native.  Every `print` streams
back rank-tagged while the loop runs.""")

code("""\
import optax
opt = optax.adamw(3e-4)
state = opt.init(params)
B = 2  # per-rank batch

from nbdistributed_tpu.models import loss_fn

@jax.jit
def local_grads(p, batch):
    return jax.value_and_grad(lambda p: loss_fn(p, batch, cfg))(p)

@jax.jit
def apply_grads(p, s, g):
    u, s = opt.update(g, s, p)
    return optax.apply_updates(p, u), s

def ddp_step(p, s, batch):
    l, g = local_grads(p, batch)
    if world_size > 1:
        g = jax.tree.map(lambda t: all_reduce(t, "mean"), g)
    return *apply_grads(p, s, g), l

print(f"rank {rank}: fine-tune step ready (B={B}/rank, "
      f"global batch {B * world_size})")""")

code("""\
import time
it = batch_iterator({"tokens": data}, batch_size=B, rank=rank,
                    world_size=world_size, seed=0, epochs=None)
losses = []
for step in range(4):
    batch = {k: jnp.asarray(v) for k, v in next(it).items()}
    t0 = time.time()
    params, state, l = ddp_step(params, state, batch)
    losses.append(float(l))
    print(f"step {step}: loss {float(l):.4f} "
          f"({time.time() - t0:.1f}s)")
print(f"rank {rank}: loss {losses[0]:.4f} -> {losses[-1]:.4f} "
      f"({'improved' if losses[-1] < losses[0] else 'NOT improved'})")""")

md("""## Memory-lean loss: chunked-vocab cross-entropy

`ce_chunk=N` makes the loss stream the lm_head in N-column blocks
(`ops/xent.py`): the `(B, S, V)` logits — the buffer that caps the
train batch at LM scale — never materialize, in forward or backward.
Same numbers, a fraction of the memory:""")

code("""\
import dataclasses
cfg_lean = dataclasses.replace(cfg, ce_chunk=8192)
check = {"tokens": jnp.asarray(data[:2])}
l_full = float(loss_fn(params, check, cfg))
l_lean = float(loss_fn(params, check, cfg_lean))
print(f"rank {rank}: full-logits loss {l_full:.6f}, "
      f"chunked {l_lean:.6f} (match: {abs(l_full - l_lean) < 1e-4})")""")

md("""## Generate from the fine-tuned weights (rank 0)

`%%rank [0]` targets one worker, like the reference's rank-0
inspection cells.  The prompt is a byte-tokenized string; the greedy
continuation decodes back to text.""")

code("""\
%%rank [0]
from nbdistributed_tpu.models import generate
prompt_text = "The reference "
prompt = jnp.asarray(
    _np.frombuffer(prompt_text.encode(), dtype=_np.uint8)
    .astype(_np.int32))[None]
toks = generate(params, prompt, cfg, max_new_tokens=16)
cont = bytes(int(t) for t in toks[0, prompt.shape[1]:]
             if 0 <= int(t) < 256).decode("utf-8", "replace")
print(f"prompt {prompt_text!r} -> continuation {cont!r}")""")

md("""## Checkpoint the fine-tuned state and shut down

`%dist_checkpoint` saves named namespace entries per rank (atomic,
exact round-trip) — the resume story the reference leaves to
`torch.save` in user cells.""")

code("%dist_checkpoint {ck_out} params")

code("%dist_shutdown")

nb.cells = C

if __name__ == "__main__":
    import os

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "02_finetune.ipynb")
    nbf.write(nb, out)
    print(f"wrote {out} ({len(C)} cells)")
