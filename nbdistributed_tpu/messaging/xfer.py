"""Streaming bulk-transfer plane: chunked, flow-controlled, resumable
data movement (ISSUE 20, ROADMAP item 2b).

``%dist_push``/``%dist_pull`` and checkpoint movement used to
serialize a whole multi-GB pytree through ONE blocking codec frame
(``arr.tobytes()`` per leaf, a single ``sendall``, a fixed
``timeout=60``): a retry redelivered the entire payload, a slow
client wedged the sender, and the whole value sat in memory three
times at once (source, serialized frame, decode copy).  This module
replaces that with a streaming protocol layered on the existing
``submit()``/``wait()`` control plane — nothing new at the socket
layer, so every retry / redelivery / replay-cache / epoch-fencing /
fault-injection behavior the control plane already has applies to
every chunk for free.

Shape of a push (pull is the mirror image, receiver-driven):

- the value is flattened once (:func:`flatten_pytree_wire`) and
  viewed as ONE contiguous logical byte stream across its leaf
  buffers; nothing is ever concatenated — chunk reads are gathered
  zero-copy-ish from the source arrays, chunk writes are scattered
  into preallocated destination arrays;
- ``xfer_begin`` ships the tree meta + leaf layout; the receiver
  preallocates the destination and answers with the bitmap of chunks
  it ALREADY has (resume — see below);
- chunks go out as pipelined ``xfer_chunk`` sub-messages under a
  **credit window** (``NBD_XFER_WINDOW`` in-flight chunks max): peak
  extra memory on either side is bounded by window x chunk, never by
  payload size;
- every chunk carries a crc32 of its raw bytes in the ``xf`` wire
  header; a corrupted chunk is refused by the receiver and re-sent
  (counter ``nbd_xfer_chunks_resent_total``), a dropped frame is
  redelivered by the retry layer under the same msg_id and deduped by
  the worker's replay cache — only missing chunks ever cross again;
- ``xfer_commit`` assembles + binds exactly once: the commit reply is
  replay-cached (redelivery-safe) AND the xid is memoized in a
  completed-set (a resumed push from a NEW coordinator after SIGKILL
  learns "already applied" at ``xfer_begin`` and sends nothing).

Resumability: the transfer id is **content-addressed** — a sha1 over
(kind, name, total bytes, chunk size, per-chunk crcs).  A coordinator
killed mid-push and reattached (``%dist_attach``) recomputes the same
xid from the same source value, and ``xfer_begin`` returns each
worker's chunk bitmap, so the re-push sends only what's missing.  A
best-effort manifest (xid, bitmap progress) is journaled under the
run dir for ``%dist_doctor``-style inspection; correctness never
depends on it.

Compression (EQuARX's control-plane sibling): optional per-chunk
codec — zlib always available, lz4/zstd auto-detected — with a
per-chunk "stored" escape when compression doesn't pay.  Off by
default (``NBD_XFER_CODEC=none``): weight-like float payloads rarely
compress and the data plane must never burn minutes of CPU by
surprise.  The chosen codec is flight-recorded per transfer.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import uuid
import zlib
from collections import OrderedDict, deque
from typing import Any, Callable

import numpy as np

from ..utils import knobs
from .codec import (Message, _np_dtype, flatten_pytree_wire,
                    unflatten_pytree_wire)

DEFAULT_CHUNK_BYTES = 4 << 20
DEFAULT_WINDOW = 8
DEFAULT_THRESHOLD_BYTES = 8 << 20
DEFAULT_MIN_BYTES_PER_S = 1 << 20
DEFAULT_INBOUND_MAX = 4

#: message types of the transfer plane (registered in the retry
#: layer's bulk class and the worker's handler table).
XFER_TYPES = ("xfer_begin", "xfer_chunk", "xfer_commit",
              "xfer_pull_begin", "xfer_read", "xfer_pull_end")


class XferError(Exception):
    """A transfer failed in a way retry cannot heal (bad state on the
    receiver, chunk refused repeatedly, incomplete commit)."""


class XferFallback(Exception):
    """The value cannot ride the buffer path (non-pytree leaf, no
    array leaves) — callers fall back to the legacy single-frame
    path, exactly like ``flatten_pytree_wire``'s TypeError contract."""


# ----------------------------------------------------------------------
# knobs


def chunk_bytes() -> int:
    return max(1 << 16, knobs.get_int("NBD_XFER_CHUNK_BYTES",
                                      DEFAULT_CHUNK_BYTES))


def window_size() -> int:
    return max(1, knobs.get_int("NBD_XFER_WINDOW", DEFAULT_WINDOW))


def threshold_bytes() -> int:
    """Payloads at or above this ride the chunked plane; smaller ones
    keep the legacy single-frame path (one round-trip beats protocol
    overhead at small sizes)."""
    return knobs.get_int("NBD_XFER_THRESHOLD_BYTES",
                         DEFAULT_THRESHOLD_BYTES)


def approx_nbytes(value: Any) -> int:
    """Cheap payload-size estimate WITHOUT flattening: drives the
    chunked-vs-legacy routing decision and the payload-scaled
    deadlines.  Unsized leaves (ints, strings, custom objects) count
    as 0 — they either inline trivially or fall back anyway."""
    n = getattr(value, "nbytes", None)
    if n is not None:
        try:
            return int(n)
        except (TypeError, ValueError):
            return 0
    if isinstance(value, dict):
        return sum(approx_nbytes(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(approx_nbytes(v) for v in value)
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    return 0


def scaled_timeout(nbytes: int, *, floor: float | None = None) -> float:
    """Per-transfer deadline that scales with payload size: a GB-scale
    move gets the seconds it needs at the ``NBD_XFER_MIN_BYTES_PER_S``
    floor rate, while a genuine stall still fails loudly (the floor
    rate is deliberately pessimistic — 1 MB/s — so the scaled budget
    is an upper bound on 'healthy but slow', not an expectation)."""
    if floor is None:
        floor = knobs.get_float("NBD_XFER_MIN_TIMEOUT_S", 60.0)
    rate = max(1.0, knobs.get_float("NBD_XFER_MIN_BYTES_PER_S",
                                    float(DEFAULT_MIN_BYTES_PER_S)))
    return max(floor, nbytes / rate)


# ----------------------------------------------------------------------
# per-chunk compression codecs


_OPTIONAL: dict[str, Any] = {}


def _optional(name: str):
    """Import-once probe for the optional codec modules."""
    if name not in _OPTIONAL:
        try:
            if name == "lz4":
                import lz4.frame as mod  # type: ignore
            elif name == "zstd":
                import zstandard as mod  # type: ignore
            else:
                mod = None
        except Exception:
            mod = None
        _OPTIONAL[name] = mod
    return _OPTIONAL[name]


def available_codecs() -> list[str]:
    out = ["zlib"]
    if _optional("lz4") is not None:
        out.append("lz4")
    if _optional("zstd") is not None:
        out.append("zstd")
    return out


def pick_codec() -> str:
    """Resolve ``NBD_XFER_CODEC``: ``none`` (default), an explicit
    codec, or ``auto`` = the cheapest available (lz4 > zstd > zlib)."""
    choice = (knobs.get_str("NBD_XFER_CODEC") or "none").lower()
    if choice in ("", "none", "stored", "0", "off"):
        return "none"
    if choice == "auto":
        avail = available_codecs()
        for c in ("lz4", "zstd", "zlib"):
            if c in avail:
                return c
        return "none"
    if choice == "zlib" or choice in available_codecs():
        return choice
    return "zlib"  # requested codec missing: zlib is always there


def compress_chunk(codec: str, raw) -> tuple[str, bytes]:
    """Compress one chunk; returns ``(enc, payload)`` where ``enc`` is
    the codec actually used — ``"stored"`` when compression didn't pay
    (payload would not shrink) or the codec is ``none``."""
    raw_b = raw if isinstance(raw, (bytes, bytearray)) else bytes(raw)
    if codec == "none":
        return "stored", bytes(raw_b)
    if codec == "zlib":
        out = zlib.compress(raw_b, 1)
    elif codec == "lz4":
        mod = _optional("lz4")
        if mod is None:
            return "stored", bytes(raw_b)
        out = mod.compress(raw_b)
    elif codec == "zstd":
        mod = _optional("zstd")
        if mod is None:
            return "stored", bytes(raw_b)
        out = mod.ZstdCompressor(level=1).compress(raw_b)
    else:
        return "stored", bytes(raw_b)
    if len(out) >= len(raw_b):
        return "stored", bytes(raw_b)
    return codec, out


def decompress_chunk(enc: str, payload: bytes, raw_len: int) -> bytes:
    if enc == "stored":
        return payload if isinstance(payload, bytes) else bytes(payload)
    if enc == "zlib":
        return zlib.decompress(payload)
    if enc == "lz4":
        mod = _optional("lz4")
        if mod is None:
            raise XferError("chunk compressed with lz4 but lz4 is not "
                            "installed here (pip install lz4)")
        return mod.decompress(payload)
    if enc == "zstd":
        mod = _optional("zstd")
        if mod is None:
            raise XferError("chunk compressed with zstd but zstandard "
                            "is not installed here")
        return mod.ZstdDecompressor().decompress(payload,
                                                 max_output_size=raw_len)
    raise XferError(f"unknown chunk encoding {enc!r}")


# ----------------------------------------------------------------------
# the logical byte stream: gather (source) / scatter (sink)


def _byte_view(arr: np.ndarray) -> np.ndarray:
    """1-D uint8 view of a C-contiguous array (works for ml_dtypes
    extras like bfloat16, which don't all speak the buffer protocol)."""
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    return arr.reshape(-1).view(np.uint8)


class ChunkSource:
    """Sender-side: an ordered set of leaf buffers viewed as one
    contiguous logical byte stream, readable in fixed-size chunks.
    Nothing is concatenated — :meth:`read` gathers each chunk from the
    underlying arrays into one chunk-sized scratch buffer, so sender
    extra memory is O(chunk), not O(payload)."""

    def __init__(self, bufs: dict[str, np.ndarray]):
        self.descs: list[dict] = []
        self._views: list[np.ndarray] = []
        self._offsets: list[int] = []
        off = 0
        for name, value in bufs.items():
            arr = np.asarray(value)
            view = _byte_view(arr)
            self.descs.append({"b": name, "dtype": arr.dtype.name,
                               "shape": list(arr.shape),
                               "len": int(view.nbytes)})
            self._views.append(view)
            self._offsets.append(off)
            off += view.nbytes
        self.total = off

    def n_chunks(self, csize: int) -> int:
        return max(1, -(-self.total // csize)) if self.total else 1

    def read(self, seq: int, csize: int) -> bytes:
        """Gather chunk ``seq`` of the logical stream."""
        start = seq * csize
        stop = min(start + csize, self.total)
        out = bytearray(stop - start)
        pos = 0
        for view, voff in zip(self._views, self._offsets):
            if voff + view.nbytes <= start:
                continue
            if voff >= stop:
                break
            a = max(start, voff) - voff
            b = min(stop, voff + view.nbytes) - voff
            n = b - a
            out[pos:pos + n] = memoryview(view[a:b])
            pos += n
        return bytes(out)

    def crcs(self, csize: int) -> list[int]:
        """crc32 of every chunk's raw bytes — one pass over the
        source; these are both the per-chunk integrity checks and the
        input to the content-addressed transfer id."""
        return [zlib.crc32(self.read(seq, csize))
                for seq in range(self.n_chunks(csize))]


class ChunkSink:
    """Receiver-side: preallocated destination leaf arrays plus the
    chunk bitmap.  Chunks scatter straight into the final arrays —
    assembly is free at commit time and the destination is the ONLY
    payload-sized allocation on the receiver."""

    def __init__(self, descs: list[dict], total: int, n_chunks: int,
                 csize: int):
        self.descs = descs
        self.total = int(total)
        self.n_chunks = int(n_chunks)
        self.csize = int(csize)
        self.arrays: dict[str, np.ndarray] = {}
        self._views: list[np.ndarray] = []
        self._offsets: list[int] = []
        off = 0
        for d in descs:
            arr = np.empty(tuple(d["shape"]), dtype=_np_dtype(d["dtype"]))
            self.arrays[d["b"]] = arr
            view = _byte_view(arr)
            if view.nbytes != d["len"]:
                raise XferError(f"leaf {d['b']}: dtype/shape disagree "
                                f"with byte length {d['len']}")
            self._views.append(view)
            self._offsets.append(off)
            off += view.nbytes
        if off != self.total:
            raise XferError("leaf layout does not sum to total bytes")
        self._bits = bytearray((self.n_chunks + 7) // 8)
        self.have = 0

    def has(self, seq: int) -> bool:
        return bool(self._bits[seq >> 3] & (1 << (seq & 7)))

    def write(self, seq: int, raw: bytes) -> None:
        """Scatter one raw chunk into the destination arrays."""
        if not (0 <= seq < self.n_chunks):
            raise XferError(f"chunk seq {seq} out of range")
        start = seq * self.csize
        stop = min(start + self.csize, self.total)
        if len(raw) != stop - start:
            raise XferError(f"chunk {seq}: got {len(raw)} bytes, "
                            f"want {stop - start}")
        src = np.frombuffer(raw, dtype=np.uint8)
        pos = 0
        for view, voff in zip(self._views, self._offsets):
            if voff + view.nbytes <= start:
                continue
            if voff >= stop:
                break
            a = max(start, voff) - voff
            b = min(stop, voff + view.nbytes) - voff
            n = b - a
            view[a:b] = src[pos:pos + n]
            pos += n
        if not self.has(seq):
            self._bits[seq >> 3] |= 1 << (seq & 7)
            self.have += 1

    def bitmap_hex(self) -> str:
        return bytes(self._bits).hex()

    def missing(self) -> list[int]:
        return [s for s in range(self.n_chunks) if not self.has(s)]

    def complete(self) -> bool:
        return self.have >= self.n_chunks


def missing_from_bitmap(hex_bitmap: str, n_chunks: int) -> list[int]:
    """Coordinator-side resume: decode a receiver's ``have`` bitmap
    into the chunk seqs it is still missing."""
    try:
        bits = bytes.fromhex(hex_bitmap or "")
    except ValueError:
        bits = b""
    out = []
    for seq in range(n_chunks):
        byte = bits[seq >> 3] if (seq >> 3) < len(bits) else 0
        if not (byte & (1 << (seq & 7))):
            out.append(seq)
    return out


def transfer_id(kind: str, name: str, total: int, csize: int,
                crcs: list[int]) -> str:
    """Content-addressed transfer id: the same (value, destination
    name) always maps to the same xid, which is what lets a reattached
    coordinator — a DIFFERENT process with no shared state — resume a
    half-finished push from the receivers' bitmaps alone."""
    h = hashlib.sha1()
    h.update(json.dumps([kind, name, int(total), int(csize)],
                        sort_keys=True).encode())
    for c in crcs:
        h.update(int(c).to_bytes(4, "little"))
    return "x" + h.hexdigest()[:16]


# ----------------------------------------------------------------------
# run-dir manifest (observability / postmortem only — resume
# correctness comes from the content-addressed xid + receiver bitmaps)


def _manifest_path(xid: str) -> str | None:
    try:
        from ..observability import flightrec
        d = os.path.join(flightrec.run_dir(), "xfer")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{xid}.json")
    except Exception:
        return None


def write_manifest(xid: str, info: dict) -> None:
    path = _manifest_path(xid)
    if path is None:
        return
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, path)
    except OSError:
        pass  # the manifest is advisory, never load-bearing


def load_manifest(xid: str) -> dict | None:
    path = _manifest_path(xid)
    if path is None:
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ----------------------------------------------------------------------
# coordinator side: push


def _record(comm, event: str, **fields) -> None:
    try:
        comm.flight.record(event, **fields)
    except Exception:
        pass


def _counter(name: str, doc: str, n: int = 1) -> None:
    try:
        from ..observability import metrics as obs_metrics
        obs_metrics.registry().counter(name, doc).inc(n)
    except Exception:
        pass


class _Window:
    """Credit-based flow control: at most ``size`` chunk submissions
    in flight, drained oldest-first.  Tracks the peak in-flight bytes
    — the deterministic half of the 'bounded by window x chunk'
    acceptance assertion (the RSS half lives in the chaos test)."""

    def __init__(self, size: int):
        self.size = size
        self._q: deque = deque()
        self.inflight_bytes = 0
        self.peak_bytes = 0
        self.drained: list = []

    def admit(self, handle, nbytes: int, seq: int, ranks: list[int],
              drain: Callable) -> None:
        self._q.append((handle, nbytes, seq, ranks))
        self.inflight_bytes += nbytes
        self.peak_bytes = max(self.peak_bytes, self.inflight_bytes)
        while len(self._q) >= self.size:
            self.drain_one(drain)

    def drain_one(self, drain: Callable) -> None:
        handle, nbytes, seq, ranks = self._q.popleft()
        self.inflight_bytes -= nbytes
        drain(handle, seq, ranks)

    def drain_all(self, drain: Callable) -> None:
        while self._q:
            self.drain_one(drain)


def push_value(comm, ranks: list[int], name: str, value: Any, *,
               tenant: str | None = None,
               log: Callable[[str], None] | None = None) -> dict:
    """Chunked ``%dist_push``: stream ``value`` into ``name`` in each
    rank's namespace.  Raises :class:`XferFallback` when the value
    cannot ride the buffer path (caller keeps the legacy frame)."""
    try:
        meta, bufs = flatten_pytree_wire(value)
    except TypeError as e:
        raise XferFallback(str(e)) from e
    return push_flat(comm, ranks, "var", name, meta, bufs,
                     tenant=tenant, log=log)


def push_file(comm, ranks: list[int], src_path: str, dest_path: str, *,
              tenant: str | None = None,
              log: Callable[[str], None] | None = None) -> dict:
    """Ship one local file to ``dest_path`` on every target rank over
    the chunked plane — checkpoint-restore shipping for worlds with no
    shared filesystem."""
    data = np.fromfile(src_path, dtype=np.uint8)
    meta = {"k": "leaf", "buf": "f0", "jax": False}
    return push_flat(comm, ranks, "file", os.path.basename(src_path),
                     meta, {"f0": data}, dest=dest_path, tenant=tenant,
                     log=log)


def push_flat(comm, ranks: list[int], kind: str, name: str, meta: dict,
              bufs: dict[str, np.ndarray], *, dest: str | None = None,
              tenant: str | None = None,
              log: Callable[[str], None] | None = None) -> dict:
    """The push engine: begin → windowed chunks (resume-aware) →
    commit.  Returns a stats dict (xid, bytes, chunks, resent,
    resumed, wire bytes, peak in-flight bytes, seconds)."""
    t0 = time.monotonic()
    csize = chunk_bytes()
    src = ChunkSource(bufs)
    n = src.n_chunks(csize)
    crcs = src.crcs(csize)
    xid = transfer_id(kind, name, src.total, csize, crcs)
    codec = pick_codec()
    ranks = list(ranks)

    write_manifest(xid, {"xid": xid, "kind": kind, "name": name,
                         "total": src.total, "chunk_bytes": csize,
                         "n_chunks": n, "ranks": ranks, "codec": codec,
                         "state": "begin"})
    _record(comm, "xfer_begin", xid=xid, kind=kind, name=name,
            total=src.total, n_chunks=n, codec=codec, ranks=ranks)

    begin = comm.send_to_ranks(
        ranks, "xfer_begin",
        {"xid": xid, "kind": kind, "name": name, "dest": dest,
         "total": src.total, "chunk_bytes": csize, "n_chunks": n,
         "meta": meta, "descs": src.descs},
        tenant=tenant, timeout=scaled_timeout(0))

    need: dict[int, set[int]] = {}
    resumed = 0
    done_ranks: set[int] = set()
    for r, reply in begin.items():
        d = reply.data or {}
        if d.get("error"):
            raise XferError(f"rank {r} refused transfer: {d['error']}")
        if d.get("done"):
            done_ranks.add(r)
            continue
        missing = set(missing_from_bitmap(d.get("have", ""), n))
        need[r] = missing
        resumed += n - len(missing)

    retry_resent = 0
    crc_resent = 0
    crc_failed: set[tuple[int, int]] = set()  # (rank, seq)
    wire_bytes = 0
    win = _Window(window_size())

    def drain(handle, seq: int, tranks: list[int]) -> None:
        nonlocal retry_resent
        replies = handle.wait()
        if handle.msg is not None and handle.msg.attempt:
            # The retry layer redelivered this chunk (dropped frame or
            # dropped reply) — that is a chunk-level resend, and the
            # replay cache guarantees it was not a double-write.
            retry_resent += 1
        for r, reply in replies.items():
            d = reply.data or {}
            if d.get("error"):
                if "crc" in str(d.get("error", "")):
                    crc_failed.add((r, seq))
                else:
                    raise XferError(
                        f"rank {r} chunk {seq}: {d['error']}")

    def send_chunk(seq: int, tranks: list[int]) -> None:
        nonlocal wire_bytes
        raw = src.read(seq, csize)
        enc, payload = compress_chunk(codec, raw)
        wire_bytes += len(payload)
        handle = comm.submit(
            tranks, "xfer_chunk", None, bufs={"c": payload},
            xfer={"x": xid, "s": seq, "c": crcs[seq], "e": enc,
                  "r": len(raw)},
            tenant=tenant, timeout=scaled_timeout(csize))
        win.admit(handle, len(payload), seq, tranks, drain)

    live = [r for r in ranks if r not in done_ranks]
    todo = sorted(set().union(*need.values())) if need else []
    for seq in todo:
        tranks = [r for r in live if seq in need.get(r, ())]
        if tranks:
            send_chunk(seq, tranks)
    win.drain_all(drain)

    # Chunks the receiver refused on crc (a corrupted frame whose
    # header survived): re-send, fresh msg_id, bounded attempts.
    rounds = 0
    while crc_failed:
        rounds += 1
        if rounds > 8:
            raise XferError(f"chunks kept failing crc after {rounds} "
                            f"rounds: {sorted(crc_failed)[:4]}...")
        batch, crc_failed = crc_failed, set()
        crc_resent += len(batch)
        _counter("nbd_xfer_chunks_resent_total",
                 "bulk-transfer chunks re-sent", len(batch))
        by_seq: dict[int, list[int]] = {}
        for r, seq in batch:
            by_seq.setdefault(seq, []).append(r)
        for seq, tranks in sorted(by_seq.items()):
            send_chunk(seq, tranks)
        win.drain_all(drain)

    if retry_resent:
        _counter("nbd_xfer_chunks_resent_total",
                 "bulk-transfer chunks re-sent", retry_resent)
    resent = retry_resent + crc_resent

    commit = comm.send_to_ranks(
        live, "xfer_commit",
        {"xid": xid, "kind": kind, "name": name, "dest": dest},
        tenant=tenant, timeout=scaled_timeout(src.total))
    applies = {}
    for r, reply in commit.items():
        d = reply.data or {}
        if d.get("error"):
            raise XferError(f"rank {r} commit failed: {d['error']}")
        applies[r] = d.get("applies", 1)

    secs = time.monotonic() - t0
    stats = {"xid": xid, "kind": kind, "name": name,
             "bytes": src.total, "chunks": n, "ranks": ranks,
             "already_done": sorted(done_ranks),
             "resumed_chunks": resumed, "resent_chunks": resent,
             "wire_bytes": wire_bytes, "codec": codec,
             "inflight_peak_bytes": win.peak_bytes,
             "window": win.size, "chunk_bytes": csize,
             "applies": applies, "seconds": round(secs, 3)}
    write_manifest(xid, {**stats, "state": "applied"})
    _record(comm, "xfer_done", **{k: v for k, v in stats.items()
                                  if k != "applies"})
    if log is not None and secs > 0:
        log(f"[xfer] {name}: {src.total / 1e6:.1f} MB in {n} chunks "
            f"({src.total / secs / 1e9:.2f} GB/s, codec={codec}, "
            f"resumed={resumed}, resent={resent})")
    return stats


# ----------------------------------------------------------------------
# coordinator side: pull


def pull_value(comm, rank: int, name: str, *, readonly: bool = False,
               tenant: str | None = None) -> tuple[Any, dict]:
    """Chunked ``%dist_pull``: returns ``(value, stats)``.  Small or
    inline-able values come back in the begin round-trip; large ones
    stream receiver-driven ``xfer_read`` chunks into preallocated
    destination arrays (exactly one copy end to end — satellite:
    never view + copy).  Raises :class:`XferFallback` for values that
    must take the legacy ``get_var`` path."""
    t0 = time.monotonic()
    csize = chunk_bytes()
    begin = comm.send_to_rank(
        rank, "xfer_pull_begin",
        {"name": name, "chunk_bytes": csize,
         "threshold": threshold_bytes(), "codec": pick_codec()},
        timeout=scaled_timeout(0))
    d = begin.data or {}
    if d.get("error"):
        raise XferError(d["error"])
    if d.get("fallback"):
        raise XferFallback(d.get("why", "not a buffer-path value"))
    if d.get("inline"):
        if readonly:
            leaf_fn = (lambda a, j: a)
        else:
            leaf_fn = (lambda a, j: np.array(a))
        value = unflatten_pytree_wire(d["meta"], begin.bufs, leaf_fn)
        return value, {"bytes": d.get("total", 0), "chunks": 0,
                       "inline": True, "readonly": readonly,
                       "seconds": round(time.monotonic() - t0, 3)}

    xid = d["xid"]
    total, n = int(d["total"]), int(d["n_chunks"])
    sink = ChunkSink(d["descs"], total, n, int(d["chunk_bytes"]))
    win = _Window(window_size())
    resent = 0
    wire_bytes = 0
    retries: list[int] = []

    def drain(handle, seq: int, _ranks) -> None:
        nonlocal resent, wire_bytes
        reply = handle.wait()[rank]
        rd = reply.data or {}
        if rd.get("error"):
            raise XferError(f"chunk {seq}: {rd['error']}")
        xf = reply.xfer or {}
        payload = reply.bufs.get("c", b"")
        payload = payload if isinstance(payload, (bytes, bytearray)) \
            else bytes(payload)
        wire_bytes += len(payload)
        raw = decompress_chunk(xf.get("e", "stored"), payload,
                               int(xf.get("r", 0)))
        if zlib.crc32(raw) != xf.get("c"):
            retries.append(seq)
            return
        sink.write(seq, raw)

    def request(seq: int) -> None:
        handle = comm.submit([rank], "xfer_read",
                             {"xid": xid, "seq": seq}, tenant=tenant,
                             timeout=scaled_timeout(csize))
        win.admit(handle, sink.csize, seq, [rank], drain)

    for seq in range(n):
        request(seq)
    win.drain_all(drain)
    rounds = 0
    while retries:
        rounds += 1
        if rounds > 8:
            raise XferError(f"pull chunks kept failing crc: "
                            f"{retries[:4]}...")
        batch, retries[:] = list(retries), []
        resent += len(batch)
        _counter("nbd_xfer_chunks_resent_total",
                 "bulk-transfer chunks re-sent", len(batch))
        for seq in batch:
            request(seq)
        win.drain_all(drain)
    try:
        comm.send_to_ranks([rank], "xfer_pull_end", {"xid": xid},
                           tenant=tenant, timeout=30)
    except Exception:
        pass  # snapshot GC is best-effort; the worker LRU-caps it

    if readonly:
        # The chunked path has no decode views to hand back (chunks
        # stream straight into the destination arrays), so honor the
        # flag by freezing those — same contract as the inline path.
        for a in sink.arrays.values():
            a.flags.writeable = False
    value = unflatten_pytree_wire(d["meta"], sink.arrays,
                                  lambda a, j: a)
    secs = time.monotonic() - t0
    return value, {"xid": xid, "bytes": total, "chunks": n,
                   "resent_chunks": resent, "wire_bytes": wire_bytes,
                   "inline": False, "readonly": readonly,
                   "inflight_peak_bytes": win.peak_bytes,
                   "seconds": round(secs, 3)}


def pull_file(comm, rank: int, src_path: str, dest_path: str, *,
              tenant: str | None = None) -> dict:
    """Fetch one file from a rank to a local path over the chunked
    plane — checkpoint-save shipping (gather per-rank shards)."""
    begin = comm.send_to_rank(
        rank, "xfer_pull_begin",
        {"file": src_path, "chunk_bytes": chunk_bytes(),
         "threshold": threshold_bytes(), "codec": pick_codec()},
        timeout=scaled_timeout(0))
    d = begin.data or {}
    if d.get("error"):
        raise XferError(d["error"])
    os.makedirs(os.path.dirname(os.path.abspath(dest_path)),
                exist_ok=True)
    if d.get("inline"):
        blob = begin.bufs.get("f0", b"")
        with open(dest_path, "wb") as f:
            f.write(blob if isinstance(blob, bytes) else bytes(blob))
        return {"bytes": d.get("total", 0), "chunks": 0, "inline": True}
    value, stats = _pull_started(comm, rank, d, tenant=tenant)
    np.asarray(value).tofile(dest_path)
    return stats


def _pull_started(comm, rank: int, d: dict, *,
                  tenant: str | None = None) -> tuple[Any, dict]:
    """Finish a pull whose ``xfer_pull_begin`` reply ``d`` announced a
    chunked transfer (shared by :func:`pull_file`)."""
    xid = d["xid"]
    total, n = int(d["total"]), int(d["n_chunks"])
    sink = ChunkSink(d["descs"], total, n, int(d["chunk_bytes"]))
    for seq in range(n):
        reply = comm.send_to_rank(rank, "xfer_read",
                                  {"xid": xid, "seq": seq},
                                  timeout=scaled_timeout(sink.csize))
        xf = reply.xfer or {}
        payload = reply.bufs.get("c", b"")
        raw = decompress_chunk(xf.get("e", "stored"),
                               payload if isinstance(payload, bytes)
                               else bytes(payload), int(xf.get("r", 0)))
        if zlib.crc32(raw) != xf.get("c"):
            raise XferError(f"pull chunk {seq} failed crc")
        sink.write(seq, raw)
    try:
        comm.send_to_ranks([rank], "xfer_pull_end", {"xid": xid},
                           tenant=tenant, timeout=30)
    except Exception:
        pass
    value = unflatten_pytree_wire(d["meta"], sink.arrays,
                                  lambda a, j: a)
    return value, {"xid": xid, "bytes": total, "chunks": n,
                   "inline": False}


# ----------------------------------------------------------------------
# worker side: the transfer endpoint


class _Inbound:
    __slots__ = ("xid", "kind", "name", "dest", "meta", "sink",
                 "created", "tenant")

    def __init__(self, xid, kind, name, dest, meta, sink, tenant):
        self.xid, self.kind, self.name = xid, kind, name
        self.dest, self.meta, self.sink = dest, meta, sink
        self.tenant = tenant
        self.created = time.monotonic()


class _Outbound:
    __slots__ = ("xid", "src", "csize", "codec", "crcs", "created")

    def __init__(self, xid, src, csize, codec):
        self.xid, self.src = xid, src
        self.csize, self.codec = csize, codec
        self.crcs = None  # lazy: per-chunk crc computed on demand
        self.created = time.monotonic()


class XferEndpoint:
    """Worker-side state machine for both transfer directions.

    Owned by the worker's serial request loop — no locking needed.
    Inbound (push) transfers scatter into preallocated destination
    arrays; the bind into the namespace (or file write) happens ONCE
    at commit, and completed xids are memoized so a resumed push from
    a post-SIGKILL coordinator — or a redelivered commit the replay
    cache has already aged out — still applies exactly once."""

    def __init__(self, rank: int = 0,
                 say: Callable[[str], None] | None = None):
        self.rank = rank
        self._say = say or (lambda s: None)
        self.inbound: OrderedDict[str, _Inbound] = OrderedDict()
        self.outbound: OrderedDict[str, _Outbound] = OrderedDict()
        # xid -> the commit reply data already sent (bounded memo).
        self.completed: OrderedDict[str, dict] = OrderedDict()
        # xid -> staleness probe from bind(): the memo only answers
        # "done" while the committed binding is intact (variable still
        # bound to the applied object / file still on disk).  A rebound
        # or deleted destination drops the memo so a deliberate re-push
        # of the same content restores it instead of no-oping.
        self._probes: dict[str, Callable[[], bool] | None] = {}
        self.counters = {"begins": 0, "chunks": 0, "dup_chunks": 0,
                         "crc_rejects": 0, "applies": 0,
                         "evicted": 0, "reads": 0}

    def _memo(self, xid: str) -> dict | None:
        """The completed-xid memo entry, validated against its
        staleness probe.  Exactly-once holds per content per BINDING:
        once the destination drifts (user rebound/deleted the
        variable, removed the file) the memo is dropped and the next
        push of this content applies again."""
        entry = self.completed.get(xid)
        if entry is None:
            return None
        probe = self._probes.get(xid)
        try:
            fresh = probe() if probe is not None else True
        except Exception:
            fresh = False
        if not fresh:
            del self.completed[xid]
            self._probes.pop(xid, None)
            return None
        return entry

    # -- push (coordinator → worker) -----------------------------------

    def handle_begin(self, msg: Message) -> Message:
        d = msg.data
        xid = d["xid"]
        self.counters["begins"] += 1
        if self._memo(xid) is not None:
            # Exactly-once across coordinator generations: a resumed
            # push for an already-applied transfer sends NOTHING.
            return msg.reply(data={"done": True, "xid": xid},
                             rank=self.rank)
        st = self.inbound.get(xid)
        if st is None:
            try:
                sink = ChunkSink(d["descs"], d["total"], d["n_chunks"],
                                 d["chunk_bytes"])
            except (XferError, TypeError, ValueError) as e:
                return msg.reply(data={"error": f"bad layout: {e}"},
                                 rank=self.rank)
            st = _Inbound(xid, d.get("kind", "var"), d.get("name"),
                          d.get("dest"), d.get("meta"), sink,
                          msg.tenant)
            self.inbound[xid] = st
            cap = max(1, knobs.get_int("NBD_XFER_INBOUND_MAX",
                                       DEFAULT_INBOUND_MAX))
            while len(self.inbound) > cap:
                old, _ = self.inbound.popitem(last=False)
                self.counters["evicted"] += 1
                self._say(f"[xfer] evicted incomplete inbound "
                          f"transfer {old} (cap {cap})")
        else:
            self.inbound.move_to_end(xid)
        return msg.reply(data={"ok": True, "xid": xid,
                               "have": st.sink.bitmap_hex(),
                               "n_have": st.sink.have},
                         rank=self.rank)

    def handle_chunk(self, msg: Message) -> Message:
        xf = msg.xfer or {}
        xid, seq = xf.get("x"), int(xf.get("s", -1))
        if self._memo(xid) is not None:
            return msg.reply(data={"ok": True, "done": True},
                             rank=self.rank)
        st = self.inbound.get(xid)
        if st is None:
            return msg.reply(data={"error": "unknown transfer",
                                   "xid": xid}, rank=self.rank)
        self.counters["chunks"] += 1
        if st.sink.has(seq):
            # Same chunk again under a NEW msg_id (retry-layer
            # redeliveries under the same id never even reach here —
            # the replay cache answers them).  Bitmap-idempotent.
            self.counters["dup_chunks"] += 1
            return msg.reply(data={"ok": True, "dup": True,
                                   "n_have": st.sink.have},
                             rank=self.rank)
        payload = msg.bufs.get("c", b"")
        try:
            raw = decompress_chunk(
                xf.get("e", "stored"),
                payload if isinstance(payload, (bytes, bytearray))
                else bytes(payload),
                int(xf.get("r", 0)))
        except Exception as e:
            self.counters["crc_rejects"] += 1
            return msg.reply(data={"error": f"crc/decode reject: {e}",
                                   "seq": seq}, rank=self.rank)
        if zlib.crc32(raw) != xf.get("c"):
            self.counters["crc_rejects"] += 1
            return msg.reply(data={"error": "crc mismatch",
                                   "seq": seq}, rank=self.rank)
        try:
            st.sink.write(seq, raw)
        except XferError as e:
            return msg.reply(data={"error": str(e), "seq": seq},
                             rank=self.rank)
        return msg.reply(data={"ok": True, "n_have": st.sink.have},
                         rank=self.rank)

    def handle_commit(self, msg: Message,
                      bind: Callable[[_Inbound], Any]) -> Message:
        """``bind`` applies the completed transfer and may return a
        zero-argument staleness probe (see :meth:`_memo`)."""
        xid = msg.data["xid"]
        memo = self._memo(xid)
        if memo is not None:
            # A second commit (new coordinator after SIGKILL, or a
            # redelivery the replay cache aged out): answer from the
            # memo — the bind ran exactly once.
            return msg.reply(data=dict(memo), rank=self.rank)
        st = self.inbound.get(xid)
        if st is None:
            return msg.reply(data={"error": "unknown transfer",
                                   "xid": xid}, rank=self.rank)
        if not st.sink.complete():
            return msg.reply(
                data={"error": "incomplete",
                      "missing": len(st.sink.missing()),
                      "have": st.sink.bitmap_hex()},
                rank=self.rank)
        try:
            probe = bind(st)
        except Exception as e:
            return msg.reply(data={"error": f"bind failed: {e}"},
                             rank=self.rank)
        self.counters["applies"] += 1
        del self.inbound[xid]
        reply_data = {"status": "applied", "xid": xid, "applies": 1,
                      "kind": st.kind, "name": st.name}
        self.completed[xid] = reply_data
        self._probes[xid] = probe if callable(probe) else None
        while len(self.completed) > 32:
            old, _ = self.completed.popitem(last=False)
            self._probes.pop(old, None)
        return msg.reply(data=dict(reply_data), rank=self.rank)

    # -- pull (worker → coordinator) -----------------------------------

    def handle_pull_begin(self, msg: Message,
                          ns: dict | None) -> Message:
        d = msg.data or {}
        csize = int(d.get("chunk_bytes") or chunk_bytes())
        small = int(d.get("threshold") or threshold_bytes())
        codec = d.get("codec") or "none"
        if d.get("file"):
            path = os.path.expanduser(d["file"])
            if not os.path.isfile(path):
                return msg.reply(data={"error": f"no such file: "
                                       f"{path}"}, rank=self.rank)
            bufs = {"f0": np.fromfile(path, dtype=np.uint8)}
            meta = {"k": "leaf", "buf": "f0", "jax": False}
        else:
            name = d.get("name")
            if ns is None or name not in ns:
                return msg.reply(data={"error": f"name {name!r} not "
                                       f"defined"}, rank=self.rank)
            try:
                meta, bufs = flatten_pytree_wire(ns[name])
            except TypeError as e:
                return msg.reply(data={"fallback": True,
                                       "why": str(e)}, rank=self.rank)
        src = ChunkSource(bufs)
        if src.total <= small:
            return msg.reply(data={"inline": True, "meta": meta,
                                   "total": src.total},
                             rank=self.rank, bufs=bufs)
        xid = "p" + uuid.uuid4().hex[:16]
        self.outbound[xid] = _Outbound(xid, src, csize, codec)
        cap = max(1, knobs.get_int("NBD_XFER_INBOUND_MAX",
                                   DEFAULT_INBOUND_MAX))
        while len(self.outbound) > cap:
            old, _ = self.outbound.popitem(last=False)
            self.counters["evicted"] += 1
        return msg.reply(data={"xid": xid, "meta": meta,
                               "descs": src.descs, "total": src.total,
                               "chunk_bytes": csize,
                               "n_chunks": src.n_chunks(csize)},
                         rank=self.rank)

    def handle_read(self, msg: Message) -> Message:
        d = msg.data or {}
        st = self.outbound.get(d.get("xid"))
        if st is None:
            return msg.reply(data={"error": "unknown transfer",
                                   "xid": d.get("xid")},
                             rank=self.rank)
        self.outbound.move_to_end(st.xid)
        seq = int(d.get("seq", -1))
        if not (0 <= seq < st.src.n_chunks(st.csize)):
            return msg.reply(data={"error": f"seq {seq} out of range"},
                             rank=self.rank)
        self.counters["reads"] += 1
        raw = st.src.read(seq, st.csize)
        enc, payload = compress_chunk(st.codec, raw)
        reply = msg.reply(data={"ok": True, "seq": seq},
                          rank=self.rank, bufs={"c": payload})
        reply.xfer = {"x": st.xid, "s": seq, "c": zlib.crc32(raw),
                      "e": enc, "r": len(raw)}
        return reply

    def handle_pull_end(self, msg: Message) -> Message:
        gone = self.outbound.pop((msg.data or {}).get("xid"), None)
        return msg.reply(data={"ok": gone is not None},
                         rank=self.rank)

    # -- introspection -------------------------------------------------

    def status(self) -> dict:
        return {**self.counters,
                "inbound": len(self.inbound),
                "outbound": len(self.outbound),
                "completed": len(self.completed)}
