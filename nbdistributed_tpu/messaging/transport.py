"""TCP transport for the control plane.

The reference uses ZMQ ROUTER (coordinator) / DEALER (worker) sockets with
identity strings ``worker_{rank}`` (reference: communication.py:124-125,
worker.py:154-157).  This module provides the same topology on plain
sockets: a :class:`CoordinatorListener` accepts one connection per worker
and routes frames by the rank announced in an initial HELLO frame, and a
:class:`WorkerChannel` is the worker-side dial-out.

Differences from the reference, by design:

* **Explicit readiness**: the HELLO handshake makes worker attachment an
  observable event, replacing the reference's ``sleep(2)`` + ZMQ late-join
  buffering (reference: process_manager.py:136-150, SURVEY §7 "hard parts").
* **Single poller, no busy loop**: the coordinator reader thread blocks in
  ``selector.select()`` instead of polling every 100 ms
  (reference: communication.py:170), so round-trip latency is wire-bound.
* **Disconnect notifications**: worker socket death is surfaced via
  ``on_disconnect`` so pending requests can fail fast instead of hanging
  forever in no-timeout mode (reference: communication.py:263-269).

A C++ fast-path transport with the same interface can be slotted in via
:mod:`nbdistributed_tpu.messaging.native` when built (see native/).
"""

from __future__ import annotations

import selectors
import socket
import struct
import threading
from typing import Callable

from .codec import (CodecError, Message, decode, encode, frame_ready,
                    wire_hook)

# Connection preamble: worker announces its rank in a fixed header
# before any frames — the identity handshake ZMQ did with socket
# identities (reference: worker.py:154-157), kept trivially parseable
# so the native C++ listener and this Python listener speak one
# protocol.  Two variants:
#   "NBDW" + i32 rank                      (8 bytes, loopback worlds)
#   "NBDA" + i32 rank + sha256(token)      (40 bytes, authenticated:
#                                           non-loopback/multihost)
# The digest form keeps the preamble fixed-size for any token length
# and never puts the secret itself on the wire.
PREAMBLE_MAGIC = b"NBDW"
AUTH_PREAMBLE_MAGIC = b"NBDA"
PREAMBLE_SIZE = 8
AUTH_PREAMBLE_SIZE = 40


def token_digest(auth_token: str) -> bytes:
    import hashlib

    return hashlib.sha256(auth_token.encode("utf-8",
                                            "surrogatepass")).digest()


def make_preamble(rank: int, auth_token: str | None = None) -> bytes:
    if auth_token is None:
        return PREAMBLE_MAGIC + struct.pack("<i", rank)
    return (AUTH_PREAMBLE_MAGIC + struct.pack("<i", rank)
            + token_digest(auth_token))


class TransportError(Exception):
    pass


# Documented exemptions for the blocking-call-under-lock self-lint
# (analysis/concur.py).  The write locks below exist PRECISELY to
# serialize whole-frame socket writes from concurrent sender threads
# (coordinator caller threads; worker stdout-streamer + heartbeat) —
# they guard no other state, are never nested inside another lock,
# and a frame interleaved mid-write would tear the stream for good.
_LINT_BLOCKING_OK = {
    "_ConnState.send_frame:send":
        "wlock is the per-connection frame-write serializer; holding "
        "it across the (possibly partial) non-blocking send IS its "
        "one job",
    "WorkerChannel.__init__:sendall":
        "the HELLO preamble must hit the wire before any frame; the "
        "channel is not yet shared when __init__ runs",
    "WorkerChannel._send_frame:sendall":
        "_wlock is the worker-side frame-write serializer (streamer "
        "and heartbeat threads send concurrently); it guards nothing "
        "else",
}


def _set_keepalive(sock: socket.socket) -> None:
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)


class _ConnState:
    """Per-connection incremental read buffer + locked writer.

    ``auth_digest``: when set, only the "NBDA" preamble carrying this
    sha256(token) digest identifies the connection — anything else is a
    CodecError and the listener drops the peer before any frame is
    decoded (so an unauthenticated peer can never reach the codec,
    least of all its pickle path).
    """

    def __init__(self, sock: socket.socket,
                 auth_digest: bytes | None = None):
        self.sock = sock
        self.rbuf = bytearray()
        self.wlock = threading.Lock()
        self.rank: int | None = None  # set after the (validated) preamble
        self.registered = False
        self.auth_digest = auth_digest

    def send_frame(self, frame: bytes) -> None:
        """Write the whole frame even on a non-blocking socket.

        Coordinator-side sockets are non-blocking (the IO thread selects
        on them for reads), so a plain ``sendall`` of a frame larger than
        the kernel buffer would raise mid-write and tear the stream.
        Writes happen on caller threads, so blocking in ``select`` for
        writability here is safe.
        """
        import select as _select

        view = memoryview(frame)
        with self.wlock:
            while view:
                try:
                    n = self.sock.send(view)
                except (BlockingIOError, InterruptedError):
                    _select.select([], [self.sock], [], 1.0)
                    continue
                view = view[n:]

    def feed(self, data: bytes) -> list[bytes]:
        """Append received bytes; return complete frames.  Consumes the
        connection preamble first (setting ``self.rank``), enforcing
        the auth digest when this listener requires one."""
        self.rbuf.extend(data)
        if self.rank is None:
            if len(self.rbuf) < 4:
                return []
            magic = bytes(self.rbuf[:4])
            if magic == AUTH_PREAMBLE_MAGIC:
                need = AUTH_PREAMBLE_SIZE
            elif magic == PREAMBLE_MAGIC:
                need = PREAMBLE_SIZE
            else:
                raise CodecError(f"bad preamble {magic!r}")
            if len(self.rbuf) < need:
                return []
            if self.auth_digest is not None:
                import hmac
                if magic != AUTH_PREAMBLE_MAGIC or not hmac.compare_digest(
                        bytes(self.rbuf[8:AUTH_PREAMBLE_SIZE]),
                        self.auth_digest):
                    raise CodecError("auth digest mismatch")
            self.rank = struct.unpack_from("<i", self.rbuf, 4)[0]
            del self.rbuf[:need]
        frames: list[bytes] = []
        while True:
            n = frame_ready(self.rbuf)
            if not n:
                return frames
            frames.append(bytes(self.rbuf[:n]))
            del self.rbuf[:n]


class CoordinatorListener:
    """Accepts worker connections and routes frames by rank.

    ZMQ-ROUTER analog (reference: communication.py:95-135) with explicit
    connection tracking.  All callbacks run on the single reader thread;
    they must not block.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 allow_pickle: bool = True, auth_token: str | None = None):
        self._allow_pickle = allow_pickle
        # Shared-secret handshake: when set, only the "NBDA" preamble
        # carrying sha256(token) identifies a connection — enforced in
        # _ConnState.feed before any frame exists, so an
        # unauthenticated peer can never reach the codec (least of all
        # its pickle path).  Required for non-loopback binds
        # (multihost): the control plane executes code.
        self._auth_digest = (token_digest(auth_token)
                             if auth_token is not None else None)
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(128)
        self.host, self.port = self._server.getsockname()
        self._sel = selectors.DefaultSelector()
        self._conns: dict[int, _ConnState] = {}  # rank -> conn
        self._lock = threading.Lock()
        self._running = False
        self._thread: threading.Thread | None = None
        self.on_message: Callable[[int, Message], None] = lambda r, m: None
        self.on_connect: Callable[[int], None] = lambda r: None
        self.on_disconnect: Callable[[int], None] = lambda r: None
        # Chaos hook (resilience/faults.py): when set, every outgoing
        # frame passes through the plan, which may drop/delay/
        # duplicate/truncate it deterministically.  None in production.
        self.fault_plan = None
        # Link-shaping topology (ISSUE 6): which host each rank lives
        # on, and this process's own host label — a fault plan with
        # per-link specs uses them to decide which frames cross a
        # partitioned / slow / lossy link.  Empty map = no link ever
        # matches (single-host worlds pay nothing).
        self.host_of_rank: dict[int, str] = {}
        self.local_host: str = "local"
        # wake-up pipe so close() interrupts select()
        self._wake_r, self._wake_w = socket.socketpair()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._running = True
        self._server.setblocking(False)
        self._sel.register(self._server, selectors.EVENT_READ, ("accept", None))
        self._sel.register(self._wake_r, selectors.EVENT_READ, ("wake", None))
        self._thread = threading.Thread(target=self._loop,
                                        name="nbd-coordinator-io", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._running = False
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        if self._thread:
            self._thread.join(timeout=2)
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass
        for s in (self._server, self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    # -- sending -----------------------------------------------------------

    def connected_ranks(self) -> list[int]:
        with self._lock:
            return sorted(self._conns)

    def _transmit(self, conn: "_ConnState", frame: bytes,
                  kind: str) -> None:
        # tx accounting wraps the ACTUAL socket write: a fan-out send
        # counts once per rank, and a chaos plan's drops (0 writes) /
        # duplicates (2 writes) / truncations (shorter frame) are all
        # counted as what really hit the wire.
        def _tx(f: bytes) -> None:
            conn.send_frame(f)
            hook = wire_hook()
            if hook is not None:
                hook("tx", kind, len(f))

        plan = self.fault_plan
        if plan is not None:
            if plan.has_links():
                # Link shaping first (partition/loss/latency/bw for the
                # host pair this frame crosses), composing with the
                # per-frame faults inside link_transmit.
                dst = (self.host_of_rank.get(conn.rank)
                       if conn.rank is not None else None)
                plan.link_transmit(self.local_host, dst, frame, _tx,
                                   kind=kind)
            else:
                plan.transmit(frame, _tx, kind=kind)
        else:
            _tx(frame)

    def send_to_rank(self, rank: int, msg: Message) -> None:
        with self._lock:
            conn = self._conns.get(rank)
        if conn is None:
            raise TransportError(f"rank {rank} is not connected")
        self._transmit(conn, encode(msg, allow_pickle=self._allow_pickle),
                       msg.msg_type)

    def send_to_ranks(self, ranks: list[int], msg: Message) -> None:
        frame = encode(msg, allow_pickle=self._allow_pickle)
        missing = []
        with self._lock:
            conns = [(r, self._conns.get(r)) for r in ranks]
        for r, conn in conns:
            if conn is None:
                missing.append(r)
            else:
                self._transmit(conn, frame, msg.msg_type)
        if missing:
            raise TransportError(f"ranks {missing} are not connected")

    # -- reader loop -------------------------------------------------------

    def _loop(self) -> None:
        unidentified: dict[socket.socket, _ConnState] = {}
        while self._running:
            try:
                events = self._sel.select(timeout=1.0)
            except OSError:
                if not self._running:
                    return
                raise
            for key, _ in events:
                tag, conn = key.data
                if tag == "wake":
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                elif tag == "accept":
                    try:
                        sock, _addr = self._server.accept()
                    except OSError:
                        continue
                    _set_keepalive(sock)
                    sock.setblocking(False)
                    st = _ConnState(sock, auth_digest=self._auth_digest)
                    unidentified[sock] = st
                    self._sel.register(sock, selectors.EVENT_READ, ("conn", st))
                else:
                    # One misbehaving connection must never kill the
                    # selector thread (that would deafen the whole
                    # control plane): any unexpected error drops just
                    # that connection.
                    try:
                        self._service(conn, unidentified)
                    except Exception:
                        import traceback as _tb
                        _tb.print_exc()
                        self._drop(conn, unidentified)

    def _service(self, conn: _ConnState, unidentified: dict) -> None:
        try:
            data = conn.sock.recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            self._drop(conn, unidentified)
            return
        try:
            frames = conn.feed(data)  # enforces the auth preamble
        except CodecError:
            self._drop(conn, unidentified)
            return
        if conn.rank is not None and not conn.registered:
            self._register(conn, unidentified)
        if not conn.registered:
            return
        for frame in frames:
            try:
                msg = decode(frame, allow_pickle=self._allow_pickle)
            except CodecError:
                continue
            # A handler bug on ONE message must neither kill the
            # selector thread nor cost the rank its (healthy)
            # connection — log and move to the next frame.
            try:
                self.on_message(conn.rank, msg)
            except Exception:
                import traceback as _tb
                _tb.print_exc()

    def _register(self, conn: "_ConnState", unidentified: dict) -> None:
        conn.registered = True
        unidentified.pop(conn.sock, None)
        with self._lock:
            old = self._conns.get(conn.rank)
            self._conns[conn.rank] = conn
        if old is not None:
            # Replaced by a reconnect: detach the stale socket from
            # the selector too, and mark it non-current so a late
            # EOF on it does not fire on_disconnect for a live rank.
            old.rank = None
            try:
                self._sel.unregister(old.sock)
            except (KeyError, ValueError):
                pass
            try:
                old.sock.close()
            except OSError:
                pass
        self.on_connect(conn.rank)

    def _drop(self, conn: _ConnState, unidentified: dict) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        unidentified.pop(conn.sock, None)
        if conn.rank is not None:
            with self._lock:
                is_current = self._conns.get(conn.rank) is conn
                if is_current:
                    del self._conns[conn.rank]
            # Only report disconnect for the rank's *current* connection —
            # a late EOF on a connection already replaced by a reconnect
            # must not mark the live worker dead.
            if is_current:
                self.on_disconnect(conn.rank)


class WorkerChannel:
    """Worker-side control-plane connection (ZMQ-DEALER analog,
    reference: worker.py:154-157).

    ``recv()`` is blocking and intended for the worker's serial message
    loop (reference: worker.py:200-246); ``send()`` is thread-safe so the
    stdout streamer and heartbeat thread can push concurrently
    (reference: worker.py:43 uses a lock for the same reason).
    """

    def __init__(self, host: str, port: int, rank: int, *,
                 allow_pickle: bool = True, connect_timeout: float = 30.0,
                 auth_token: str | None = None):
        self.rank = rank
        self._allow_pickle = allow_pickle
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        _set_keepalive(self._sock)
        self._wlock = threading.Lock()
        self._rbuf = bytearray()
        # Chaos hook (resilience/faults.py), mirroring the listener's:
        # outgoing frames (replies, stream output, pings) pass through
        # the plan when set.  The HELLO preamble below deliberately
        # bypasses it — an unattached worker is a bring-up problem, not
        # a chaos scenario.
        self.fault_plan = None
        # Link-shaping labels (ISSUE 6): which host this process lives
        # on and which host the coordinator lives on.  When a fault
        # plan declares the pair partitioned, send() SEVERS the
        # connection and raises — emulating the keepalive teardown a
        # real blackholed link ends in — so the worker's orphan
        # machinery engages exactly as it would on real hardware.
        self.local_host: str | None = None
        self.peer_host: str | None = None
        with self._wlock:
            # The authenticated preamble variant when the coordinator
            # requires the shared secret (non-loopback binds).
            self._sock.sendall(make_preamble(rank, auth_token))

    def _send_frame(self, frame: bytes) -> None:
        with self._wlock:
            self._sock.sendall(frame)

    def send(self, msg: Message) -> None:
        frame = encode(msg, allow_pickle=self._allow_pickle)

        def _tx(f: bytes) -> None:
            # Count actual writes (see CoordinatorListener._transmit).
            self._send_frame(f)
            hook = wire_hook()
            if hook is not None:
                hook("tx", msg.msg_type, len(f))

        plan = self.fault_plan
        if plan is not None:
            if plan.has_links() and self.local_host:
                if plan.link_blocked(self.local_host, self.peer_host):
                    # Injected partition: tear the stream the way TCP
                    # keepalive would on a real blackholed link, then
                    # surface it — the recv side sees EOF and enters
                    # the orphan machinery.
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    raise TransportError(
                        "link partitioned (injected fault)")
                plan.link_transmit(self.local_host, self.peer_host,
                                   frame, _tx, kind=msg.msg_type)
            else:
                plan.transmit(frame, _tx, kind=msg.msg_type)
        else:
            _tx(frame)

    def recv(self, timeout: float | None = None, *,
             gate=None) -> Message:
        """Block until one complete frame arrives; raise TransportError on
        EOF (coordinator gone), TimeoutError on timeout.

        The timeout is implemented with ``select`` rather than
        ``settimeout`` so the socket object's blocking mode is never
        mutated — concurrent ``send()`` from the stdout-streamer or
        heartbeat thread must not inherit a read deadline mid-write.

        ``gate`` (worker main-thread loop): an
        :class:`~nbdistributed_tpu.runtime.interrupt.InterruptGate`
        scoping SIGINT to the ``select`` wait, where no byte has been
        consumed — received bytes always reach ``_rbuf`` (partial
        frames persist across calls), so an interrupt can never desync
        the stream.  A KI between ``sock.recv`` returning and the
        buffer append would otherwise silently drop those bytes: the
        next frame parse then reads garbage, the worker tears the
        connection down, and the coordinator declares a perfectly alive
        worker dead.  Outside the gate's window the handler records the
        signal as pending (PEP 475 then restarts the interrupted
        syscall), so byte consumption is atomic with respect to
        interrupts no matter which OS thread received the signal.
        """
        import select as _select
        import time as _time

        use_gate = gate is not None and gate.main_thread()
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            n = frame_ready(self._rbuf)
            if n:
                frame = bytes(self._rbuf[:n])
                del self._rbuf[:n]
                return decode(frame, allow_pickle=self._allow_pickle)
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("recv timed out")
            else:
                remaining = None
            try:
                if use_gate:
                    # KI may propagate from this block (pending
                    # delivered at window entry, or SIGINT during the
                    # wait) — nothing has been consumed yet, so the
                    # stream stays in sync.
                    with gate.window():
                        readable, _, _ = _select.select([self._sock], [],
                                                        [], remaining)
                elif deadline is not None:
                    readable, _, _ = _select.select([self._sock], [], [],
                                                    remaining)
                else:
                    readable = [self._sock]
                if not readable:
                    raise TimeoutError("recv timed out")
                data = self._sock.recv(1 << 20)
            except TimeoutError:
                raise  # a timeout is not a dead socket (OSError subclass!)
            except (OSError, ValueError) as e:
                # The socket died under us — a peer reset, or our own
                # send path severed it (injected link partition).  Both
                # mean "coordinator unreachable": surface the one error
                # the worker loop's orphan machinery handles.
                raise TransportError(
                    f"connection lost: {type(e).__name__}: {e}") from e
            if not data:
                raise TransportError("coordinator closed connection")
            self._rbuf.extend(data)

    def close(self) -> None:
        # shutdown() before close(): closing an fd does NOT wake a
        # thread blocked in an untimed recv() on it (the classic
        # close-vs-blocked-reader race — the TenantClient reader
        # would hang past its close() join without this); SHUT_RDWR
        # delivers EOF to the blocked recv immediately.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
