"""Control-plane messaging: wire codec, sockets transport, coordinator.

Layer L2 of the architecture (SURVEY §1) — coordinator↔worker request/
response with streaming push, rebuilt from the reference's ZMQ+pickle
design (reference: communication.py) on plain TCP with a safe codec.
"""

from .codec import COORDINATOR_RANK, CodecError, Message, decode, encode
from .coordinator import CommunicationManager, WorkerDied
from .transport import CoordinatorListener, TransportError, WorkerChannel

__all__ = [
    "COORDINATOR_RANK", "CodecError", "Message", "decode", "encode",
    "CommunicationManager", "WorkerDied",
    "CoordinatorListener", "TransportError", "WorkerChannel",
]
