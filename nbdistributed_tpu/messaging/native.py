"""ctypes bindings for the native (C++) control-plane listener.

Loads ``native/libnbdtransport.so`` (built by ``native/build.sh``) and
wraps it in :class:`NativeCoordinatorListener`, interface-compatible with
the pure-Python :class:`~nbdistributed_tpu.messaging.transport.
CoordinatorListener`.  Selection:

* ``NBD_NATIVE=0`` forces pure Python;
* ``NBD_NATIVE=1`` requires the native lib (raises if unbuilt);
* unset: native if the library is present, else Python.

The C side owns sockets, epoll, framing, and identity routing; a single
Python dispatch thread pops whole events (connect / disconnect /
complete frames) and runs the same callbacks the Python listener does —
no C→Python reentrancy, and the GIL is released for the duration of
every native call.
"""

from __future__ import annotations

import ctypes
import os
import threading

from ..utils import knobs
from .codec import CodecError, decode, encode

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native",
    "libnbdtransport.so")

_EVENT_MESSAGE, _EVENT_CONNECT, _EVENT_DISCONNECT = 0, 1, 2

_lib = None


def _build_library() -> None:
    """Compile the native listener on first use in a fresh checkout.

    The .so is a build artifact (not committed); build.sh is a one-file
    g++ invocation, so building lazily keeps `pip install -e . && pytest`
    working without a separate build step.
    """
    src_dir = os.path.dirname(_LIB_PATH)
    script = os.path.join(src_dir, "build.sh")
    if not os.path.exists(script):
        return
    import subprocess
    subprocess.run(["sh", script], check=True, capture_output=True,
                   timeout=120)


def load_library():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        try:
            _build_library()
        except Exception:
            pass
    lib = ctypes.CDLL(_LIB_PATH)
    lib.nbd_listener_create.restype = ctypes.c_void_p
    lib.nbd_listener_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_int)]
    try:
        lib.nbd_listener_create_auth.restype = ctypes.c_void_p
        lib.nbd_listener_create_auth.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int)]
    except AttributeError:
        pass  # stale pre-auth .so; make_listener falls back for auth
    lib.nbd_listener_poll.restype = ctypes.c_int
    lib.nbd_listener_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.nbd_listener_send.restype = ctypes.c_int
    lib.nbd_listener_send.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                      ctypes.c_char_p, ctypes.c_uint64]
    lib.nbd_listener_ranks.restype = ctypes.c_int
    lib.nbd_listener_ranks.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_int32),
                                       ctypes.c_int]
    lib.nbd_listener_close.restype = None
    lib.nbd_listener_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    if knobs.get_str("NBD_NATIVE") == "0":
        return False
    try:
        load_library()
        return True
    except OSError:
        if knobs.get_str("NBD_NATIVE") == "1":
            raise
        return False


class NativeCoordinatorListener:
    """Drop-in replacement for the Python CoordinatorListener backed by
    the C++ epoll listener."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 allow_pickle: bool = True, auth_token: str | None = None):
        self._allow_pickle = allow_pickle
        self._lib = load_library()
        out_port = ctypes.c_int(0)
        if auth_token is not None:
            if not hasattr(self._lib, "nbd_listener_create_auth"):
                raise OSError(
                    "native listener library predates the "
                    "authenticated preamble; rebuild with "
                    "native/build.sh")
            from .transport import token_digest
            self._handle = self._lib.nbd_listener_create_auth(
                host.encode(), port, token_digest(auth_token),
                ctypes.byref(out_port))
        else:
            self._handle = self._lib.nbd_listener_create(
                host.encode(), port, ctypes.byref(out_port))
        if not self._handle:
            raise OSError(f"native listener failed to bind {host}:{port}")
        self.host, self.port = host, out_port.value
        self._running = False
        self._thread: threading.Thread | None = None
        self.on_message = lambda r, m: None
        self.on_connect = lambda r: None
        self.on_disconnect = lambda r: None
        # Chaos hook (resilience/faults.py) — applied in this Python
        # wrapper so fault injection behaves identically over the C++
        # and pure-Python transports.  host_of_rank/local_host feed the
        # per-link shaping exactly like the Python listener's.
        self.fault_plan = None
        self.host_of_rank: dict[int, str] = {}
        self.local_host: str = "local"

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._dispatch,
                                        name="nbd-native-dispatch",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=2)
        handle, self._handle = self._handle, None
        if handle:
            self._lib.nbd_listener_close(handle)

    def connected_ranks(self) -> list[int]:
        if not self._handle:
            return []
        buf = (ctypes.c_int32 * 4096)()
        n = self._lib.nbd_listener_ranks(self._handle, buf, 4096)
        return sorted(buf[i] for i in range(n))

    def send_to_rank(self, rank: int, msg) -> None:
        frame = encode(msg, allow_pickle=self._allow_pickle)
        self._send_frame(rank, frame, msg.msg_type)

    def send_to_ranks(self, ranks: list[int], msg) -> None:
        from .transport import TransportError
        frame = encode(msg, allow_pickle=self._allow_pickle)
        missing = [r for r in ranks
                   if self._transmit(r, frame, msg.msg_type) != 0]
        if missing:
            raise TransportError(f"ranks {missing} are not connected")

    def _send_frame(self, rank: int, frame: bytes, kind: str) -> None:
        from .transport import TransportError
        if self._transmit(rank, frame, kind) != 0:
            raise TransportError(f"rank {rank} is not connected")

    def _transmit(self, rank: int, frame: bytes, kind: str) -> int:
        plan = self.fault_plan
        if plan is None:
            return self._send_accounted(rank, frame, kind)
        rcs: list[int] = []
        if plan.has_links():
            plan.link_transmit(
                self.local_host, self.host_of_rank.get(rank), frame,
                lambda f: rcs.append(self._send_accounted(rank, f, kind)),
                kind=kind)
            return rcs[-1] if rcs else 0
        plan.transmit(
            frame,
            lambda f: rcs.append(self._send_accounted(rank, f, kind)),
            kind=kind)
        # A dropped frame never touched the socket: report success —
        # under chaos, loss is the point, and the retry layer owns
        # recovery.
        return rcs[-1] if rcs else 0

    def _send_accounted(self, rank: int, frame: bytes, kind: str) -> int:
        rc = self._try_send(rank, frame)
        if rc == 0:
            # tx accounting on the actual (successful) socket write,
            # mirroring the Python transport's per-rank counting.
            from .codec import wire_hook
            hook = wire_hook()
            if hook is not None:
                hook("tx", kind, len(frame))
        return rc

    def _try_send(self, rank: int, frame: bytes) -> int:
        if not self._handle:
            return -1
        return self._lib.nbd_listener_send(self._handle, rank, frame,
                                           len(frame))

    def _dispatch(self) -> None:
        etype = ctypes.c_int32()
        rank = ctypes.c_int32()
        data = ctypes.POINTER(ctypes.c_uint8)()
        size = ctypes.c_uint64()
        while self._running and self._handle:
            rc = self._lib.nbd_listener_poll(
                self._handle, 200, ctypes.byref(etype), ctypes.byref(rank),
                ctypes.byref(data), ctypes.byref(size))
            if rc < 0:
                return
            if rc == 0:
                continue
            try:
                if etype.value == _EVENT_CONNECT:
                    self.on_connect(rank.value)
                elif etype.value == _EVENT_DISCONNECT:
                    self.on_disconnect(rank.value)
                else:
                    frame = ctypes.string_at(data, size.value)
                    try:
                        msg = decode(frame,
                                     allow_pickle=self._allow_pickle)
                    except CodecError:
                        continue
                    self.on_message(rank.value, msg)
            except Exception:
                # Callbacks must not kill the dispatch thread, but a
                # swallowed bug here would surface only as a hang —
                # make it loud (the Python listener would crash its IO
                # thread loudly in the same situation).
                import traceback
                traceback.print_exc()


def make_listener(host: str = "127.0.0.1", port: int = 0, *,
                  allow_pickle: bool = True, auth_token: str | None = None):
    """Listener factory honoring NBD_NATIVE (see module docstring).

    Both listeners implement the shared-secret preamble.  An auth
    world on a stale .so (no create_auth export) falls back to Python
    with a loud warning — or raises under NBD_NATIVE=1, which promises
    the native listener — never by silently accepting unauthenticated
    peers.
    """
    if available():
        stale_for_auth = (auth_token is not None
                          and not hasattr(load_library(),
                                          "nbd_listener_create_auth"))
        if not stale_for_auth:
            return NativeCoordinatorListener(host, port,
                                             allow_pickle=allow_pickle,
                                             auth_token=auth_token)
        if knobs.get_str("NBD_NATIVE") == "1":
            raise OSError(
                "NBD_NATIVE=1 but libnbdtransport.so predates the "
                "authenticated preamble; rebuild with native/build.sh")
        import sys
        print("[nbd] native listener predates the authenticated "
              "preamble; using the Python listener (rebuild with "
              "native/build.sh)", file=sys.stderr)
    from .transport import CoordinatorListener
    return CoordinatorListener(host, port, allow_pickle=allow_pickle,
                               auth_token=auth_token)
