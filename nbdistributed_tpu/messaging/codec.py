"""Wire codec for the control plane.

The reference serializes every control-plane message with blind ``pickle``
(reference: communication.py:249, worker.py:203), which is both a trust
boundary problem and awkward for tensors.  This codec replaces it with a
length-delimited binary frame whose header is JSON and whose payload is a
sequence of raw binary buffers (ndarrays carry explicit dtype/shape
metadata, so JAX/NumPy arrays cross the wire zero-copy-ish and safely).
Arbitrary Python objects are still supported — via an explicit, flagged
pickle encoding that can be disabled per-channel (``allow_pickle=False``)
without losing any of the framework's own message types, which are all
JSON + buffers.

Frame layout (all integers little-endian):

    magic   4 bytes  b"NBD1"
    hlen    u32      header length in bytes
    plen    u64      payload length in bytes
    header  hlen     UTF-8 JSON object
    payload plen     concatenated buffers, in header["bufs"] order

Header schema::

    {
      "id":   str,      # correlation id (uuid4 hex)
      "type": str,      # message type, e.g. "execute", "response"
      "rank": int,      # sender rank; -1 = coordinator
      "ts":   float,    # sender wall-clock
      "data": ...,      # JSON-able body (absent if enc == "pickle")
      "enc":  "json" | "pickle",
      "bufs": [{"name": str, "kind": "ndarray"|"bytes",
                "dtype": str, "shape": [int...], "len": int}, ...]
    }
"""

from __future__ import annotations

import io
import json
import pickle
import struct
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

import numpy as np

MAGIC = b"NBD1"
_HEADER_FMT = "<4sIQ"
HEADER_SIZE = struct.calcsize(_HEADER_FMT)  # 16 bytes

# Coordinator sentinel rank (reference: communication.py:44 uses -1 too).
COORDINATOR_RANK = -1

# Base frame-header keys: always present, the original wire schema.
BASE_HEADER_KEYS = frozenset(
    {"id", "type", "rank", "ts", "data", "enc", "bufs"})

# The one registry of OPTIONAL wire extensions — every field that can
# ride the wire beyond the base schema is declared here, and the
# static self-lint (analysis/selfcheck.py) verifies this table against
# the code: the ``header``-plane keys must match exactly what
# :func:`encode` conditionally emits and :func:`decode` reads, and the
# ``ping``-plane keys must match what the worker's heartbeat thread
# piggybacks into a ping's ``data`` dict (runtime/worker.py) for the
# coordinator/watchdog to read.  Adding a field in only one place
# fails ``nbd-lint --self`` in CI instead of silently desyncing the
# two ends of the wire.
WIRE_EXTENSIONS: dict[str, dict] = {
    # frame-header plane (encode/decode below)
    "at": {"plane": "header", "attr": "attempt",
           "doc": "delivery attempt (>0 only on retry redeliveries)"},
    "tr": {"plane": "header", "attr": "trace",
           "doc": "span context while a %dist_trace is active"},
    "ep": {"plane": "header", "attr": "epoch",
           "doc": "session epoch stamp (durable-session fencing)"},
    "tn": {"plane": "header", "attr": "tenant",
           "doc": "tenant tag (gateway pools: routes the request to "
                  "the tenant's worker-side namespace and attributes "
                  "its flight/span records)"},
    "lt": {"plane": "header", "attr": "latency",
           "doc": "latency-observatory stage stamps: 1 on a request "
                  "asks the worker to stamp; the reply carries "
                  "{dq,xs,xe,cs,rs} worker-clock stamps (dequeue, "
                  "handler entry/exit, compile seconds, reply build) "
                  "— absent unless NBD_LAT is on"},
    "xf": {"plane": "header", "attr": "xfer",
           "doc": "bulk-transfer chunk header (messaging/xfer.py): "
                  "{x: transfer id, s: chunk seq, c: crc32 of the "
                  "raw chunk, e: per-chunk encoding (stored/zlib/"
                  "lz4/zstd), r: raw chunk length} — present only on "
                  "xfer_chunk requests and xfer_read replies; "
                  "non-transfer frames stay byte-identical"},
    # heartbeat-ping data plane (worker _heartbeat → coordinator)
    "busy_type": {"plane": "ping",
                  "doc": "in-flight request type while busy"},
    "busy_tenant": {"plane": "ping",
                    "doc": "tenant whose cell is in flight (gateway "
                           "pools) — the %dist_top tenant column"},
    "busy_s": {"plane": "ping",
               "doc": "seconds busy on the monotonic clock"},
    "busy_id": {"plane": "ping",
                "doc": "in-flight request id (hang watchdog)"},
    "busy_deadline": {"plane": "ping",
                      "doc": "per-cell --deadline budget echo"},
    "col": {"plane": "ping",
            "doc": "collective-progress snapshot (hang watchdog)"},
    "tel": {"plane": "ping",
            "doc": "device telemetry sample (HBM, buffers, compiles)"},
    "srv": {"plane": "ping",
            "doc": "serving-loop telemetry while a DecodeServer is "
                   "live (tokens total, tokens/s, KV-slot occupancy) "
                   "— the %dist_top / pool-status serving columns"},
    "rep": {"plane": "ping",
            "doc": "step-loop progress of an in-flight %%distributed "
                   "--repeat cell (step index, total, last scalar, "
                   "steps/s) — per-step telemetry with one dispatch; "
                   "also collective-progress evidence for the hang "
                   "watchdog (a stepping loop is never a stall)"},
    "tg": {"plane": "ping",
           "doc": "training-integrity guard snapshot while a "
                  "TrainGuard is live (skip count, last audit "
                  "step/verdict, rollback/repair counts, quarantine "
                  "suspects) — the %dist_top guard column and the "
                  "Supervisor's quarantine scan"},
}


class CodecError(Exception):
    """Raised on malformed frames or disallowed encodings."""


# Per-frame accounting hook ``(direction "tx"|"rx", msg_type, nbytes)``
# — installed by observability.metrics.install_wire_hook.  "rx" fires
# here in decode (exactly one decode per received frame); "tx" fires at
# the transports' per-socket writes (a fan-out send writes one encoded
# frame to N sockets, and a chaos plan may drop or duplicate a write —
# encode-time counting would misstate all of those).  One global read
# per frame when unset; the hook must never raise.
_wire_hook = None


def set_wire_hook(hook) -> None:
    global _wire_hook
    _wire_hook = hook


def wire_hook():
    return _wire_hook


def _np_dtype(name: str) -> np.dtype:
    """dtype-from-string that understands ml_dtypes extras (bfloat16 etc.)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


@dataclass
class Message:
    """Control-plane message envelope.

    Mirrors the role of the reference's ``Message`` dataclass
    (reference: communication.py:30-62) with two upgrades: binary buffer
    attachments and an explicit encoding tag instead of ambient pickle.
    """

    msg_type: str
    data: Any = None
    rank: int = COORDINATOR_RANK
    msg_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    timestamp: float = field(default_factory=time.time)
    bufs: dict[str, Any] = field(default_factory=dict)  # name -> ndarray | bytes
    # Delivery attempt, 0 = first send.  A retried request goes out
    # under the SAME msg_id with a bumped attempt, so the worker's
    # replay cache recognizes it and the wire shows which delivery a
    # frame belongs to (debugging dropped-frame chaos runs).
    attempt: int = 0
    # Span context {"tid": trace_id, "sid": span_id} while a trace is
    # active (observability/spans.py), None otherwise.  Like `attempt`,
    # the header field is only emitted when set — untraced frames stay
    # byte-identical to the pre-tracing wire format.
    trace: dict | None = None
    # Session epoch of the sending coordinator (durable sessions).  A
    # reattaching coordinator bumps the manifest epoch and stamps every
    # frame; workers reject frames stamped with an OLDER epoch, so a
    # stale coordinator (the pre-crash kernel, or a second kernel that
    # lost the attach race) can never drive a fleet that has been
    # handed over.  None (the default) is never rejected — unstamped
    # sessions keep the pre-epoch wire format byte-identically.
    epoch: int | None = None
    # Tenant tag (gateway pools, ISSUE 8).  A gateway forwarding a
    # tenant's cell stamps it so the worker executes in that tenant's
    # namespace and attributes flight/span records to it.  None (the
    # default) keeps the single-tenant wire format byte-identical.
    tenant: str | None = None
    # Latency-observatory stage stamps (ISSUE 13).  ``1`` on a request
    # asks the worker's loop to stamp it; the reply carries the
    # worker-clock stamp dict.  None (the default, and always when
    # NBD_LAT=0) keeps the wire format byte-identical — the same
    # absent-when-off contract as ``trace``.
    latency: Any = None
    # Bulk-transfer chunk header (ISSUE 20, messaging/xfer.py):
    # {x: xid, s: seq, c: crc32, e: encoding, r: raw_len} on frames
    # that carry one chunk of a streamed transfer.  None (the default)
    # keeps every non-transfer frame byte-identical.
    xfer: dict | None = None

    def reply(self, msg_type: str = "response", data: Any = None,
              rank: int = COORDINATOR_RANK,
              bufs: dict[str, Any] | None = None) -> "Message":
        """Build a response correlated to this message (echoes msg_id
        and the tenant tag, the pattern at reference:
        worker.py:224-233)."""
        return Message(msg_type=msg_type, data=data, rank=rank,
                       msg_id=self.msg_id, bufs=bufs or {},
                       tenant=self.tenant)


def _json_default(_obj: Any):
    raise TypeError("not JSON-serializable")


def encode(msg: Message, *, allow_pickle: bool = True) -> bytes:
    """Serialize a Message to one wire frame."""
    bufs: list[tuple[str, str, str, list[int], bytes]] = []
    for name, value in msg.bufs.items():
        if isinstance(value, (bytes, bytearray, memoryview)):
            raw = bytes(value)
            bufs.append((name, "bytes", "", [], raw))
        else:
            arr = np.asarray(value)
            if not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr)
            bufs.append((name, "ndarray", arr.dtype.name, list(arr.shape),
                         arr.tobytes()))

    header: dict[str, Any] = {
        "id": msg.msg_id,
        "type": msg.msg_type,
        "rank": msg.rank,
        "ts": msg.timestamp,
    }
    if msg.attempt:
        # Only on redeliveries: first-send frames stay byte-identical
        # to the pre-retry wire format.
        header["at"] = msg.attempt
    if msg.trace:
        # Only while a trace is active (near-zero overhead when off).
        header["tr"] = msg.trace
    if msg.epoch is not None:
        # Only for epoch-stamped (durable) sessions.
        header["ep"] = msg.epoch
    if msg.tenant is not None:
        # Only for tenant-tagged (gateway pool) traffic.
        header["tn"] = msg.tenant
    if msg.latency is not None:
        # Only while the latency observatory is on.
        header["lt"] = msg.latency
    if msg.xfer is not None:
        # Only on bulk-transfer chunk frames.
        header["xf"] = msg.xfer

    header["data"] = msg.data
    header["enc"] = "json"
    header["bufs"] = [
        {"name": n, "kind": k, "dtype": d, "shape": s, "len": len(raw)}
        for (n, k, d, s, raw) in bufs
    ]
    try:
        hbytes = json.dumps(header, default=_json_default).encode("utf-8")
    except TypeError:
        if not allow_pickle:
            raise CodecError(
                f"message data of type {type(msg.data).__name__} is not "
                "JSON-serializable and pickle is disabled on this channel")
        del header["data"]
        header["enc"] = "pickle"
        pickled = pickle.dumps(msg.data, protocol=pickle.HIGHEST_PROTOCOL)
        bufs.append(("__pickle__", "bytes", "", [], pickled))
        header["bufs"].append({"name": "__pickle__", "kind": "bytes",
                               "dtype": "", "shape": [], "len": len(pickled)})
        hbytes = json.dumps(header).encode("utf-8")
    payload = b"".join(raw for (_, _, _, _, raw) in bufs)
    out = io.BytesIO()
    out.write(struct.pack(_HEADER_FMT, MAGIC, len(hbytes), len(payload)))
    out.write(hbytes)
    out.write(payload)
    return out.getvalue()


def decode(frame: bytes | memoryview, *, allow_pickle: bool = True) -> Message:
    """Deserialize one wire frame produced by :func:`encode`."""
    frame = memoryview(frame)
    if len(frame) < HEADER_SIZE:
        raise CodecError("short frame")
    magic, hlen, plen = struct.unpack_from(_HEADER_FMT, frame, 0)
    if magic != MAGIC:
        raise CodecError(f"bad magic {magic!r}")
    if len(frame) != HEADER_SIZE + hlen + plen:
        raise CodecError("frame length mismatch")
    try:
        header = json.loads(bytes(frame[HEADER_SIZE:HEADER_SIZE + hlen]))
    except json.JSONDecodeError as e:
        raise CodecError(f"bad header: {e}") from e

    payload = frame[HEADER_SIZE + hlen:]
    bufs: dict[str, Any] = {}
    off = 0
    pickled: bytes | None = None
    for desc in header.get("bufs", []):
        raw = payload[off:off + desc["len"]]
        off += desc["len"]
        if desc["name"] == "__pickle__":
            pickled = bytes(raw)
            continue
        if desc["kind"] == "ndarray":
            arr = np.frombuffer(raw, dtype=_np_dtype(desc["dtype"]))
            bufs[desc["name"]] = arr.reshape(desc["shape"])
        else:
            bufs[desc["name"]] = bytes(raw)

    enc = header.get("enc", "json")
    if enc == "pickle":
        if not allow_pickle:
            raise CodecError("received pickle-encoded message on a channel "
                             "with pickle disabled")
        if pickled is None:
            raise CodecError("pickle-encoded message missing payload")
        data = pickle.loads(pickled)
    else:
        data = header.get("data")

    hook = _wire_hook
    if hook is not None:
        hook("rx", header["type"], len(frame))
    return Message(
        msg_type=header["type"],
        data=data,
        rank=header["rank"],
        msg_id=header["id"],
        timestamp=header["ts"],
        bufs=bufs,
        attempt=header.get("at", 0),
        trace=header.get("tr"),
        epoch=header.get("ep"),
        tenant=header.get("tn"),
        latency=header.get("lt"),
        xfer=header.get("xf"),
    )


def flatten_pytree_wire(value: Any) -> tuple[dict, dict]:
    """Flatten a dict/list/tuple pytree of arrays (+ JSON scalars)
    into ``(meta, bufs)`` for the buffer path: the tree structure
    travels as JSON in the message data, the array leaves as raw
    binary buffers — no pickle anywhere, so model/optimizer state
    crosses ``allow_pickle=False`` channels intact.

    ``meta`` is a recursive ``{"k": kind, ...}`` description; leaves
    record whether they were JAX arrays so the receiving side can
    rebuild them on-device.  Raises TypeError for values that are not
    such a pytree (an unknown leaf type, non-string dict keys, or no
    array leaves at all) — callers fall back to the plain JSON or
    explicit-pickle paths.
    """
    values: dict[str, Any] = {}
    jax_names: list[str] = []

    def rec(v):
        # Exact container types only: a NamedTuple, OrderedDict, or
        # other subclass would be silently flattened to the base type
        # and come back structurally wrong (optax states are
        # NamedTuples) — those keep the explicit-pickle fallback.
        if type(v) is dict:
            if not all(isinstance(k, str) for k in v):
                raise TypeError("pytree wire needs string dict keys")
            return {"k": "dict",
                    "items": [[k, rec(x)] for k, x in v.items()]}
        if type(v) in (list, tuple):
            return {"k": "list" if type(v) is list else "tuple",
                    "items": [rec(x) for x in v]}
        if isinstance(v, np.generic):
            # numpy scalars keep their exact type across the wire (a
            # 0-d ndarray would silently change isinstance checks /
            # hashability after one round-trip).  Checked BEFORE the
            # plain-python branch: np.float64 subclasses float and
            # would otherwise silently decay to a python float.  Only
            # JSON-safe kinds ride the meta; complex/datetime/bytes_
            # scalars fall through to the buffer path (as 0-d arrays —
            # their .item() would break the JSON header).
            if (isinstance(v, (np.bool_, np.integer, np.floating))
                    and not isinstance(v, np.timedelta64)):
                # (timedelta64 subclasses signedinteger but .item()
                # yields datetime.timedelta — not JSON; buffer path.)
                return {"k": "npscalar", "dtype": v.dtype.name,
                        "v": v.item()}
        if v is None or isinstance(v, (bool, int, float, str)):
            return {"k": "json", "v": v}
        mod = type(v).__module__
        if (isinstance(v, (np.ndarray, np.generic))    # np.generic:
                # non-JSON scalar kinds (complex, datetime, ml_dtypes
                # like bfloat16) ride as 0-d buffers
                or mod.startswith(("jax", "numpy"))):
            if isinstance(v, np.ndarray) and type(v) is not np.ndarray:
                # MaskedArray, np.matrix, … — np.asarray would strip
                # subclass state (masks!) silently; keep them on the
                # explicit-pickle path like subclassed containers.
                raise TypeError(
                    f"ndarray subclass {type(v).__name__} cannot cross "
                    f"the buffer path without losing state")
            arr = v if isinstance(v, np.ndarray) else None
            if arr is not None and arr.dtype.hasobject:
                # np.random.Generator, dtype objects, object arrays …
                # have no raw-bytes representation.
                raise TypeError("object-dtype leaf cannot cross the "
                                "buffer path")
            name = f"pt{len(values)}"
            is_jax = mod.startswith("jax")
            if is_jax and not hasattr(v, "dtype"):
                raise TypeError(f"not a pytree-wire leaf: "
                                f"{type(v).__name__}")
            values[name] = v
            if is_jax:
                jax_names.append(name)
            return {"k": "leaf", "buf": name, "jax": is_jax}
        raise TypeError(f"not a pytree-wire leaf: {type(v).__name__}")

    meta = rec(value)
    if not values:
        # Pure-JSON values don't need the buffer path at all.
        raise TypeError("pytree has no array leaves")
    if jax_names:
        # One batched device_get for all JAX leaves — per-leaf
        # np.asarray would serialize a D2H transfer per leaf.
        import jax

        fetched = jax.device_get([values[n] for n in jax_names])
        values.update(zip(jax_names, fetched))
    bufs: dict[str, Any] = {}
    for name, v in values.items():
        arr = np.asarray(v)
        if arr.dtype.hasobject:
            raise TypeError("object-dtype leaf cannot cross the "
                            "buffer path")
        bufs[name] = arr
    return meta, bufs


def unflatten_pytree_wire(meta: dict, bufs: dict, leaf_fn=None) -> Any:
    """Rebuild the value from :func:`flatten_pytree_wire` output.
    ``leaf_fn(arr, is_jax)`` converts each leaf — pass e.g.
    ``lambda a, j: jnp.asarray(a) if j else a`` to put JAX leaves
    back on device.  The default COPIES each leaf: decoded buffers
    are read-only ``frombuffer`` views, and a pulled/pushed tree must
    be mutable like any other value."""
    leaf_fn = leaf_fn or (lambda arr, is_jax: np.array(arr))

    def rec(m):
        k = m["k"]
        if k == "dict":
            return {key: rec(sub) for key, sub in m["items"]}
        if k == "list":
            return [rec(x) for x in m["items"]]
        if k == "tuple":
            return tuple(rec(x) for x in m["items"])
        if k == "json":
            return m["v"]
        if k == "npscalar":
            # _np_dtype: ml_dtypes scalar kinds (bfloat16, float8_*)
            # are not plain np.dtype names.
            return _np_dtype(m["dtype"]).type(m["v"])
        return leaf_fn(bufs[m["buf"]], m.get("jax", False))

    return rec(meta)


def frame_ready(buf: bytes | bytearray | memoryview) -> int:
    """Return total frame size if ``buf`` starts with a complete frame,
    else 0.  Used by incremental socket readers."""
    if len(buf) < HEADER_SIZE:
        return 0
    magic, hlen, plen = struct.unpack_from(_HEADER_FMT, memoryview(buf), 0)
    if magic != MAGIC:
        raise CodecError(f"bad magic {magic!r}")
    total = HEADER_SIZE + hlen + plen
    return total if len(buf) >= total else 0
