"""Wire codec for the control plane.

The reference serializes every control-plane message with blind ``pickle``
(reference: communication.py:249, worker.py:203), which is both a trust
boundary problem and awkward for tensors.  This codec replaces it with a
length-delimited binary frame whose header is JSON and whose payload is a
sequence of raw binary buffers (ndarrays carry explicit dtype/shape
metadata, so JAX/NumPy arrays cross the wire zero-copy-ish and safely).
Arbitrary Python objects are still supported — via an explicit, flagged
pickle encoding that can be disabled per-channel (``allow_pickle=False``)
without losing any of the framework's own message types, which are all
JSON + buffers.

Frame layout (all integers little-endian):

    magic   4 bytes  b"NBD1"
    hlen    u32      header length in bytes
    plen    u64      payload length in bytes
    header  hlen     UTF-8 JSON object
    payload plen     concatenated buffers, in header["bufs"] order

Header schema::

    {
      "id":   str,      # correlation id (uuid4 hex)
      "type": str,      # message type, e.g. "execute", "response"
      "rank": int,      # sender rank; -1 = coordinator
      "ts":   float,    # sender wall-clock
      "data": ...,      # JSON-able body (absent if enc == "pickle")
      "enc":  "json" | "pickle",
      "bufs": [{"name": str, "kind": "ndarray"|"bytes",
                "dtype": str, "shape": [int...], "len": int}, ...]
    }
"""

from __future__ import annotations

import io
import json
import pickle
import struct
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

import numpy as np

MAGIC = b"NBD1"
_HEADER_FMT = "<4sIQ"
HEADER_SIZE = struct.calcsize(_HEADER_FMT)  # 16 bytes

# Coordinator sentinel rank (reference: communication.py:44 uses -1 too).
COORDINATOR_RANK = -1


class CodecError(Exception):
    """Raised on malformed frames or disallowed encodings."""


def _np_dtype(name: str) -> np.dtype:
    """dtype-from-string that understands ml_dtypes extras (bfloat16 etc.)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


@dataclass
class Message:
    """Control-plane message envelope.

    Mirrors the role of the reference's ``Message`` dataclass
    (reference: communication.py:30-62) with two upgrades: binary buffer
    attachments and an explicit encoding tag instead of ambient pickle.
    """

    msg_type: str
    data: Any = None
    rank: int = COORDINATOR_RANK
    msg_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    timestamp: float = field(default_factory=time.time)
    bufs: dict[str, Any] = field(default_factory=dict)  # name -> ndarray | bytes

    def reply(self, msg_type: str = "response", data: Any = None,
              rank: int = COORDINATOR_RANK,
              bufs: dict[str, Any] | None = None) -> "Message":
        """Build a response correlated to this message (echoes msg_id,
        the pattern at reference: worker.py:224-233)."""
        return Message(msg_type=msg_type, data=data, rank=rank,
                       msg_id=self.msg_id, bufs=bufs or {})


def _json_default(_obj: Any):
    raise TypeError("not JSON-serializable")


def encode(msg: Message, *, allow_pickle: bool = True) -> bytes:
    """Serialize a Message to one wire frame."""
    bufs: list[tuple[str, str, str, list[int], bytes]] = []
    for name, value in msg.bufs.items():
        if isinstance(value, (bytes, bytearray, memoryview)):
            raw = bytes(value)
            bufs.append((name, "bytes", "", [], raw))
        else:
            arr = np.asarray(value)
            if not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr)
            bufs.append((name, "ndarray", arr.dtype.name, list(arr.shape),
                         arr.tobytes()))

    header: dict[str, Any] = {
        "id": msg.msg_id,
        "type": msg.msg_type,
        "rank": msg.rank,
        "ts": msg.timestamp,
    }

    header["data"] = msg.data
    header["enc"] = "json"
    header["bufs"] = [
        {"name": n, "kind": k, "dtype": d, "shape": s, "len": len(raw)}
        for (n, k, d, s, raw) in bufs
    ]
    try:
        hbytes = json.dumps(header, default=_json_default).encode("utf-8")
    except TypeError:
        if not allow_pickle:
            raise CodecError(
                f"message data of type {type(msg.data).__name__} is not "
                "JSON-serializable and pickle is disabled on this channel")
        del header["data"]
        header["enc"] = "pickle"
        pickled = pickle.dumps(msg.data, protocol=pickle.HIGHEST_PROTOCOL)
        bufs.append(("__pickle__", "bytes", "", [], pickled))
        header["bufs"].append({"name": "__pickle__", "kind": "bytes",
                               "dtype": "", "shape": [], "len": len(pickled)})
        hbytes = json.dumps(header).encode("utf-8")
    payload = b"".join(raw for (_, _, _, _, raw) in bufs)
    out = io.BytesIO()
    out.write(struct.pack(_HEADER_FMT, MAGIC, len(hbytes), len(payload)))
    out.write(hbytes)
    out.write(payload)
    return out.getvalue()


def decode(frame: bytes | memoryview, *, allow_pickle: bool = True) -> Message:
    """Deserialize one wire frame produced by :func:`encode`."""
    frame = memoryview(frame)
    if len(frame) < HEADER_SIZE:
        raise CodecError("short frame")
    magic, hlen, plen = struct.unpack_from(_HEADER_FMT, frame, 0)
    if magic != MAGIC:
        raise CodecError(f"bad magic {magic!r}")
    if len(frame) != HEADER_SIZE + hlen + plen:
        raise CodecError("frame length mismatch")
    try:
        header = json.loads(bytes(frame[HEADER_SIZE:HEADER_SIZE + hlen]))
    except json.JSONDecodeError as e:
        raise CodecError(f"bad header: {e}") from e

    payload = frame[HEADER_SIZE + hlen:]
    bufs: dict[str, Any] = {}
    off = 0
    pickled: bytes | None = None
    for desc in header.get("bufs", []):
        raw = payload[off:off + desc["len"]]
        off += desc["len"]
        if desc["name"] == "__pickle__":
            pickled = bytes(raw)
            continue
        if desc["kind"] == "ndarray":
            arr = np.frombuffer(raw, dtype=_np_dtype(desc["dtype"]))
            bufs[desc["name"]] = arr.reshape(desc["shape"])
        else:
            bufs[desc["name"]] = bytes(raw)

    enc = header.get("enc", "json")
    if enc == "pickle":
        if not allow_pickle:
            raise CodecError("received pickle-encoded message on a channel "
                             "with pickle disabled")
        if pickled is None:
            raise CodecError("pickle-encoded message missing payload")
        data = pickle.loads(pickled)
    else:
        data = header.get("data")

    return Message(
        msg_type=header["type"],
        data=data,
        rank=header["rank"],
        msg_id=header["id"],
        timestamp=header["ts"],
        bufs=bufs,
    )


def frame_ready(buf: bytes | bytearray | memoryview) -> int:
    """Return total frame size if ``buf`` starts with a complete frame,
    else 0.  Used by incremental socket readers."""
    if len(buf) < HEADER_SIZE:
        return 0
    magic, hlen, plen = struct.unpack_from(_HEADER_FMT, memoryview(buf), 0)
    if magic != MAGIC:
        raise CodecError(f"bad magic {magic!r}")
    total = HEADER_SIZE + hlen + plen
    return total if len(buf) >= total else 0
