"""Coordinator-side control-plane manager.

Equivalent of the reference's ``CommunicationManager``
(reference: communication.py:65-389), rebuilt on the sockets transport with
three structural fixes called out in SURVEY §7:

1. **Per-request expectation sets.**  The reference's completion Event only
   fires at full world size, forcing the subset path (``send_to_ranks``) to
   busy-poll every 10 ms (reference: communication.py:348-359).  Here every
   request carries its own expected-rank set and its own Event, so targeted
   and broadcast requests share one wait path with no polling.
2. **Fail-fast on worker death.**  With ``timeout=None`` the reference
   blocks forever if a worker dies mid-request
   (reference: communication.py:263-269).  The transport's disconnect
   callback (and the process manager's child monitor, via
   :meth:`mark_worker_dead`) abort all pending requests that still expect
   the dead rank.
3. **Readiness handshake.**  ``wait_for_workers`` observes HELLO
   attachments, replacing the spawn-then-``sleep(2)`` race
   (reference: process_manager.py:136-137).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

from ..gateway.scheduler import (ACTIVE, SHED, CellRejected, CellShed,
                                 Scheduler)
from ..observability import flightrec
from ..observability import metrics as obs_metrics
from ..observability import spans as obs_spans
from ..observability.clock import ClockEstimator
from ..observability.latency import LatencyObservatory
from ..resilience.retry import RetryPolicy, class_of
from ..utils import knobs
from .codec import Message
from .native import make_listener
from .transport import TransportError


# Documented exemptions for the thread-shared-state self-lint
# (analysis/selfcheck.py): attributes with exactly one writer thread
# (or GIL-atomic mutation) that deliberately skip the lock.
_LINT_SINGLE_WRITER = {
    "CommunicationManager._notify_callbacks":
        "registered from the main thread at wiring time only; list "
        "append is atomic under the GIL and the IO thread only "
        "iterates",
}


class WorkerDied(RuntimeError):
    """A worker exited/disconnected while a request was pending on it.

    ``msg_id`` names the pending request that was aborted (when raised
    from one) — the postmortem layer matches it against the dead
    rank's recovered flight ring to find the fatal dispatch."""

    msg_id: str | None = None


class _Pending:
    __slots__ = ("expect", "responses", "event", "failure", "sent_at",
                 "msg_type", "cell_sha1", "tenant", "on_done")

    def __init__(self, expect: set[int], msg_type: str = "",
                 tenant: str | None = None):
        self.msg_type = msg_type
        # Completion hook for ASYNC submissions (ISSUE 14): invoked on
        # the IO thread right after ``event.set()`` so a pipelined
        # cell's future resolves the moment its last reply lands,
        # without a waiter thread per in-flight cell.  None on the
        # synchronous path — wait() then finalizes on the caller
        # thread exactly as before the submit/wait split.
        self.on_done = None
        # Which tenant's cell this is (gateway pools) — lets the hang
        # watchdog / doctor / %dist_top attribute an in-flight request
        # to the right tenant.  None on the single-kernel path.
        self.tenant = tenant
        self.expect = set(expect)
        self.responses: dict[int, Message] = {}
        self.event = threading.Event()
        self.failure: Exception | None = None
        # Wall clock of the FIRST delivery: the t_send of the NTP-style
        # clock samples (observability/clock.py).  Redeliveries do not
        # refresh it — a retried sample just has a big RTT and loses
        # the min-RTT filter.
        self.sent_at: float = 0.0
        # Source hash of an execute request's cell (the same value the
        # worker reports as ``cell_sha1``): lets a hang verdict on this
        # request cite the pre-dispatch lint finding for its cell.
        self.cell_sha1: str | None = None


class PendingHandle:
    """One in-flight request: the submission half of the old blocking
    ``send_to_ranks`` (ISSUE 14 submission/completion split).

    :meth:`CommunicationManager.submit` transmits the request and
    returns this handle immediately; :meth:`wait` drives the retry/
    redelivery schedule and collects the responses — today's blocking
    call is literally ``submit(...).wait()`` on the same code path, so
    the async pipeline and the synchronous magics share every wire,
    scheduler, retry, and latency-stage behavior.

    Completion is terminal and idempotent: whichever of the IO-thread
    ``on_done`` hook (async submissions), a :meth:`wait` caller, or a
    timeout settles first wins; later settlers observe the stored
    result/error.  ``add_done_callback`` fires on (or after) that
    first settle — from the IO thread for event-driven completion, so
    callbacks must be fast and non-blocking.
    """

    def __init__(self, comm: "CommunicationManager", msg: Message,
                 msg_type: str, ranks: list[int], pending: _Pending,
                 ticket, timeout: float | None, deadline: float | None,
                 tenant: str | None, span):
        self._comm = comm
        self.msg = msg
        self.msg_id = msg.msg_id
        self.msg_type = msg_type
        self.ranks = list(ranks)
        self.tenant = tenant
        self._pending = pending
        self._ticket = ticket
        self._timeout = timeout
        self._deadline = deadline
        self._span = span
        self._done_lock = threading.Lock()
        self._terminal = False
        self._result: dict[int, Message] | None = None
        self._error: Exception | None = None
        self._callbacks: list = []

    @classmethod
    def resolved(cls, result: dict) -> "PendingHandle":
        """An already-complete handle (empty rank set — nothing was
        ever on the wire, mirroring the old early ``return {}``)."""
        h = cls.__new__(cls)
        h._comm = None
        h.msg = None
        h.msg_id = None
        h.msg_type = ""
        h.ranks = []
        h.tenant = None
        h._pending = None
        h._ticket = None
        h._timeout = None
        h._deadline = None
        h._span = None
        h._done_lock = threading.Lock()
        h._terminal = True
        h._result = dict(result)
        h._error = None
        h._callbacks = []
        return h

    # ------------------------------------------------------------------

    def done(self) -> bool:
        return self._terminal or self._pending.event.is_set()

    @property
    def error(self) -> Exception | None:
        return self._error

    @property
    def results(self) -> dict[int, Message] | None:
        """The collected rank→reply map after a successful settle,
        None before (or on failure)."""
        return self._result

    def add_done_callback(self, cb) -> None:
        """``cb(handle)`` after the handle settles (immediately when it
        already has).  IO-thread dispatch for event-driven completion."""
        fire = False
        with self._done_lock:
            if self._terminal:
                fire = True
            else:
                self._callbacks.append(cb)
        if fire:
            try:
                cb(self)
            except Exception:
                pass

    # ------------------------------------------------------------------
    # settle paths (each terminal, first one wins)

    def _event_fired(self) -> None:
        """IO-thread hook (``_Pending.on_done``): the expectation set
        completed or a death aborted it — settle from pending state."""
        self._settle_from_pending()

    def _settle_from_pending(self) -> None:
        pending = self._pending
        with self._done_lock:
            if self._terminal:
                return
            if pending.failure is not None:
                self._error = pending.failure
            else:
                with self._comm._lock:
                    self._result = dict(pending.responses)
            self._terminal = True
            cbs, self._callbacks = self._callbacks, []
        self._comm._finish(self, self._error)
        for cb in cbs:
            try:
                cb(self)
            except Exception:
                pass

    def _fail(self, exc: Exception) -> None:
        with self._done_lock:
            if self._terminal:
                return
            self._error = exc
            self._terminal = True
            cbs, self._callbacks = self._callbacks, []
        self._comm._finish(self, exc)
        for cb in cbs:
            try:
                cb(self)
            except Exception:
                pass

    def _outcome(self) -> dict[int, Message]:
        if self._error is not None:
            raise self._error
        return self._result if self._result is not None else {}

    # ------------------------------------------------------------------

    def wait(self, timeout: float | None = ...) -> dict[int, Message]:
        """Collect the responses (the completion half of the old
        ``send_to_ranks``): waits on the expectation set, driving the
        retry/redelivery schedule exactly as the blocking call did.
        ``timeout=...`` keeps the budget given at submit (whose clock
        started THEN — queue time is part of the caller's wait);
        an explicit value restarts the budget from now.  Idempotent:
        a settled handle returns (or re-raises) its stored outcome."""
        if self._terminal:
            return self._outcome()
        comm, msg, pending = self._comm, self.msg, self._pending
        if timeout is ...:
            timeout, deadline = self._timeout, self._deadline
        else:
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
        policy = comm.retry_for(self.msg_type)
        attempts = policy.attempts if policy.enabled() else 1
        complete = False
        try:
            for attempt in range(1, attempts + 1):
                if self._terminal or pending.event.is_set():
                    complete = True
                    break
                if attempt > 1:
                    self._redeliver_missing(attempt - 1)
                if attempt == attempts:
                    step = (None if deadline is None
                            else max(0.0, deadline - time.monotonic()))
                else:
                    step = policy.attempt_wait_s(attempt - 1)
                    if deadline is not None:
                        step = min(step,
                                   max(0.0,
                                       deadline - time.monotonic()))
                complete = pending.event.wait(step)
                if complete:
                    break
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    break
        except BaseException as e:
            # Mirror the pre-split finally blocks: a KeyboardInterrupt
            # (or anything unexpected) escaping the blocking wait must
            # still release the pending-table entry, the trace span,
            # the mesh slot, and the stage record — without this, a
            # Ctrl-C during %sync left a phantom ACTIVE request that
            # wedged every later cell behind the occupied slot.
            if isinstance(e, Exception):
                self._fail(e)
            else:
                self._fail(RuntimeError(
                    f"wait aborted by {type(e).__name__}"))
            raise
        if not complete and not self._terminal \
                and not pending.event.is_set():
            with comm._lock:  # IO thread inserts under the same lock
                got = set(pending.responses)
            missing = sorted(pending.expect - got)
            err = TimeoutError(
                f"no response from ranks {missing} within {timeout}s "
                f"for '{self.msg_type}'"
                + (f" ({attempts} deliveries)" if attempts > 1 else ""))
            self._fail(err)
            raise err
        self._settle_from_pending()
        return self._outcome()

    def _redeliver_missing(self, attempt: int) -> None:
        """One redelivery to the still-missing ranks, same msg_id (the
        worker replay cache makes this idempotent).  Shared by the
        blocking wait's retry schedule and the async window's
        :meth:`pump`."""
        comm, msg, pending = self._comm, self.msg, self._pending
        with comm._lock:
            missing_now = sorted(pending.expect
                                 - set(pending.responses))
        msg.attempt = attempt
        try:
            comm.flight.record("retry", msg_id=msg.msg_id,
                               attempt=msg.attempt,
                               ranks=missing_now)
            comm._listener.send_to_ranks(missing_now, msg)
            with comm._lock:
                # Concurrent senders (a %dist_top reader, two cells
                # in flight) share this counter: the read-modify-
                # write needs the lock.
                comm.retries_sent += 1
                for r in missing_now:
                    comm.retries_by_rank[r] = \
                        comm.retries_by_rank.get(r, 0) + 1
            obs_metrics.registry().counter(
                "nbd_retries_total",
                "request redeliveries transmitted").inc()
        except TransportError:
            pass  # disconnected rank: death callback aborts us

    def pump(self, now: float | None = None) -> None:
        """Non-blocking maintenance for an ASYNC in-flight request
        (ISSUE 14): nobody sits in :meth:`wait` for a windowed cell,
        so without this a lost request would never be redelivered and
        a submit-time deadline would never fire until an unbounded
        drain.  The async executor pumps its in-flight handles from
        its admission-wait and bounded-drain loops: a DUE redelivery
        (per the retry policy's backoff schedule, clocked from
        ``sent_at``) is transmitted, and a blown submit deadline
        fails the handle so its future rejects."""
        if self._terminal or self._pending.event.is_set():
            return
        now = time.monotonic() if now is None else now
        if self._deadline is not None and now >= self._deadline:
            with self._comm._lock:
                got = set(self._pending.responses)
            missing = sorted(self._pending.expect - got)
            self._fail(TimeoutError(
                f"no response from ranks {missing} within "
                f"{self._timeout}s for '{self.msg_type}' "
                f"(async window)"))
            return
        policy = self._comm.retry_for(self.msg_type)
        if not policy.enabled():
            return
        # The next attempt is due when the cumulative backoff since
        # the first transmission has elapsed.
        done_attempts = self.msg.attempt + 1   # deliveries so far
        if done_attempts >= policy.attempts:
            return
        elapsed = time.time() - self._pending.sent_at
        due = sum(policy.attempt_wait_s(i)
                  for i in range(done_attempts))
        if elapsed >= due:
            self._redeliver_missing(done_attempts)


class CommunicationManager:
    """Owns the control-plane listener and request/response correlation."""

    def __init__(self, num_workers: int, *, host: str = "127.0.0.1",
                 port: int = 0, timeout: float | None = None,
                 allow_pickle: bool = True, auth_token: str | None = None,
                 retry: RetryPolicy | None = None,
                 session_token: str | None = None,
                 session_epoch: int = 0,
                 scheduler: Scheduler | None = None):
        self.num_workers = num_workers
        # Mesh scheduler (gateway/scheduler.py): EVERY execute request
        # routes through it — admission, queueing, fair-share (ISSUE
        # 8).  The default is an unlimited-slot FIFO with one implicit
        # tenant, so the single-kernel path dispatches immediately and
        # behaves exactly as before while sharing the gateway's code
        # path (no fork).  A gateway passes a bounded pool policy.
        self.scheduler = scheduler or Scheduler()
        self.default_timeout = timeout  # None = wait forever (training mode)
        self.auth_token = auth_token
        # Durable-session identity (resilience/session.py): when the
        # epoch is nonzero every outgoing request is stamped with it,
        # and workers whose fleet has been handed to a NEWER epoch
        # answer our frames with a stale-coordinator error instead of
        # executing them.  Zero (the default) leaves frames unstamped —
        # the pre-epoch wire format, never rejected.
        self.session_token = session_token
        self.session_epoch = int(session_epoch or 0)
        # Redelivery policy for slow/lost responses (resilience/retry):
        # explicit argument > NBD_RETRY_* env > disabled (the exact
        # pre-retry single-attempt behavior).
        self.retry = (retry if retry is not None
                      else RetryPolicy.from_env() or RetryPolicy())
        # Per-message-class budget overrides (NBD_RETRY_CLASS_*): bulk
        # push/pull/checkpoint frames get a long-haul budget on slow
        # links while control frames keep their tight one (ISSUE 6).
        self.retry_classes = RetryPolicy.classes_from_env(self.retry)
        self.retries_sent = 0  # redeliveries actually transmitted
        self.retries_by_rank: dict[int, int] = {}  # per-rank, for the
        # per-link loss estimate in link_stats()
        # Observability: the process tracer (spans around requests,
        # off until %dist_trace start), per-rank clock offsets fed from
        # response RTTs, and wire-frame accounting into the registry.
        self.tracer = obs_spans.tracer()
        self.clock = ClockEstimator()
        # Latency observatory (ISSUE 13): stage attribution for every
        # completed execute request.  On by default (NBD_LAT=0 turns
        # it off and drops the `lt` wire header entirely); its offsets
        # come from the same clock estimator the trace merge uses.
        self.lat = LatencyObservatory()
        obs_metrics.install_wire_hook()
        # Flight recorder (always on): opening it here also mints the
        # shared run directory and exports NBD_RUN_DIR, so workers
        # spawned after this constructor land their rings next to ours.
        self.flight = flightrec.init("coordinator")
        # Push-based per-rank telemetry: the last few snapshots that
        # rode heartbeat pings (runtime/worker.py piggybacks them) —
        # the postmortem's "last known device state" for a dead rank.
        self._telemetry: dict[int, deque] = {}
        # Native C++ listener when built (see messaging/native.py), the
        # pure-Python selector listener otherwise — same protocol.
        self._listener = make_listener(host=host, port=port,
                                       allow_pickle=allow_pickle,
                                       auth_token=auth_token)
        self.port = self._listener.port
        self.flight.record("coordinator_start",
                           num_workers=num_workers, port=self.port)
        self._lock = threading.Lock()
        self._pending: dict[str, _Pending] = {}
        self._connected: set[int] = set()
        self._ever_connected: set[int] = set()
        self._dead: set[int] = set()
        # Host topology (multi-host worlds): rank -> host label, plus
        # this process's own label — fed to the listener for per-link
        # fault shaping and to the partition sentry / link_stats.
        self.hosts: dict[int, str] = {}
        self.local_host: str = knobs.get_str("NBD_HOST") or "local"
        self._listener.local_host = self.local_host
        self._ready = threading.Event()
        self._last_seen: dict[int, float] = {}
        self._last_ping: dict[int, tuple[float, dict]] = {}
        self._output_callback: Callable[[int, dict], None] | None = None
        self._notify_callbacks: list[Callable[[int, Message], None]] = []
        self._listener.on_message = self._on_message
        self._listener.on_connect = self._on_connect
        self._listener.on_disconnect = self._on_disconnect
        self._listener.start()

    # ------------------------------------------------------------------
    # wiring

    def set_output_callback(self, cb: Callable[[int, dict], None]) -> None:
        """Register the streaming-output sink (reference:
        communication.py:137-144).  Called from the IO thread — keep fast."""
        self._output_callback = cb

    def add_notify_callback(self, cb: Callable[[int, Message], None]) -> None:
        """Register a sink for unsolicited non-stream messages
        (heartbeats, profiler events, timeline marks)."""
        self._notify_callbacks.append(cb)

    def set_fault_plan(self, plan) -> None:
        """Install (or clear, with ``None``) a chaos
        :class:`~nbdistributed_tpu.resilience.faults.FaultPlan` on the
        coordinator→worker send path."""
        self._listener.fault_plan = plan

    def fault_plan(self):
        return getattr(self._listener, "fault_plan", None)

    def set_host_map(self, hosts: dict[int, str]) -> None:
        """Record which host each rank runs on (multi-host worlds) —
        feeds per-link fault shaping, the partition sentry, and the
        per-host diagnosis surfaces."""
        self.hosts = dict(hosts or {})
        self._listener.host_of_rank = dict(self.hosts)

    def retry_for(self, msg_type: str) -> RetryPolicy:
        """The redelivery policy for one message type: its class
        override when configured (NBD_RETRY_CLASS_*), the base policy
        otherwise."""
        return self.retry_classes.get(class_of(msg_type), self.retry)

    # ------------------------------------------------------------------
    # readiness / liveness

    def wait_for_workers(self, timeout: float = 60.0) -> None:
        """Block until all ``num_workers`` ranks have attached."""
        if not self._ready.wait(timeout):
            missing = sorted(set(range(self.num_workers)) - self._connected)
            raise TimeoutError(
                f"workers {missing} did not attach to the control plane "
                f"within {timeout:.0f}s")

    def connected_ranks(self) -> list[int]:
        with self._lock:
            return sorted(self._connected)

    def last_seen(self, rank: int) -> float | None:
        with self._lock:
            return self._last_seen.get(rank)

    def pending_snapshot(self) -> dict[str, dict]:
        """Read-only view of in-flight requests for the hang watchdog:
        ``{msg_id: {"type", "expect", "responded", "sent_at"}}``.  A
        cell where some ranks responded while others sit on an old
        collective seq is the watchdog's skew signal — this is how it
        learns which ranks a hung request is still waiting on."""
        with self._lock:
            return {mid: {"type": p.msg_type,
                          "expect": sorted(p.expect),
                          "responded": sorted(p.responses),
                          "sent_at": p.sent_at,
                          "cell_sha1": p.cell_sha1,
                          "tenant": p.tenant}
                    for mid, p in self._pending.items()}

    def last_ping(self, rank: int) -> tuple[float, dict] | None:
        """(arrival time, payload) of the rank's latest heartbeat.  The
        payload carries the worker loop's busy state ({"busy_type",
        "busy_s"} mid-request, empty when idle) — the only liveness
        signal that does NOT go through the worker's serial request
        loop, so it works exactly when a status probe would stall
        behind a long-running cell."""
        with self._lock:
            return self._last_ping.get(rank)

    def last_telemetry(self, rank: int) -> dict | None:
        """The rank's newest heartbeat-piggybacked telemetry snapshot
        (HBM, live buffers, compile activity), or None."""
        with self._lock:
            hist = self._telemetry.get(rank)
            return hist[-1] if hist else None

    def telemetry_history(self, rank: int) -> list[dict]:
        """The last few telemetry snapshots for ``rank`` (bounded) —
        what the postmortem bundles as the dead rank's final device
        state."""
        with self._lock:
            return list(self._telemetry.get(rank) or ())

    def link_stats(self) -> dict:
        """Per-rank and per-host link health, assembled from state the
        coordinator already collects: the clock estimator's min-RTT
        samples (RTT estimate per rank), heartbeat ages, and redelivery
        counts (loss proxy — every retry is a frame some link ate or
        delayed past its class budget).  Shape::

            {"ranks": {rank: {"host", "rtt_ms", "offset_ms", "samples",
                              "hb_age_s", "retries"}},
             "hosts": {host: {"ranks", "rtt_ms" (min over ranks),
                              "hb_age_s" (max), "retries" (sum)}}}
        """
        now = time.time()
        clock = self.clock.stats()
        with self._lock:
            pings = dict(self._last_ping)
            retries = dict(self.retries_by_rank)
        ranks: dict[int, dict] = {}
        for r in range(self.num_workers):
            cs = clock.get(r) or {}
            ping = pings.get(r)
            rtt = cs.get("min_rtt_s")
            ranks[r] = {
                "host": self.hosts.get(r, "local"),
                "rtt_ms": round(rtt * 1e3, 2) if rtt is not None else None,
                "offset_ms": round((cs.get("offset_s") or 0.0) * 1e3, 2),
                "samples": cs.get("samples", 0),
                "hb_age_s": (round(now - ping[0], 1)
                             if ping is not None else None),
                "retries": retries.get(r, 0),
            }
        hosts: dict[str, dict] = {}
        for r, v in ranks.items():
            h = hosts.setdefault(v["host"], {"ranks": [], "rtt_ms": None,
                                             "hb_age_s": None,
                                             "retries": 0})
            h["ranks"].append(r)
            if v["rtt_ms"] is not None and (h["rtt_ms"] is None
                                            or v["rtt_ms"] < h["rtt_ms"]):
                h["rtt_ms"] = v["rtt_ms"]
            if v["hb_age_s"] is not None and (h["hb_age_s"] is None
                                              or v["hb_age_s"]
                                              > h["hb_age_s"]):
                h["hb_age_s"] = v["hb_age_s"]
            h["retries"] += v["retries"]
        return {"ranks": ranks, "hosts": hosts}

    def mark_worker_dead(self, rank: int) -> None:
        """Called by the process monitor when a worker process exits.
        Aborts every pending request still expecting this rank."""
        with self._lock:
            newly = rank not in self._dead
            self._dead.add(rank)
            pendings = [(mid, p) for mid, p in self._pending.items()
                        if rank in p.expect and rank not in p.responses]
        if newly:
            self.flight.record("worker_dead", rank=rank,
                               pending=[mid for mid, _ in pendings])
        for mid, p in pendings:
            failure = WorkerDied(f"worker {rank} died while a request "
                                 "was pending")
            # Which request died with it — the postmortem matches this
            # id against the dead rank's recovered dispatch events.
            failure.msg_id = mid
            p.failure = failure
            p.event.set()
            cb = p.on_done
            if cb is not None:
                # Async submission (ISSUE 14): resolve its future NOW
                # — a death must abort every in-flight windowed cell,
                # not only the one a thread happens to be waiting on.
                try:
                    cb()
                except Exception:
                    pass

    def dead_ranks(self) -> set[int]:
        """Snapshot of ranks currently marked dead (death callback or
        heartbeat verdict); a transport reconnect revives a rank out
        of the set.  Callers that must reach "everyone alive" send to
        ``range(world) - dead_ranks()`` — send_to_ranks raises on any
        dead target BEFORE transmitting to the rest."""
        with self._lock:
            return set(self._dead)

    def reset_world(self, num_workers: int, session_epoch: int) -> None:
        """Re-seed the world for an elastic resize (ISSUE 16): the old
        fleet is gone (drained, told to shut down, reaped), a new one
        of ``num_workers`` ranks is about to dial this same listener
        under ``session_epoch``.  Clears the connection/death/heartbeat
        bookkeeping and re-arms the ready barrier so
        ``wait_for_workers`` means the NEW fleet.  Any request still
        pending (the drain barrier should have left none) is failed
        loudly rather than left to hit its timeout against ranks that
        no longer exist.

        Frames from the old epoch that are still in flight need no
        handling here: every reply carries the ``ep`` header and
        ``_on_message`` fences ``epoch < session_epoch`` with an
        explicit rejected-verdict counter."""
        with self._lock:
            self.num_workers = int(num_workers)
            self.session_epoch = int(session_epoch)
            self._connected.clear()
            self._ever_connected.clear()
            self._dead.clear()
            self._ready.clear()
            self._last_seen.clear()
            self._last_ping.clear()
            self._telemetry.clear()
            stale = list(self._pending.items())
            self._pending.clear()
        self.flight.record("world_reset", num_workers=num_workers,
                           epoch=session_epoch,
                           aborted=[mid for mid, _ in stale])
        for mid, p in stale:
            failure = WorkerDied(
                f"request {mid} aborted: the fleet was resized "
                f"(epoch {session_epoch}) while it was pending")
            failure.msg_id = mid
            p.failure = failure
            p.event.set()
            cb = p.on_done
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass

    # ------------------------------------------------------------------
    # request/response

    def send_to_all(self, msg_type: str, data: Any = None, *,
                    bufs: dict | None = None,
                    timeout: float | None = ...,
                    vet_s: float | None = None) -> dict[int, Message]:
        return self.send_to_ranks(list(range(self.num_workers)), msg_type,
                                  data, bufs=bufs, timeout=timeout,
                                  vet_s=vet_s)

    def send_to_rank(self, rank: int, msg_type: str, data: Any = None, *,
                     bufs: dict | None = None,
                     timeout: float | None = ...) -> Message:
        return self.send_to_ranks([rank], msg_type, data, bufs=bufs,
                                  timeout=timeout)[rank]

    def send_to_ranks(self, ranks: list[int], msg_type: str,
                      data: Any = None, *, bufs: dict | None = None,
                      timeout: float | None = ...,
                      tenant: str | None = None, priority: int = 0,
                      msg_id: str | None = None,
                      on_verdict=None,
                      collective: str = "unknown",
                      vet_s: float | None = None
                      ) -> dict[int, Message]:
        """Send one request to ``ranks`` and collect their responses.

        ``timeout=...`` (unset) uses the manager default; ``None`` waits
        forever — but still aborts if an expected worker dies.

        With a retry policy enabled (``retry=`` / ``NBD_RETRY_*``), a
        request whose responses are slower than the per-attempt timeout
        is REDELIVERED to the still-missing ranks under the same msg_id
        with exponential backoff + jitter — the worker's replay cache
        makes redelivery idempotent, so a lost request or lost reply
        costs one backoff interval instead of the whole deadline.  The
        caller's ``timeout`` stays the total budget; the final attempt
        waits out whatever remains of it (forever when ``None``).

        ``execute`` requests route through :attr:`scheduler` first
        (ISSUE 8): the default single-tenant policy always dispatches
        immediately, a gateway's bounded policy may queue this call
        (it blocks until granted, within ``timeout``), shed it under
        overload (:class:`CellShed`), or refuse it at the tenant's
        in-flight cap (:class:`CellRejected`).  ``on_verdict(ticket)``
        fires right after admission — the gateway's hook for sending
        the explicit ``{"status": "queued", "position": n}`` reply
        instead of silently blocking.  ``tenant`` tags the wire frame
        (worker-side namespace routing + blame attribution) and is the
        scheduler's accounting key; ``msg_id`` pins the outgoing id so
        a gateway can keep tenant-side and worker-side correlation ids
        identical end to end.  ``collective`` is the cell's effects-
        admission class (``analysis.effects.collective_class``: free /
        bearing / unknown) — consulted only when the scheduler's
        effects gate is armed (ISSUE 9).  ``vet_s`` is how long the
        caller spent vetting/classifying the cell before this call —
        the latency observatory's "vet" stage (the submitter is the
        only layer that knows it).

        This is literally ``submit(...).wait()`` — the async pipeline
        (ISSUE 14) calls :meth:`submit` directly and waits later.
        """
        return self.submit(ranks, msg_type, data, bufs=bufs,
                           timeout=timeout, tenant=tenant,
                           priority=priority, msg_id=msg_id,
                           on_verdict=on_verdict, collective=collective,
                           vet_s=vet_s).wait()

    def submit(self, ranks: list[int], msg_type: str,
               data: Any = None, *, bufs: dict | None = None,
               timeout: float | None = ...,
               tenant: str | None = None, priority: int = 0,
               msg_id: str | None = None,
               on_verdict=None,
               collective: str = "unknown",
               vet_s: float | None = None,
               xfer: dict | None = None,
               on_done=None) -> PendingHandle:
        """Non-blocking dispatch (ISSUE 14): admit through the
        scheduler, transmit the request, and return a
        :class:`PendingHandle` without waiting for replies — the async
        executor streams cell N+1 while cell N runs through exactly
        this path.  Admission failures (``CellRejected``/``CellShed``/
        a dead target rank / a queued-admission timeout) still raise
        HERE, synchronously: an unadmitted cell has no handle.
        ``on_done(handle)`` fires from the IO thread the moment the
        expectation set completes (or a death aborts it) — the async
        future-resolution hook; without it, completion bookkeeping
        runs on whichever thread calls :meth:`PendingHandle.wait`,
        preserving the pre-split synchronous behavior exactly."""
        if timeout is ...:
            timeout = self.default_timeout
        if not ranks:
            # An empty expectation would otherwise never complete.
            return PendingHandle.resolved({})
        msg = Message(msg_type=msg_type, data=data, bufs=bufs or {})
        if msg_id is not None:
            msg.msg_id = msg_id
        if xfer is not None:
            # Bulk-transfer chunk header (messaging/xfer.py): rides
            # the frame header so a retry redelivers the SAME chunk
            # identity (xid/seq/crc) under the same msg_id.
            msg.xfer = xfer
        if self.session_epoch:
            msg.epoch = self.session_epoch
        if tenant is not None:
            msg.tenant = tenant
        if msg_type == "execute" and self.lat.enabled:
            # Ask the workers to stamp this request (dequeue / handler
            # entry+exit / compile seconds / reply build) and open the
            # coordinator-side stage record.  One flag check when off;
            # no wire header is emitted unless enabled.
            msg.latency = 1
            self.lat.begin(msg.msg_id, msg_type, tenant, vet_s=vet_s)
        # The total budget starts NOW: time spent queued behind the
        # mesh is part of the caller's wait, not free.
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        ticket = None
        try:
            if msg_type == "execute":
                ticket = self.scheduler.submit(tenant or "local",
                                               msg.msg_id, priority,
                                               collective=collective)
                if on_verdict is not None:
                    try:
                        on_verdict(ticket)
                    except Exception:
                        pass
                v = ticket.verdict
                if v["status"] == "rejected":
                    raise CellRejected(v.get("reason", "rejected"),
                                       tenant or "local")
                if v["status"] == "shed":
                    raise CellShed(tenant or "local", msg.msg_id)
                if v["status"] == "queued":
                    wait_s = (None if deadline is None
                              else max(0.0,
                                       deadline - time.monotonic()))
                    if not ticket.event.wait(wait_s):
                        self.scheduler.cancel(msg.msg_id)
                        raise TimeoutError(
                            f"cell spent {timeout}s queued behind the "
                            f"mesh without dispatch (tenant "
                            f"{tenant or 'local'}); withdrawn")
                    if ticket.state == SHED:
                        raise CellShed(tenant or "local", msg.msg_id)
            if msg.latency is not None:
                # The mesh slot is granted (immediately on an idle
                # mesh, after the queued wait otherwise) — closes the
                # queue stage.
                self.lat.note_grant(msg.msg_id)
            return self._transmit(ranks, msg, msg_type, timeout,
                                  deadline, tenant, ticket, on_done)
        except BaseException:
            # Never-transmitted request: free the mesh slot and the
            # stage record here — there is no handle to finish them.
            # (A transmitted request's cleanup runs in _finish when
            # its handle settles — success OR failure frees the slot;
            # a dead worker must not wedge the pool.)
            if ticket is not None and ticket.state == ACTIVE:
                self.scheduler.complete(msg.msg_id)
            if msg.latency is not None:
                self.lat.drop(msg.msg_id)
            raise

    def _transmit(self, ranks: list[int], msg: Message, msg_type: str,
                  timeout: float | None, deadline: float | None,
                  tenant: str | None, ticket,
                  on_done) -> PendingHandle:
        tr = self.tracer
        span_attrs = {"ranks": list(ranks)}
        if tenant is not None:
            span_attrs["tenant"] = tenant
        span = (tr.begin(f"send/{msg_type}", kind="coordinator",
                         attrs=span_attrs)
                if tr.enabled else None)
        if span is not None:
            # The worker's handler span adopts these ids as its parent,
            # stitching the cross-process timeline together.
            msg.trace = tr.context_for(span)
        pending = _Pending(set(ranks), msg_type, tenant)
        data = msg.data
        if msg_type == "execute" and isinstance(data, dict) \
                and isinstance(data.get("code"), str):
            from ..runtime.collective_guard import cell_hash
            pending.cell_sha1 = cell_hash(data["code"])
        with self._lock:
            already_dead = pending.expect & self._dead
            self._pending[msg.msg_id] = pending
        if already_dead:
            with self._lock:
                del self._pending[msg.msg_id]
            if span is not None:
                tr.end(span)
            raise WorkerDied(f"workers {sorted(already_dead)} are dead")
        handle = PendingHandle(self, msg, msg_type, ranks, pending,
                               ticket, timeout, deadline, tenant, span)
        try:
            pending.sent_at = time.time()
            self.flight.record("send", msg_id=msg.msg_id,
                               type=msg_type, ranks=list(ranks),
                               **({"tenant": tenant}
                                  if tenant is not None else {}))
            self._listener.send_to_ranks(list(ranks), msg)
        except BaseException:
            with self._lock:
                self._pending.pop(msg.msg_id, None)
            if span is not None:
                tr.end(span)
            raise
        if on_done is not None:
            handle.add_done_callback(on_done)
            # Event-driven settle from the IO thread; attached AFTER
            # the transmit so a synchronously-failing send never
            # leaves a dangling hook.  Late attach is race-safe: an
            # event that fired in the gap settles inline here.
            pending.on_done = handle._event_fired
            if pending.event.is_set():
                handle._event_fired()
        return handle

    def _finish(self, handle: PendingHandle, error) -> None:
        """One-time completion bookkeeping for a settled handle —
        stage-record close, span end, pending-table pop, mesh-slot
        release.  Runs exactly once per handle (the settle paths are
        terminal), on whichever thread settled it: the caller thread
        for synchronous waits (pre-split behavior, byte for byte),
        the IO thread for event-driven async completion."""
        msg = handle.msg
        tr = self.tracer
        span = handle._span
        if error is None and msg.latency is not None:
            # Close the stage record: per-rank worker stamps from the
            # reply headers, corrected by the clock estimator,
            # delivery stamped NOW (the caller receives the result
            # when the wait returns / the future resolves).  Mirrored
            # as stage/* child spans of the send span while a trace
            # is active.
            self.lat.complete(
                msg.msg_id, handle._result or {}, self.clock.offset,
                tracer=tr,
                parent=(tr.context_for(span)
                        if span is not None else None))
        if span is not None:
            span.attrs["deliveries"] = msg.attempt + 1
            tr.end(span)
        with self._lock:
            self._pending.pop(msg.msg_id, None)
        if handle._ticket is not None \
                and handle._ticket.state == ACTIVE:
            # Success OR failure frees the mesh slot and promotes
            # queued work — a dead worker must not wedge the pool.
            self.scheduler.complete(msg.msg_id)
        if msg.latency is not None:
            # No-op after a completed record; forgets the stage
            # record of a timed-out / aborted cell (only COMPLETED
            # cells feed the histograms).
            self.lat.drop(msg.msg_id)

    def post(self, ranks: list[int], msg_type: str, data: Any = None, *,
             bufs: dict | None = None) -> str:
        """Fire-and-forget send (no response expected) — used for
        shutdown-style messages where the reference tolerates silence
        (reference: worker.py:205-206 sends no shutdown response).
        Returns the message id, so a caller that later needs to
        correlate (e.g. the reattach tests matching a parked result to
        the request the coordinator died holding) can."""
        msg = Message(msg_type=msg_type, data=data, bufs=bufs or {})
        if self.session_epoch:
            msg.epoch = self.session_epoch
        try:
            self._listener.send_to_ranks(list(ranks), msg)
        except TransportError:
            pass
        return msg.msg_id

    # ------------------------------------------------------------------
    # IO-thread callbacks

    def _on_connect(self, rank: int) -> None:
        with self._lock:
            reconnect = rank in self._ever_connected
            self._connected.add(rank)
            self._ever_connected.add(rank)
            self._dead.discard(rank)
            self._last_seen[rank] = time.time()
            all_in = len(self._connected) >= self.num_workers
        # Transport-level connect events land in the flight ring on
        # BOTH sides so a postmortem can tell "link flapped" (connect /
        # eof / reconnect trail) from "peer died" (eof, then nothing).
        if reconnect:
            self.flight.record("transport_reconnect", rank=rank,
                               host=self.hosts.get(rank))
            obs_metrics.registry().counter(
                "nbd_link_reconnects_total",
                "worker control-plane reconnections (link flaps, "
                "partition heals, orphan reattaches)").inc()
        else:
            self.flight.record("transport_connect", rank=rank,
                               host=self.hosts.get(rank))
        if all_in:
            self._ready.set()

    def _on_disconnect(self, rank: int) -> None:
        with self._lock:
            self._connected.discard(rank)
        self.flight.record("transport_eof", rank=rank,
                           host=self.hosts.get(rank))
        self.mark_worker_dead(rank)

    def _on_message(self, rank: int, msg: Message) -> None:
        with self._lock:
            self._last_seen[rank] = time.time()
        if msg.msg_type == "stream_output":
            # Routed straight to the display callback, never queued
            # (reference: communication.py:174-184).
            cb = self._output_callback
            if cb is not None:
                try:
                    cb(rank, msg.data)
                except Exception:
                    pass
            return
        if msg.msg_type == "response":
            # Epoch fence, worker→coordinator direction (ISSUE 6):
            # workers stamp replies with their session epoch, so a
            # result computed for a PREVIOUS tenancy — a stale-side
            # rank delivering across a healed partition after this
            # coordinator already healed replacements — is rejected
            # here, never double-applied.  Unstamped replies (epoch
            # None: pre-partition worlds) are never rejected.
            if (msg.epoch is not None and self.session_epoch
                    and msg.epoch < self.session_epoch):
                obs_metrics.registry().counter(
                    "nbd_epoch_rejected_results",
                    "stale-epoch worker replies rejected by the "
                    "coordinator").inc()
                self.flight.record("epoch_rejected_result", rank=rank,
                                   msg_id=msg.msg_id,
                                   frame_epoch=msg.epoch,
                                   epoch=self.session_epoch)
                return
            # Arrival stamp for the latency observatory's reply stage
            # (and the clock sample below) — stamped HERE, on the IO
            # thread, so a slow completion wait can't inflate it.
            msg.recv_ts = time.time()
            with self._lock:
                pending = self._pending.get(msg.msg_id)
                if pending is None:
                    return  # late response to a timed-out request
                pending.responses[rank] = msg
                complete = set(pending.responses) >= pending.expect
            if pending.sent_at:
                # NTP-style clock sample: (t_send, worker reply stamp,
                # t_recv) — the estimator's min-RTT filter keeps only
                # the cleanest of these.
                self.clock.add(rank, pending.sent_at, msg.timestamp,
                               msg.recv_ts)
            if complete:
                pending.event.set()
                cb = pending.on_done
                if cb is not None:
                    # Async submission (ISSUE 14): settle the handle
                    # from the IO thread so a pipelined cell's future
                    # resolves the moment its last reply lands.
                    try:
                        cb()
                    except Exception:
                        pass
            return
        if msg.msg_type == "ping":
            data = msg.data or {}
            with self._lock:
                self._last_ping[rank] = (time.time(), data)
                tel = data.get("tel")
                if tel is not None:
                    self._telemetry.setdefault(
                        rank, deque(maxlen=8)).append(tel)
            return
        for cb in self._notify_callbacks:
            try:
                cb(rank, msg)
            except Exception:
                pass

    # ------------------------------------------------------------------

    def shutdown(self) -> None:
        """Tear down the listener (reference: communication.py:372-389)."""
        self._listener.close()
