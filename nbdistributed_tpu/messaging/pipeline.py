"""Async pipelined executor: spend the effects DAG on wall-clock
(ISSUE 14 tentpole).

Two PRs of static analysis built the proofs — the effects engine
proves cells collective-free (PR 9), the per-session dependency DAG
answers "is cell N+1 independent of cell N", and the scheduler already
overlaps proven-free cells on the pool — but the single-kernel
coordinator still dispatched one cell, blocked on its reply, then
dispatched the next, paying ~2 ms of control-plane overhead per cell.
This module is the executor that converts the proofs into overlap, in
the Podracer shape (PAPERS.md): **decouple submission from
completion**.  The coordinator streams cells N+1..N+k to the workers
while cell N runs; the per-rank worker loop is serial and its channel
is FIFO, so streamed cells execute back-to-back with zero inter-cell
coordinator round-trips, and every rank sees the same order.

The in-flight window is bounded by ``NBD_ASYNC_WINDOW`` and **gated by
the deps DAG + effects verdicts** — the same analyses
``%dist_lint deps`` renders:

* a cell may enter the window only when it has **no RAW/WAR/WAW
  hazard edge** to any in-flight cell
  (:func:`~..analysis.preflight.hazard_names` — literally the function
  that draws the DAG's edges, so "no edge" and "admissible" cannot
  drift apart);
* AND it is proven collective-free, OR it is the **sole**
  collective-bearing cell in flight — the one-collective-stream
  invariant ``NBD_POOL_SCHED_EFFECTS`` already enforces on the
  gateway, now applied to the single-kernel path (two concurrent
  collective streams carry no cross-rank ordering guarantee under
  retries/redelivery, so at most one is ever outstanding);
* opaque / unparseable / unknown-footprint cells **drain the window
  and run serialized** (their footprint edges to everything — the
  hazard test enforces this on its own; the explicit reason string is
  for diagnosability).

A blocked submission *waits* (draining the oldest in-flight work)
rather than failing: program order is always preserved per rank by
channel FIFO, so the gate is about cross-cell result/namespace
consistency and collective-stream safety, never about reordering.

Completion is event-driven: each in-flight cell's
:class:`~.coordinator.PendingHandle` resolves its
:class:`~..magics.proxies.CellFuture` from the coordinator's IO
thread the moment the last reply lands — no waiter thread per cell,
no polling.  On each completion the executor also bumps the latency
observatory's grant stamp for every still-in-flight successor
(:meth:`~..observability.latency.LatencyObservatory.note_worker_free`)
so a pipelined cell's socket-sit time behind its predecessor is
attributed to the ``queue`` stage, not double-counted as ``wire``.

Pure-testable by construction: the only comm surface used is
``submit(...) -> handle`` with ``handle.add_done_callback`` /
``handle.wait`` — the unit tests drive the whole admission state
machine with a fake comm and hand-fired handles, zero sleeps.
"""

from __future__ import annotations

import threading
import time

from ..analysis import preflight
from ..observability import flightrec
from ..observability import metrics as obs_metrics
from ..utils import knobs

DEFAULT_WINDOW = 4

# Documented exemptions for the blocking-under-lock self-lint
# (analysis/concur.py): per-site "Class.method:op" → reason.
_LINT_BLOCKING_OK = {
    "AsyncExecutor._blocked_reason_locked:join":
        "str.join over hazard-name strings — not Thread.join; no IO",
    "AsyncExecutor.submit_cell:wait":
        "Condition.wait RELEASES the lock while blocking — the "
        "admission wait parking a held submitter until a completion "
        "notify is the designed pattern, not IO under a held lock",
}

# Documented exemptions for the lifecycle self-lint
# (analysis/lifecycle.py): per-site "Class.method:resource" → reason.
_LINT_LIFECYCLE_OK = {
    "AsyncExecutor.submit_cell:async-window":
        "the slot is released on the COMPLETION path by design (the "
        "IO thread's done callback pops the cell), and the raise "
        "edges are covered piecewise: the payload is built before "
        "window entry, nothing between the append and the wire "
        "submit can throw, and the submit's own `except "
        "BaseException` removes the cell before re-raising",
}

# Collective-admission classes (analysis.effects.collective_class).
FREE, BEARING, UNKNOWN = "free", "bearing", "unknown"


def classify_entry(entry: dict | None) -> str:
    """The three-way collective class of a recorded footprint entry
    (the dict form of ``EffectReport.as_dict()``), mirroring
    ``analysis.effects.collective_class`` for the preflight store's
    entries: missing/unparsed/opaque → unknown."""
    if not entry or not entry.get("parsed") or entry.get("opaque"):
        return UNKNOWN
    verdict = entry.get("collective_verdict")
    if verdict == "none":
        return FREE
    if verdict == "exact":
        return BEARING
    return UNKNOWN


def _opaque(entry: dict | None) -> bool:
    return not entry or not entry.get("parsed") or entry.get("opaque")


class InFlightCell:
    """One windowed cell: its footprint, admission class, future, and
    wire handle."""

    __slots__ = ("seq", "msg_id", "sha", "entry", "collective",
                 "future", "handle", "submitted_at")

    def __init__(self, seq, msg_id, sha, entry, collective, future,
                 handle, submitted_at):
        self.seq = seq
        self.msg_id = msg_id
        self.sha = sha
        self.entry = entry
        self.collective = collective
        self.future = future
        self.handle = handle
        self.submitted_at = submitted_at


class AsyncExecutor:
    """The bounded, DAG-gated in-flight window over one
    :class:`~.coordinator.CommunicationManager` (or anything exposing
    its ``submit``/``lat`` surface)."""

    def __init__(self, comm, *, window: int | None = None,
                 now=time.monotonic, on_hold=None, on_result=None):
        self.comm = comm
        if window is None:
            window = knobs.get_int("NBD_ASYNC_WINDOW", 0) \
                or DEFAULT_WINDOW
        self.window = max(1, int(window))
        self._now = now
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inflight: list[InFlightCell] = []
        self._futures: list = []       # session order, bounded below
        self._seq = 0
        # Why the last submission waited, for status surfaces.
        self.on_hold = on_hold         # callable(reason_str) | None
        self.on_result = on_result     # callable(InFlightCell) | None
        self.submitted = 0
        self.completed = 0
        self.errored = 0
        self.held_total = 0

    # ------------------------------------------------------------------
    # admission predicate (pure; `_locked` = caller holds self._lock)

    def _blocked_reason_locked(self, entry: dict | None,
                               collective: str) -> str | None:
        """None when the cell may enter the window NOW, else a human
        reason naming the gate that held it."""
        if len(self._inflight) >= self.window:
            return (f"window full ({len(self._inflight)}/"
                    f"{self.window} in flight)")
        if _opaque(entry) and self._inflight:
            # The hazard test below would also catch this (opaque
            # edges to everything) — the dedicated reason names it.
            return ("opaque/unknown footprint — drains the window and "
                    "runs serialized")
        for f in self._inflight:
            names = preflight.hazard_names(f.entry or {"opaque": True},
                                           entry or {"opaque": True})
            if names:
                shown = ", ".join(names[:4])
                if len(names) > 4:
                    shown += f" +{len(names) - 4}"
                return (f"RAW/WAR/WAW hazard {{{shown}}} with "
                        f"in-flight cell #{f.seq}")
        if collective != FREE and any(f.collective != FREE
                                      for f in self._inflight):
            holder = next(f for f in self._inflight
                          if f.collective != FREE)
            return (f"one-collective-stream: in-flight cell "
                    f"#{holder.seq} already holds the collective "
                    f"stream ({holder.collective})")
        return None

    def try_admit(self, entry: dict | None,
                  collective: str | None = None) -> str | None:
        """Non-blocking admission probe (the unit-test surface):
        None = admissible now, else the blocking reason."""
        if collective is None:
            collective = classify_entry(entry)
        with self._lock:
            return self._blocked_reason_locked(entry, collective)

    # ------------------------------------------------------------------

    def submit_cell(self, code: str, ranks: list[int], *,
                    entry: dict | None = None, sha: str = "",
                    future=None, deadline_s: float | None = None,
                    repeat: int | None = None,
                    until: str | None = None,
                    vet_s: float | None = None,
                    timeout: float | None = ...):
        """Admit one cell into the window (blocking while the DAG /
        collective / depth gates hold it) and stream it to the
        workers.  Returns the resolved-later ``future`` (a
        :class:`~..magics.proxies.CellFuture` by default).

        The blocking wait is interruptible: a KeyboardInterrupt while
        held leaves the window intact and propagates (nothing was
        submitted)."""
        if future is None:
            from ..magics.proxies import CellFuture
            future = CellFuture(code, self._next_seq(), list(ranks))
        # Built BEFORE window entry: between the _inflight.append and
        # the wire submit's own repark-on-raise there must be no
        # statement that can throw, or the window slot strands
        # (lifecycle-lint bracket discipline).
        payload = {"code": code, "target_ranks": list(ranks)}
        if deadline_s is not None:
            payload["deadline_s"] = deadline_s
        if repeat is not None:
            payload["repeat"] = int(repeat)
            if until:
                payload["until"] = until
        collective = classify_entry(entry)
        cell = InFlightCell(future.seq, None, sha, entry, collective,
                            future, None, self._now())
        told = False
        while True:
            notify = None
            with self._cond:
                reason = self._blocked_reason_locked(entry, collective)
                if reason is None:
                    # Gate pass and window entry are ATOMIC — two
                    # racing submitters cannot both squeeze past the
                    # same free slot.  Registered BEFORE the wire
                    # submit: the IO thread may fire the done callback
                    # before submit() even returns on a fast (or
                    # fake) comm, and the pop must find the cell.
                    self._inflight.append(cell)
                    break
                if not told:
                    self.held_total += 1
                    notify = reason
                else:
                    # Completions notify this condition from the IO
                    # thread; the short timeout is a safety net
                    # against a missed notify, not a poll loop.
                    self._cond.wait(0.25)
            if notify is not None:
                told = True
                if self.on_hold is not None:
                    # Outside the lock: a callback that prints (or
                    # re-enters this object) must not deadlock it.
                    try:
                        self.on_hold(notify)
                    except Exception:
                        pass
            # A held submitter is the async window's retry driver:
            # nobody sits in wait() for a streamed cell, so due
            # redeliveries (and blown submit deadlines) of the cells
            # blocking us are pumped here — a lost request costs one
            # backoff interval, not "forever until %dist_wait".
            self._pump_inflight()
        try:
            # The cell identity rides the closure: the done callback
            # can fire from the IO thread BEFORE submit() returns (a
            # fast reply, a fake comm), i.e. before cell.handle is
            # even assigned — matching by handle would lose the race.
            handle = self.comm.submit(
                ranks, "execute", payload, vet_s=vet_s,
                timeout=timeout,
                on_done=lambda h: self._on_done_cell(cell, h))
        except BaseException as e:
            with self._cond:
                if cell in self._inflight:
                    self._inflight.remove(cell)
                self._cond.notify_all()
            if isinstance(e, Exception):
                future.reject(e)
                self._note_done(cell)
            raise
        cell.handle = handle
        cell.msg_id = handle.msg_id
        future.msg_id = handle.msg_id
        with self._lock:
            self.submitted += 1
            self._futures.append(future)
            while len(self._futures) > 256:
                self._futures.pop(0)
        flightrec.record("async_submit", msg_id=handle.msg_id,
                         seq=future.seq, window=len(self._inflight),
                         collective=collective)
        return future

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def _pump_inflight(self) -> None:
        """Drive due redeliveries / blown deadlines for every
        in-flight handle (non-blocking; see ``PendingHandle.pump``)."""
        with self._lock:
            handles = [c.handle for c in self._inflight
                       if c.handle is not None]
        for h in handles:
            try:
                h.pump()
            except Exception:
                pass  # maintenance must never break submission

    # ------------------------------------------------------------------
    # completion (IO thread)

    def _on_done_cell(self, cell: InFlightCell, handle) -> None:
        """PendingHandle done-callback: resolve the cell's future, pop
        it from the window, re-stamp successors' grant time (overlap-
        aware latency attribution), wake blocked submitters.
        Idempotent per cell — the drain path re-invokes it for
        handles whose terminal state came from wait() itself."""
        with self._cond:
            if cell not in self._inflight:
                return
            self._inflight.remove(cell)
            remaining = list(self._inflight)
            self._cond.notify_all()
        if cell.msg_id is None:
            cell.msg_id = handle.msg_id
            cell.future.msg_id = handle.msg_id
        err = handle.error
        if err is not None:
            cell.future.reject(err)
        else:
            results = {}
            try:
                for r, m in (handle.results or {}).items():
                    results[r] = getattr(m, "data", m)
            except Exception:
                pass
            cell.future.resolve(results)
        with self._lock:
            self.completed += 1
            if cell.future.state == "error":
                self.errored += 1
        # The worker freed up when this cell's reply landed: every
        # still-in-flight successor has been WAITING behind it, not on
        # the wire — move its grant stamp so the latency observatory
        # books that wait as `queue`, never as `wire` (the pipelined
        # no-double-count contract, ISSUE 14).
        lat = getattr(self.comm, "lat", None)
        if lat is not None:
            for f in remaining:
                if f.msg_id is not None:
                    try:
                        lat.note_worker_free(f.msg_id)
                    except Exception:
                        pass
        obs_metrics.registry().counter(
            "nbd_async_cells_total",
            "async-window cells completed",
            {"status": cell.future.state}).inc()
        self._note_done(cell)

    def _note_done(self, cell: InFlightCell) -> None:
        if self.on_result is not None:
            try:
                self.on_result(cell)
            except Exception:
                pass

    # ------------------------------------------------------------------
    # draining (the sync points: %dist_wait, synchronous cells)

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._inflight)

    def drain(self, timeout: float | None = None) -> list:
        """Wait until the window is empty (the explicit sync point:
        ``%dist_wait`` / ``%%distributed --sync``); drives the retry
        schedule of any straggler via its handle.  Returns the futures
        that were in flight when the drain began, settled or not (on
        timeout some may still be pending)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._lock:
            targets = list(self._inflight)
        for cell in targets:
            if deadline is None:
                if cell.handle is not None:
                    try:
                        # Full wait on the submit-time budget: drives
                        # the retry schedule for a straggler; every
                        # terminal settle (success, death, timeout)
                        # fires the done callback, which resolves the
                        # future and pops the window.
                        cell.handle.wait()
                    except Exception:
                        pass  # the outcome lives on the future
                    self._on_done_cell(cell, cell.handle)
                else:
                    cell.future.wait(None)
            else:
                # Bounded drain is NON-destructive: wait on the
                # future's event only — a cell still pending at the
                # deadline stays in flight instead of being aborted
                # the way a timed-out synchronous wait would be.
                # Pump between slices so stragglers still get their
                # due redeliveries while we watch.
                while not cell.future.wait(
                        min(0.25, max(0.0,
                                      deadline - time.monotonic()))):
                    if time.monotonic() >= deadline:
                        break
                    self._pump_inflight()
        return [c.future for c in targets]

    def unconsumed_errors(self) -> list:
        """Errored futures nobody has looked at — the next-cell warn
        pass (each returned future is marked warned, so the nag fires
        once; the error itself stays on the future for .result())."""
        out = []
        with self._lock:
            for fut in self._futures:
                if (fut.state == "error" and not fut.consumed
                        and not fut.warned):
                    fut.warned = True
                    out.append(fut)
        return out

    def snapshot(self) -> dict:
        """The ``%dist_status`` / ``%dist_doctor`` view: window depth
        and bound, per-cell state, and which in-flight cell (if any)
        holds the collective stream."""
        with self._lock:
            cells = [{"seq": c.seq,
                      "msg_id": c.msg_id,
                      "sha": (c.sha or "")[:10],
                      "collective": c.collective,
                      "age_s": round(self._now() - c.submitted_at, 2),
                      "state": c.future.state}
                     for c in self._inflight]
            holder = next((c["seq"] for c in cells
                           if c["collective"] != FREE), None)
            return {"window": self.window,
                    "depth": len(cells),
                    "cells": cells,
                    "collective_holder": holder,
                    "submitted": self.submitted,
                    "completed": self.completed,
                    "errored": self.errored,
                    "held_total": self.held_total}
