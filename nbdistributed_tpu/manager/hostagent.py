"""Host agent: spawn + death-watch workers on a host without ssh.

The ssh proxy path (``multihost.ssh_argv``) assumes an sshd, keys, and
a login shell on every host — none of which exist on stock TPU pod
VMs driven by an orchestrator, and none of which CI can exercise for
real.  The host agent replaces that hop with this stack's OWN
authenticated protocol: a small daemon (``tools/nbd_agent.py``) runs
on each host, the coordinator's :class:`AgentClient` dials it over the
existing ``NBDA``-preamble codec (same shared-secret handshake as the
worker control plane — the agent port spawns processes, so it is
never left unauthenticated on a non-loopback bind), and
``ProcessManager.start_workers_multihost(..., agents=...)`` executes a
:func:`~.multihost.make_launch_plan` through it instead of ``ssh``.

Request types (all JSON + the shared codec, no pickle):

    spawn  {rank, argv, env}        -> {pid}
    poll   {}                       -> {exits: {rank: rc}}  (all known)
    signal {rank, sig, group}       -> {signaled: bool}
    tail   {rank, n}                -> {text}
    ping   {}                       -> {status, workers, host}
    reap   {}                       -> SIGTERM/SIGKILL every child

Death-watch is push-based: the agent's monitor thread posts an
unsolicited ``worker_exit {rank, rc}`` to the attached client the
moment a child exits, and the client's receive thread folds it into a
local table — so ``_AgentWorker.poll()`` (called 4×/s per rank by the
ProcessManager monitor) never touches the network.  **Link loss makes
workers UNKNOWN, not dead**: a broken agent connection is exactly what
a network partition looks like from the coordinator, and reporting
"exited" would turn every partition into N spurious heals — the
partition sentry (``resilience/partition.py``) owns that call.  The
client redials in the background and resyncs exit state with one
``poll`` request after reconnecting.
"""

from __future__ import annotations

import os
import signal as _signal
import subprocess
import sys
import threading
import time
from collections import deque

from ..messaging.codec import Message
from ..utils import knobs
from ..messaging.transport import (CoordinatorListener, TransportError,
                                   WorkerChannel)

AGENT_CLIENT_RANK = 0  # preamble rank the manager announces to the agent


# ----------------------------------------------------------------------
# agent (daemon) side


class _AgentChildIO:
    """Bounded ring of a child's merged stdout/stderr (the agent-side
    twin of process_manager._ChildIO — kept local so the agent daemon
    imports no manager machinery it doesn't need)."""

    def __init__(self, proc: subprocess.Popen, rank: int):
        self.lines: deque[str] = deque(maxlen=400)
        self._thread = threading.Thread(
            target=self._drain, args=(proc,),
            name=f"nbd-agent-worker-{rank}-io", daemon=True)
        self._thread.start()

    def _drain(self, proc: subprocess.Popen) -> None:
        try:
            for line in proc.stdout:  # type: ignore[union-attr]
                self.lines.append(line.decode("utf-8", "replace")
                                  if isinstance(line, bytes) else line)
        except ValueError:
            pass

    def tail(self, n: int = 40) -> str:
        return "".join(list(self.lines)[-n:])


class HostAgent:
    """One per host: accepts an authenticated manager connection and
    runs spawn/poll/signal/tail requests against local children."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 auth_token: str | None = None,
                 host_label: str | None = None,
                 run_dir: str | None = None):
        self.host_label = host_label or knobs.get_str("NBD_HOST") \
            or "agent"
        # Per-host run dir: flight rings / stack dumps / manifests of
        # agent-spawned workers land HERE, never on the coordinator's
        # filesystem — the shared-run-dir assumption is exactly what
        # multi-host execution turns off.
        self.run_dir = run_dir or knobs.get_str("NBD_RUN_DIR")
        self._listener = CoordinatorListener(host, port,
                                             auth_token=auth_token)
        self.host, self.port = self._listener.host, self._listener.port
        self._procs: dict[int, subprocess.Popen] = {}
        self._io: dict[int, _AgentChildIO] = {}
        self._exits: dict[int, int] = {}
        # Ranks whose replacement Popen is in flight OUTSIDE the lock
        # (see _spawn): the death-watch must not record/push the
        # superseded process's exit during that window, or the freshly
        # spawned worker reads as instantly dead manager-side.
        self._spawning: set[int] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener.on_message = self._on_message
        self._listener.start()
        self._monitor = threading.Thread(target=self._watch,
                                         name="nbd-agent-monitor",
                                         daemon=True)
        self._monitor.start()

    # -- request handling ---------------------------------------------

    def _on_message(self, conn_rank: int, msg: Message) -> None:
        try:
            reply = self._handle(msg)
        except Exception as e:
            reply = msg.reply(data={"error": f"{type(e).__name__}: {e}"})
        try:
            self._listener.send_to_rank(conn_rank, reply)
        except TransportError:
            pass  # client vanished mid-request; it will resync on redial

    def _handle(self, msg: Message) -> Message:
        data = msg.data or {}
        t = msg.msg_type
        if t == "spawn":
            return msg.reply(data=self._spawn(data))
        if t == "poll":
            with self._lock:
                exits = {str(r): rc for r, rc in self._exits.items()}
            return msg.reply(data={"exits": exits})
        if t == "signal":
            return msg.reply(data={
                "signaled": self._signal(int(data["rank"]),
                                         int(data["sig"]),
                                         bool(data.get("group")))})
        if t == "tail":
            io = self._io.get(int(data.get("rank", -1)))
            return msg.reply(data={
                "text": io.tail(int(data.get("n", 40)))
                if io is not None else ""})
        if t == "ping":
            with self._lock:
                workers = sorted(self._procs)
            return msg.reply(data={"status": "ok", "host":
                                   self.host_label, "workers": workers,
                                   "run_dir": self.run_dir})
        if t == "reap":
            n = self._reap()
            return msg.reply(data={"reaped": n})
        return msg.reply(data={"error": f"unknown agent request {t!r}"})

    def _spawn(self, data: dict) -> dict:
        rank = int(data["rank"])
        argv = [str(a) for a in (data.get("argv") or ())]
        if not argv:
            return {"error": "spawn needs argv"}
        # Env: the agent's own environment (its NBD_RUN_DIR, its
        # platform neutralization) + the plan's overrides — the same
        # layering the ssh path's `exec env K=V ...` produces, with
        # the agent host's run dir winning over anything inherited.
        env = dict(os.environ)
        env.update({str(k): str(v)
                    for k, v in (data.get("env") or {}).items()})
        if self.run_dir:
            env["NBD_RUN_DIR"] = self.run_dir
        env.setdefault("NBD_HOST", self.host_label)
        with self._lock:
            old = self._procs.get(rank)
            if old is not None and old.poll() is None:
                return {"error": f"rank {rank} is already running "
                                 f"(pid {old.pid})"}
            self._spawning.add(rank)
        # Popen (fork+exec) runs OUTSIDE the lock: a slow spawn must
        # not stall the death-watch scan and the poll/ping handlers
        # behind process creation.  Safe unlocked: requests are served
        # serially on the listener IO thread, so no concurrent spawn
        # can race this rank's slot between the check and the insert —
        # and the _spawning mark keeps the death-watch from
        # recording/pushing the superseded dead process's exit
        # mid-window (the lock used to exclude that for the whole
        # section; the mark preserves exactly that).
        try:
            proc = subprocess.Popen(
                argv, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, env=env,
                start_new_session=True, cwd=os.getcwd())
        except BaseException:
            with self._lock:
                self._spawning.discard(rank)
            raise
        with self._lock:
            self._spawning.discard(rank)
            self._procs[rank] = proc
            self._io[rank] = _AgentChildIO(proc, rank)
            self._exits.pop(rank, None)
        return {"pid": proc.pid, "host": self.host_label}

    def _signal(self, rank: int, sig: int, group: bool) -> bool:
        with self._lock:
            proc = self._procs.get(rank)
        if proc is None or proc.poll() is not None:
            return False
        try:
            if group:
                try:
                    os.killpg(os.getpgid(proc.pid), sig)
                    return True
                except (ProcessLookupError, PermissionError, OSError):
                    pass
            proc.send_signal(sig)
            return True
        except (ProcessLookupError, OSError):
            return False

    def _reap(self) -> int:
        with self._lock:
            procs = list(self._procs.items())
        n = 0
        for _rank, proc in procs:
            if proc.poll() is None:
                self._signal_tree(proc, _signal.SIGTERM)
                n += 1
        deadline = time.time() + 3.0
        while time.time() < deadline and any(p.poll() is None
                                             for _, p in procs):
            time.sleep(0.05)
        for _rank, proc in procs:
            if proc.poll() is None:
                self._signal_tree(proc, _signal.SIGKILL)
        # Reap the SIGKILLed children: poll() is what calls waitpid,
        # and without this pass they sit as zombies for the agent's
        # lifetime (the death-watch records each exit once and never
        # polls again).  Then drop the stdout pipe fds of children
        # whose drain thread has finished — closing a BufferedReader
        # while a reader is still blocked in read() would WAIT on the
        # reader's buffer lock (a SIGKILLed worker's orphaned
        # descendant can hold the pipe's write end open), hanging
        # _reap and close(); a still-draining pipe is left to EOF on
        # its own, the pre-fix behavior.
        deadline = time.time() + 2.0
        while time.time() < deadline and any(p.poll() is None
                                             for _, p in procs):
            time.sleep(0.05)
        for rank, proc in procs:
            if proc.poll() is None or proc.stdout is None:
                continue
            io = self._io.get(rank)
            if io is not None:
                io._thread.join(timeout=0.5)
                if io._thread.is_alive():
                    continue
            try:
                proc.stdout.close()
            except OSError:
                pass
        return n

    @staticmethod
    def _signal_tree(proc: subprocess.Popen, sig: int) -> None:
        try:
            os.killpg(os.getpgid(proc.pid), sig)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    # -- death-watch ---------------------------------------------------

    def _scan_exits_once(self) -> list[tuple[int, int]]:
        """One death-watch pass: record newly-exited ranks and return
        them for the push.  Ranks with a replacement spawn in flight
        are skipped — their registered proc is the superseded corpse,
        and publishing its exit would make the new worker read dead."""
        dead: list[tuple[int, int]] = []
        with self._lock:
            for rank, proc in self._procs.items():
                if rank in self._spawning:
                    continue
                rc = proc.poll()
                if rc is not None and rank not in self._exits:
                    self._exits[rank] = rc
                    dead.append((rank, rc))
        return dead

    def _watch(self) -> None:
        while not self._stop.wait(0.25):
            dead = self._scan_exits_once()
            for rank, rc in dead:
                # Push the exit to whatever manager is attached; a
                # partitioned-away manager resyncs via `poll` later.
                try:
                    self._listener.send_to_rank(
                        AGENT_CLIENT_RANK,
                        Message(msg_type="worker_exit",
                                data={"rank": rank, "rc": rc}))
                except TransportError:
                    pass

    # -- lifecycle -----------------------------------------------------

    def serve_forever(self) -> None:
        try:
            while not self._stop.wait(1.0):
                pass
        except KeyboardInterrupt:
            pass

    def close(self, *, reap: bool = True) -> None:
        self._stop.set()
        if reap:
            try:
                self._reap()
            except Exception:
                pass
        self._listener.close()
        # The stop event ends the death-watch within one 0.25 s tick;
        # reap it so no thread that takes self._lock survives into
        # interpreter teardown.
        self._monitor.join(timeout=2.0)


# ----------------------------------------------------------------------
# coordinator (client) side


class AgentClient:
    """The coordinator's connection to one host's agent.

    Requests are correlated by msg_id on a receive thread that also
    folds in unsolicited ``worker_exit`` notices.  When the link
    drops, ``link_up`` flips False and every worker's exit state
    becomes UNKNOWN (``exit_code`` returns None) — partition-safe by
    construction — while a background redial loop keeps trying; the
    first request after a reconnect resyncs exits with ``poll``.
    """

    def __init__(self, host: str, port: int, *,
                 auth_token: str | None = None,
                 connect_timeout: float = 10.0):
        self.host, self.port = host, port
        self._auth_token = auth_token
        self._connect_timeout = connect_timeout
        self._lock = threading.Lock()
        self._pending: dict[str, tuple[threading.Event, list]] = {}
        self._exits: dict[int, int] = {}
        self._closed = threading.Event()
        self.link_up = False
        self.reconnects = 0
        # msg_id of an in-flight fire-and-forget resync 'poll' sent
        # right after a redial: its reply is folded in by the recv
        # loop itself (a blocking request() there would deadlock — the
        # redial runs ON the recv thread, the only thread that could
        # deliver the reply).
        self._resync_mid: str | None = None
        self._ch: WorkerChannel | None = None
        self._dial()
        self._thread = threading.Thread(target=self._recv_loop,
                                        name="nbd-agent-client",
                                        daemon=True)
        self._thread.start()

    def _dial(self) -> None:
        self._ch = WorkerChannel(self.host, self.port,
                                 rank=AGENT_CLIENT_RANK,
                                 auth_token=self._auth_token,
                                 connect_timeout=self._connect_timeout)
        self.link_up = True

    def _recv_loop(self) -> None:
        while not self._closed.is_set():
            ch = self._ch
            if ch is None:
                return
            try:
                msg = ch.recv(timeout=1.0)
            except TimeoutError:
                continue
            except TransportError:
                self.link_up = False
                with self._lock:
                    # Fail pending requests fast; callers see link loss.
                    for ev, box in self._pending.values():
                        box.append(None)
                        ev.set()
                    self._pending.clear()
                if self._closed.is_set():
                    return
                self._redial_until_up()
                continue
            if msg.msg_type == "worker_exit":
                d = msg.data or {}
                try:
                    with self._lock:
                        self._exits[int(d["rank"])] = int(d["rc"])
                except (KeyError, TypeError, ValueError):
                    pass
                continue
            if msg.msg_id == self._resync_mid \
                    and msg.msg_type == "response":
                # The post-reconnect resync reply: fold in every exit
                # the outage ate (the push notices had no live client
                # to land on).
                self._resync_mid = None
                self._fold_exits((msg.data or {}).get("exits") or {})
                continue
            with self._lock:
                slot = self._pending.pop(msg.msg_id, None)
            if slot is not None:
                ev, box = slot
                box.append(msg)
                ev.set()

    def _fold_exits(self, exits: dict) -> None:
        with self._lock:
            for r, rc in exits.items():
                try:
                    self._exits[int(r)] = int(rc)
                except (TypeError, ValueError):
                    pass

    def _redial_until_up(self) -> None:
        while not self._closed.wait(2.0):
            try:
                old, self._ch = self._ch, None
                if old is not None:
                    try:
                        old.close()
                    except Exception:
                        pass
                self._dial()
                self.reconnects += 1
            except Exception:
                continue
            # Resync exits missed while the link was down —
            # fire-and-forget: we ARE the recv thread, so a blocking
            # request() here could never see its own reply.
            try:
                msg = Message(msg_type="poll", data={},
                              rank=AGENT_CLIENT_RANK)
                self._resync_mid = msg.msg_id
                self._ch.send(msg)
            except Exception:
                self._resync_mid = None
            return

    # -- requests ------------------------------------------------------

    def request(self, msg_type: str, data: dict,
                timeout: float = 15.0) -> Message:
        ch = self._ch
        if ch is None or not self.link_up:
            raise TransportError(f"agent {self.host}:{self.port} link "
                                 "is down")
        msg = Message(msg_type=msg_type, data=data,
                      rank=AGENT_CLIENT_RANK)
        ev = threading.Event()
        box: list = []
        with self._lock:
            self._pending[msg.msg_id] = (ev, box)
        try:
            ch.send(msg)
        except Exception as e:
            with self._lock:
                self._pending.pop(msg.msg_id, None)
            raise TransportError(f"agent send failed: {e}") from e
        if not ev.wait(timeout):
            with self._lock:
                self._pending.pop(msg.msg_id, None)
            raise TimeoutError(f"agent {self.host}:{self.port} did not "
                               f"answer '{msg_type}' in {timeout:.0f}s")
        resp = box[0] if box else None
        if resp is None:
            raise TransportError(f"agent {self.host}:{self.port} link "
                                 f"dropped during '{msg_type}'")
        err = (resp.data or {}).get("error")
        if err:
            raise RuntimeError(f"agent {self.host}:{self.port}: {err}")
        return resp

    def spawn(self, rank: int, argv, env) -> int:
        resp = self.request("spawn", {
            "rank": rank, "argv": list(argv),
            "env": {k: v for k, v in (dict(env) if env else {}).items()},
        }, timeout=30.0)
        return int(resp.data["pid"])

    def signal(self, rank: int, sig: int, *, group: bool = False) -> bool:
        try:
            resp = self.request("signal", {"rank": rank, "sig": int(sig),
                                           "group": group}, timeout=10.0)
        except (TransportError, TimeoutError):
            return False
        return bool((resp.data or {}).get("signaled"))

    def tail(self, rank: int, n: int = 40) -> str | None:
        try:
            resp = self.request("tail", {"rank": rank, "n": n},
                                timeout=10.0)
        except (TransportError, TimeoutError, RuntimeError):
            return None
        return (resp.data or {}).get("text", "")

    def exit_code(self, rank: int) -> int | None:
        """The rank's known exit code, or None (alive OR unknowable —
        a down link never reports death)."""
        with self._lock:
            return self._exits.get(rank)

    def close(self) -> None:
        self._closed.set()
        ch, self._ch = self._ch, None
        if ch is not None:
            try:
                ch.close()
            except Exception:
                pass
        # The closed flag + dead channel end the recv loop within one
        # 1 s recv timeout; reap it so no thread that takes
        # self._lock survives into interpreter teardown.
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=3.0)


class _AgentWorker:
    """Popen-compatible shim over a worker the agent spawned on a
    remote host.  ``poll`` reads the client's local exit table (the
    push-fed death-watch) — no network per call; link loss reads as
    alive-unknown, the partition-safe answer.  ``remote = True`` keeps
    the ProcessManager's group-kill path from ever signalling the
    REMOTE pid number on the LOCAL host (which could hit an innocent
    local process)."""

    remote = True

    def __init__(self, client: AgentClient, rank: int, pid: int):
        self._client = client
        self.rank = rank
        self.pid = int(pid)
        self.stdout = None
        self.returncode: int | None = None

    def poll(self) -> int | None:
        if self.returncode is None:
            self.returncode = self._client.exit_code(self.rank)
        return self.returncode

    def wait(self, timeout: float | None = None) -> int:
        deadline = None if timeout is None else time.time() + timeout
        while self.poll() is None:
            if deadline is not None and time.time() > deadline:
                raise subprocess.TimeoutExpired(
                    f"agent worker rank {self.rank}", timeout)
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]

    def send_signal(self, sig: int) -> None:
        self._client.signal(self.rank, sig)

    def send_signal_group(self, sig: int) -> None:
        self._client.signal(self.rank, sig, group=True)


class _AgentWorkerIO:
    """Stdio view of an agent-spawned worker: the ring lives on the
    agent; ``tail`` fetches it on demand (and says so when the link is
    down rather than rendering silence as 'no output')."""

    def __init__(self, client: AgentClient, rank: int):
        self._client = client
        self._rank = rank

    def tail(self, n: int = 40) -> str:
        text = self._client.tail(self._rank, n)
        if text is None:
            return (f"(agent link {self._client.host}:"
                    f"{self._client.port} is down — worker stdio "
                    "unavailable)\n")
        return text


def parse_agents(spec: str | dict | None) -> dict[str, tuple[str, int]]:
    """Parse ``"hostB=127.0.1.3:7411,hostC=10.0.0.4:7411"`` (or an
    already-split mapping) into ``{host_label: (addr, port)}``.
    Malformed entries are a loud ValueError — a typo'd agent endpoint
    must not silently fall back to ssh."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        items = spec.items()
    else:
        items = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            host, sep, ep = part.partition("=")
            if not sep:
                raise ValueError(f"bad agent spec {part!r} (want "
                                 f"host=addr:port)")
            items.append((host.strip(), ep.strip()))
    out: dict[str, tuple[str, int]] = {}
    for host, ep in items:
        if isinstance(ep, tuple):
            addr, port = ep
        else:
            addr, sep, port = str(ep).rpartition(":")
            if not sep or not addr:
                raise ValueError(f"bad agent endpoint {ep!r} for host "
                                 f"{host!r} (want addr:port)")
        try:
            port = int(port)
        except (TypeError, ValueError):
            raise ValueError(f"bad agent port {port!r} for host "
                             f"{host!r}")
        if not host:
            raise ValueError(f"empty host label in agent spec "
                             f"(endpoint {addr}:{port})")
        if host in out:
            raise ValueError(f"duplicate agent entry for host {host!r}")
        out[host] = (addr, port)
    return out


def main(argv: list[str] | None = None) -> int:
    """CLI entry shared with ``tools/nbd_agent.py``."""
    import argparse

    p = argparse.ArgumentParser(
        description="nbdistributed_tpu host agent: spawns and "
                    "death-watches workers on this host for a remote "
                    "coordinator (the ssh-free multi-host launch path)")
    p.add_argument("--bind", default="127.0.0.1",
                   help="address to listen on (non-loopback binds "
                        "REQUIRE --token-file/--token-env)")
    p.add_argument("--port", type=int, default=0,
                   help="port (0 = ephemeral, printed on stdout)")
    p.add_argument("--token-file", default=None,
                   help="file holding the shared secret the "
                        "coordinator must present")
    p.add_argument("--token-env", default=None,
                   help="env var holding the shared secret")
    p.add_argument("--host-label", default=None,
                   help="host label for link shaping / diagnosis "
                        "(default: $NBD_HOST or 'agent')")
    p.add_argument("--run-dir", default=None,
                   help="per-host run dir for worker flight rings "
                        "(default: $NBD_RUN_DIR, else minted)")
    args = p.parse_args(argv)

    token = None
    if args.token_file:
        with open(args.token_file) as f:
            token = f.read().strip()
    elif args.token_env:
        token = os.environ.get(args.token_env) or None
    if token is None and args.bind not in ("127.0.0.1", "localhost") \
            and not args.bind.startswith("127."):
        print("refusing an unauthenticated non-loopback bind: this "
              "port spawns processes. Pass --token-file or "
              "--token-env.", file=sys.stderr)
        return 2
    run_dir = args.run_dir or knobs.get_str("NBD_RUN_DIR")
    if not run_dir:
        import tempfile
        run_dir = tempfile.mkdtemp(prefix="nbd_agent_")
    os.makedirs(run_dir, exist_ok=True)
    os.environ["NBD_RUN_DIR"] = run_dir

    agent = HostAgent(args.bind, args.port, auth_token=token,
                      host_label=args.host_label, run_dir=run_dir)
    # Machine-readable readiness line: launchers (and the integration
    # tests) block on it.
    print(f"NBD_AGENT_READY host={agent.host} port={agent.port} "
          f"label={agent.host_label} run_dir={run_dir}", flush=True)

    def _term(_sig, _frm):
        raise KeyboardInterrupt

    _signal.signal(_signal.SIGTERM, _term)
    try:
        agent.serve_forever()
    finally:
        agent.close()
    return 0
