"""Orchestration layer (L3, SURVEY §1): worker process lifecycle and
per-rank backend/topology environment."""

from .process_manager import (ProcessManager, find_free_port,
                              wait_until_ready)
from .topology import cpu_worker_env, detect_backend, tpu_worker_env, worker_env

__all__ = ["ProcessManager", "find_free_port", "wait_until_ready",
           "cpu_worker_env", "detect_backend", "tpu_worker_env",
           "worker_env"]
