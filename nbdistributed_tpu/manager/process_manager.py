"""Worker process lifecycle: spawn, monitor, tiered kill.

Rebuild of the reference's ``ProcessManager`` (reference:
process_manager.py:23-374) with the startup race fixed: instead of
``sleep(2)`` + hope (reference: process_manager.py:136-137), readiness is
the worker's control-plane HELLO, observed via
``CommunicationManager.wait_for_workers`` while this module concurrently
watches for early child death and surfaces captured stdio on failure
(reference collects stdio the same way: process_manager.py:138-150).

A monitor thread reports any child death to the communication manager so
pending requests fail fast instead of hanging (SURVEY §5.3 notes the
reference hangs forever on a dead worker in no-timeout mode).
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Callable

from . import topology


def wait_until_ready(comm, pm, timeout_s: float, *, poll_s: float = 2.0,
                     on_wait=None) -> None:
    """Block until every worker has attached to the control plane.

    Converts an early worker death into a diagnostic RuntimeError (with
    the dead child's stdio) instead of a timeout; raises TimeoutError
    at the deadline.  ``on_wait()`` runs after each poll interval
    (progress display).  The one bring-up loop shared by the magic
    layer, bench, selftest, and the integration tests.
    """
    t0 = time.time()
    deadline = t0 + timeout_s
    while True:
        try:
            comm.wait_for_workers(timeout=poll_s)
            return
        except TimeoutError:
            pm.check_startup_failure()
            if time.time() > deadline:
                # Re-raise with the *elapsed/budget* picture — the
                # inner error only knows the last poll interval, which
                # once produced "did not attach within 2s" after a
                # 240 s wait — plus each missing rank's exit status
                # and captured stdio, so an attach timeout is
                # diagnosable in one read instead of a separate
                # %dist_logs round.
                missing = sorted(set(range(comm.num_workers))
                                 - set(comm.connected_ranks()))
                diag = ""
                diag_fn = getattr(pm, "startup_diagnostics", None)
                if diag_fn is not None:
                    try:
                        diag = diag_fn(missing)
                    except Exception:
                        diag = ""  # diagnostics must not mask the error
                raise TimeoutError(
                    f"workers {missing} did not attach to the control "
                    f"plane within {time.time() - t0:.0f}s (budget "
                    f"{timeout_s:.0f}s)"
                    + (f"\n{diag}" if diag else "")) from None
            if on_wait is not None:
                on_wait()


def find_free_port() -> int:
    """Bind-to-zero port discovery (reference: process_manager.py:154-175)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _ChildIO:
    """Drains a child's merged stdout/stderr into a bounded ring buffer so
    early-death diagnostics are available without risking pipe stalls."""

    def __init__(self, proc: subprocess.Popen, rank: int):
        self.lines: deque[str] = deque(maxlen=400)
        self._thread = threading.Thread(
            target=self._drain, args=(proc,),
            name=f"nbd-worker-{rank}-io", daemon=True)
        self._thread.start()

    def _drain(self, proc: subprocess.Popen) -> None:
        try:
            for line in proc.stdout:  # type: ignore[union-attr]
                self.lines.append(line.decode("utf-8", "replace")
                                  if isinstance(line, bytes) else line)
        except ValueError:
            pass  # stream closed during shutdown

    def tail(self, n: int = 40) -> str:
        return "".join(list(self.lines)[-n:])


class _AdoptedProcess:
    """Popen-compatible shim over an externally-discovered pid the
    reattach path adopts (durable sessions: the workers outlived the
    coordinator that spawned them, so they are NOT our children and
    ``Popen.wait``/``poll`` semantics don't exist).  Death-watch is a
    signal-0 probe; the exit code of a non-child is unknowable, so a
    vanished pid reports returncode -1."""

    def __init__(self, pid: int):
        self.pid = int(pid)
        self.stdout = None  # stdio belongs to the dead coordinator
        self.returncode: int | None = None

    def poll(self) -> int | None:
        if self.returncode is not None:
            return self.returncode
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            self.returncode = -1
            return self.returncode
        except PermissionError:
            return None  # alive under another uid
        except OSError:
            self.returncode = -1
            return self.returncode
        return None

    def wait(self, timeout: float | None = None) -> int:
        deadline = None if timeout is None else time.time() + timeout
        while self.poll() is None:
            if deadline is not None and time.time() > deadline:
                raise subprocess.TimeoutExpired(f"pid {self.pid}",
                                                timeout)
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]

    def send_signal(self, sig: int) -> None:
        os.kill(self.pid, sig)


class _AdoptedIO:
    """Stdio placeholder for adopted workers — their pipes died with
    the previous coordinator; ``%dist_logs`` should say so instead of
    rendering an empty tail as 'no output'."""

    def __init__(self, pid: int):
        self._pid = pid

    def tail(self, n: int = 40) -> str:
        return (f"(adopted worker pid {self._pid}: stdio was captured "
                "by the previous coordinator and is not available)\n")


class ProcessManager:
    def __init__(self):
        self.processes: dict[int, subprocess.Popen] = {}
        self.io: dict[int, _ChildIO] = {}
        self.backend: str | None = None
        self.world_size = 0
        self.dist_port: int | None = None
        # rank -> host label ("local" for direct children).  Feeds the
        # per-link fault shaping, the partition sentry's failure
        # domains, and per-host status/doctor grouping (ISSUE 6).
        self.hosts: dict[int, str] = {}
        # host label -> AgentClient for agent-launched hosts.
        self._agents: dict = {}
        self._monitor_thread: threading.Thread | None = None
        self._monitor_stop = threading.Event()
        self._death_callbacks: list[Callable[[int, int | None], None]] = []
        self._reported_dead: set[int] = set()

    # ------------------------------------------------------------------

    def add_death_callback(self, cb: Callable[[int, int | None], None]) -> None:
        """cb(rank, returncode) — invoked once per dead worker by the
        monitor thread."""
        self._death_callbacks.append(cb)

    def remove_death_callback(self, cb: Callable[[int, int | None], None]) \
            -> None:
        """Detach a callback registered above (no-op if absent) — a
        stopped supervisor must not keep receiving death reports."""
        try:
            self._death_callbacks.remove(cb)
        except ValueError:
            pass

    def start_workers(self, num_workers: int, control_port: int, *,
                      backend: str = "auto", coordinator_host: str = "127.0.0.1",
                      chips_per_worker: int = 1,
                      chips: list[int] | None = None,
                      extra_env: dict | None = None) -> None:
        """Spawn ``num_workers`` worker processes on this host.

        ``chips`` pins the workers to an explicit chip set — the
        reference's ``gpu_ids`` analog (reference:
        process_manager.py:107-112); TPU backend only.  Non-contiguous
        ids are fine for single-chip workers; with
        ``chips_per_worker > 1`` each worker's slice must be an
        aligned physical subgrid block (validated pre-spawn).

        The caller (magic layer) pairs this with
        ``CommunicationManager.wait_for_workers``; use
        :meth:`check_startup_failure` inside that wait loop to convert an
        early child death into a diagnostic error instead of a timeout.
        """
        if self.processes:
            raise RuntimeError("workers already running; shutdown first")
        if backend == "auto":
            backend = topology.detect_backend()
        host_chips = None
        if backend == "tpu":
            # Fail fast, before any child exists, when the topology
            # can't fit this host's chips (reference validates GPU ids
            # against device_count pre-spawn: magic.py:454-488).  The
            # returned probe feeds the env carve so validation and env
            # construction share one host geometry (one probe).
            host_chips = topology.validate_tpu_request(
                num_workers, chips_per_worker, chips=chips)
        self.backend = backend
        self.world_size = num_workers
        self.dist_port = find_free_port() if num_workers > 1 else None

        for rank in range(num_workers):
            env = topology.worker_env(rank, num_workers, backend,
                                      chips_per_worker=chips_per_worker,
                                      chips=chips, host_chips=host_chips)
            if extra_env:
                env.update(extra_env)
            cmd = [sys.executable, "-m", "nbdistributed_tpu.runtime.worker",
                   "--rank", str(rank), "--world-size", str(num_workers),
                   "--coordinator-host", coordinator_host,
                   "--control-port", str(control_port),
                   "--backend", backend]
            if self.dist_port is not None:
                cmd += ["--dist-port", str(self.dist_port)]
            self._spawn(rank, cmd, env)
        self.hosts = {r: "local" for r in range(num_workers)}
        self._start_monitor()

    def start_workers_multihost(self, hosts, control_port: int, *,
                                coordinator_host: str,
                                backend: str = "auto",
                                ssh: str = "ssh",
                                auth_token: str | None = None,
                                agents=None,
                                agent_token: str | None = None,
                                extra_env: dict | None = None) -> int:
        """Launch workers across hosts per a
        :func:`~nbdistributed_tpu.manager.multihost.make_launch_plan`.

        ``hosts``: a spec string (``"h1,h2:2,local"``) or list of
        ``HostSpec``.  Entries with host ``"local"`` spawn directly.
        Remote entries launch through their **host agent** when
        ``agents`` maps their label to an endpoint (``{"h2":
        ("10.0.0.3", 7411)}`` or the ``"h2=10.0.0.3:7411"`` spec
        string — see :mod:`~nbdistributed_tpu.manager.hostagent`),
        and through an ssh proxy process otherwise.  ``extra_env``
        rides every worker's env (session token/epoch, host labels).
        Returns the world size.
        """
        from . import hostagent, multihost

        if self.processes:
            raise RuntimeError("workers already running; shutdown first")
        specs = multihost.parse_hosts(hosts) if isinstance(hosts, str) \
            else list(hosts)
        agent_eps = hostagent.parse_agents(agents)
        unknown = set(agent_eps) - {h.host for h in specs}
        if unknown:
            raise ValueError(
                f"agent endpoints for hosts {sorted(unknown)} that are "
                f"not in the host spec {[h.host for h in specs]}")
        if backend == "auto":
            backend = topology.detect_backend()
        self.backend = backend
        self.world_size = sum(h.workers for h in specs)
        self.dist_port = find_free_port() if self.world_size > 1 else None
        plan = multihost.make_launch_plan(
            specs, coordinator_host=coordinator_host,
            control_port=control_port, dist_port=self.dist_port,
            backend=backend)
        ship = dict(extra_env or {})
        if auth_token:
            # Ship the control-plane shared secret in every worker's
            # env (rides the ssh remote command for remote entries —
            # visible to local `ps` on that host; see multihost.ssh_argv).
            ship["NBD_AUTH_TOKEN"] = auth_token
        if ship:
            import dataclasses as _dc
            plan = [_dc.replace(
                l, env=tuple(sorted({**dict(l.env), **ship}.items())))
                for l in plan]
        try:
            for launch in plan:
                self.hosts[launch.rank] = launch.host
                if launch.host == "local":
                    # Direct spawn: local base env (incl. the cpu
                    # backend's sitecustomize neutralization) + the
                    # plan's overrides.
                    env = topology.cpu_worker_env() if backend == "cpu" \
                        else dict(os.environ)
                    env.update(dict(launch.env))
                    self._spawn(launch.rank, list(launch.argv), env)
                elif launch.host in agent_eps:
                    client = self._agents.get(launch.host)
                    if client is None:
                        addr, port = agent_eps[launch.host]
                        # The agent's ADMISSION secret (fixed at daemon
                        # start, NBD_AGENT_TOKEN on the kernel side) is
                        # distinct from the per-session control-plane
                        # token the workers dial back with; the latter
                        # is only a usable fallback when the caller
                        # started the daemons with it (tests do).
                        client = hostagent.AgentClient(
                            addr, port,
                            auth_token=(agent_token if agent_token
                                        is not None else auth_token))
                        self._agents[launch.host] = client
                    pid = client.spawn(launch.rank, launch.argv,
                                       dict(launch.env))
                    self.processes[launch.rank] = \
                        hostagent._AgentWorker(client, launch.rank, pid)
                    self.io[launch.rank] = \
                        hostagent._AgentWorkerIO(client, launch.rank)
                else:
                    self._spawn(launch.rank,
                                multihost.ssh_argv(launch, ssh=ssh),
                                dict(os.environ))
        except Exception:
            # A half-spawned world must not leak children or agent
            # connections: reap what came up, then re-raise.
            try:
                self.shutdown()
            except Exception:
                pass
            raise
        self._start_monitor()
        return self.world_size

    def adopt(self, pids: dict[int, int], *, backend: str | None = None,
              dist_port: int | None = None) -> None:
        """Adopt externally-discovered worker processes this manager
        did not spawn — the ``%dist_attach`` reattach path (durable
        sessions).  Death-watch works through the same monitor thread
        via signal-0 polling (see :class:`_AdoptedProcess`); interrupt
        and tiered shutdown work unchanged (the workers were started
        with their own process groups)."""
        if self.processes:
            raise RuntimeError("workers already running; shutdown first")
        self.backend = backend
        self.world_size = len(pids)
        self.dist_port = dist_port
        for rank, pid in sorted(pids.items()):
            self.processes[rank] = _AdoptedProcess(pid)
            self.io[rank] = _AdoptedIO(pid)
        self.hosts = {r: "local" for r in self.processes}
        self._start_monitor()

    def _spawn(self, rank: int, cmd: list[str], env: dict) -> None:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, start_new_session=True,  # own pgid for group kill
            cwd=os.getcwd())
        self.processes[rank] = proc
        self.io[rank] = _ChildIO(proc, rank)

    def _start_monitor(self) -> None:
        self._monitor_stop.clear()
        self._monitor_thread = threading.Thread(
            target=self._monitor, name="nbd-child-monitor", daemon=True)
        self._monitor_thread.start()

    # ------------------------------------------------------------------

    def _monitor(self) -> None:
        """Watch children; report deaths (reference's is_running prunes
        as a side effect instead: process_manager.py:229-258)."""
        while not self._monitor_stop.wait(0.25):
            for rank, proc in list(self.processes.items()):
                rc = proc.poll()
                if rc is not None and rank not in self._reported_dead:
                    self._reported_dead.add(rank)
                    for cb in self._death_callbacks:
                        try:
                            cb(rank, rc)
                        except Exception:
                            pass

    def quiesce(self) -> None:
        """Stop death monitoring ahead of an intentional shutdown so
        planned worker exits are not reported as failures."""
        self._monitor_stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=1)

    def check_startup_failure(self) -> None:
        """Raise with captured stdio if any worker died during bring-up
        (reference: process_manager.py:138-150)."""
        for rank, proc in self.processes.items():
            rc = proc.poll()
            if rc is not None:
                raise RuntimeError(
                    f"worker {rank} exited with code {rc} during startup.\n"
                    f"--- worker {rank} output ---\n{self.io[rank].tail()}")

    def startup_diagnostics(self, ranks: list[int] | None = None,
                            tail_lines: int = 8) -> str:
        """Per-rank exit status + captured stdio tail for the given
        ranks (default: all) — folded into attach-timeout errors so
        "workers [2] did not attach" also says WHY (exit code, the
        ImportError, the bind failure...) without a second probe."""
        lines = []
        for rank in sorted(ranks if ranks is not None
                           else self.processes):
            proc = self.processes.get(rank)
            if proc is None:
                lines.append(f"--- rank {rank}: never spawned")
                continue
            rc = proc.poll()
            state = (f"exited with code {rc}" if rc is not None
                     else f"still running (pid {proc.pid}, never "
                          f"attached)")
            lines.append(f"--- rank {rank}: {state}")
            io = self.io.get(rank)
            tail = io.tail(tail_lines) if io is not None else ""
            if tail.strip():
                lines.append(tail.rstrip("\n"))
            else:
                lines.append("    (no output captured)")
        return "\n".join(lines)

    def dump_stacks(self, ranks: list[int] | None = None) -> list[int]:
        """SIGUSR1 the worker process(es): each worker's faulthandler
        appends an all-thread stack dump to its
        ``<run_dir>/stacks-rank{N}.txt`` — the %dist_doctor's way to
        see INSIDE a wedged rank (works even when the main thread is
        stuck in a loop or a native call).  Returns the ranks
        signaled.  Signal delivery is to the worker pid only, not the
        process group (XLA helper subprocesses must not see it)."""
        signaled = []
        for rank, proc in sorted(self.processes.items()):
            if ranks is not None and rank not in ranks:
                continue
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGUSR1)
                    signaled.append(rank)
                except Exception:
                    pass
        return signaled

    def interrupt(self, ranks: list[int] | None = None) -> list[int]:
        """SIGINT the worker process(es) — Jupyter-style cell interrupt.
        The executing cell aborts with a KeyboardInterrupt error
        response; the worker survives.  Returns the ranks signaled."""
        signaled = []
        for rank, proc in sorted(self.processes.items()):
            if ranks is not None and rank not in ranks:
                continue
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGINT)
                    signaled.append(rank)
                except Exception:
                    pass
        return signaled

    def is_running(self) -> bool:
        return any(p.poll() is None for p in self.processes.values())

    def alive_ranks(self) -> list[int]:
        return sorted(r for r, p in self.processes.items()
                      if p.poll() is None)

    # ------------------------------------------------------------------

    def shutdown(self, *, term_grace_s: float = 3.0,
                 kill_grace_s: float = 2.0) -> None:
        """SIGTERM → wait → SIGKILL → wait, per process group
        (reference: process_manager.py:177-227)."""
        self.quiesce()  # stop + join the monitor so no shutdown path
        # reports these intentional exits as worker deaths
        procs = list(self.processes.items())
        for _rank, proc in procs:
            if proc.poll() is None:
                self._signal_group(proc, signal.SIGTERM)
        self._wait_all(procs, term_grace_s)
        for _rank, proc in procs:
            if proc.poll() is None:
                self._signal_group(proc, signal.SIGKILL)
        remaining = self._wait_all(procs, kill_grace_s)
        for rank, proc in remaining:
            print(f"warning: worker {rank} (pid {proc.pid}) survived "
                  "SIGKILL", file=sys.stderr)
        for _rank, proc in procs:
            if proc.stdout:
                try:
                    proc.stdout.close()
                except OSError:
                    pass
        for client in self._agents.values():
            # Belt-and-braces remote reap (the per-rank SIGTERM/SIGKILL
            # above already went through the agent), then drop the
            # connection.
            try:
                client.request("reap", {}, timeout=10.0)
            except Exception:
                pass
            client.close()
        self._agents.clear()
        self.processes.clear()
        self.io.clear()
        self.hosts.clear()
        self._reported_dead.clear()
        self.world_size = 0

    @staticmethod
    def _signal_group(proc: subprocess.Popen, sig: int) -> None:
        if getattr(proc, "remote", False):
            # Agent-spawned worker: its pid belongs to ANOTHER host's
            # pid namespace — a local killpg on that number could hit
            # an innocent local process.  Route through the agent.
            try:
                proc.send_signal_group(sig)
            except Exception:
                pass
            return
        try:
            os.killpg(os.getpgid(proc.pid), sig)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    @staticmethod
    def _wait_all(procs, grace_s: float):
        deadline = time.time() + grace_s
        pending = [(r, p) for r, p in procs if p.poll() is None]
        while pending and time.time() < deadline:
            time.sleep(0.05)
            pending = [(r, p) for r, p in pending if p.poll() is None]
        return pending

    # ------------------------------------------------------------------

    def get_status(self) -> dict[int, dict]:
        """Process-level status (reference: process_manager.py:260-295);
        live device details come from the workers over the control plane
        via the magic layer's %dist_status."""
        out = {}
        for rank, proc in self.processes.items():
            rc = proc.poll()
            out[rank] = {
                "pid": proc.pid,
                "running": rc is None,
                "returncode": rc,
                "backend": self.backend,
            }
        return out
