"""Per-rank environment construction: backend + TPU topology assignment.

The reference assigns one CUDA GPU per rank via an explicit id list with
modulo recycling (reference: process_manager.py:107-112) and lets the
worker pin it (reference: worker.py:135-144).  On TPU the analog is chip
*partitioning*: a single host's chips are split among worker processes
with the TPU runtime's process-bounds environment, so each worker's JAX
sees only its own chip(s) and ``jax.distributed`` stitches them into one
world over ICI.

Also owns the CPU-backend env used by tests/CI — the analog of the
reference's CUDA→Gloo fallback (reference: worker.py:146-149): cross-
process gloo collectives give a real multi-process world on any box.
"""

from __future__ import annotations

import os

# v5e single-host chip grids by chip count (x, y); z is always 1 on v5e.
_V5E_GRIDS = {1: (1, 1), 2: (1, 2), 4: (2, 2), 8: (2, 4)}


def cpu_worker_env(base: dict | None = None) -> dict:
    """Env for a CPU-backend worker: force the CPU platform and gloo
    cross-process collectives; neutralize the container's TPU
    sitecustomize (which would otherwise grab the axon TPU platform in
    every python process)."""
    env = dict(base if base is not None else os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
    return env


def parse_chips(spec: str) -> list[int]:
    """Parse an explicit chip-id list (``"2,3"``) — the analog of the
    reference's ``--gpu-ids`` parse (reference: magic.py:456-459, with
    its bad-format message at magic.py:485-488)."""
    try:
        chips = [int(x.strip()) for x in spec.split(",")]
    except ValueError:
        raise ValueError(
            "Invalid chip IDs format. Use comma-separated integers "
            "(e.g. '0,1,3')") from None
    if not chips:
        raise ValueError("empty chip ID list")
    if any(c < 0 for c in chips):
        raise ValueError(f"chip IDs must be >= 0, got {chips}")
    return chips


def _carve_geometry(host: int, chips_per_worker: int):
    """``((hx, hy), cx, cy)`` when the ``host`` chip grid exists and
    divides into aligned (cx,cy) subgrid blocks; None otherwise.  The
    single source of truth for "is this carve geometry known" —
    ``_grid_blocks``, ``_process_bounds`` and ``validate_tpu_request``
    all consult it so their notions cannot diverge."""
    hgrid = _V5E_GRIDS.get(host)
    cx, cy = _V5E_GRIDS.get(chips_per_worker, (1, chips_per_worker))
    if not hgrid or hgrid[0] % cx or hgrid[1] % cy:
        return None
    return hgrid, cx, cy


def _grid_blocks(total_chips: int, chips_per_worker: int) -> list[list[int]]:
    """The aligned (cx,cy) physical subgrid blocks of a ``total_chips``
    host grid, in row-major block order — each block is the chip-id set
    one multi-chip worker may own.  Chip ids map to the physical grid
    row-major (id = x*Y + y on an (X, Y) grid), so a worker's block is
    generally NOT a consecutive id run: 2 workers x 4 chips on a (2,4)
    v5e-8 carve 2x2 subgrids {0,1,4,5} / {2,3,6,7}.  Both the default
    ``TPU_VISIBLE_CHIPS`` assignment and the explicit-chips validation
    derive from this one function so the ids can never contradict the
    declared ``TPU_CHIPS_PER_PROCESS_BOUNDS`` carve."""
    geo = _carve_geometry(total_chips, chips_per_worker)
    if geo is None:
        # No aligned carve exists; fall back to consecutive full runs
        # (partial trailing blocks are dropped — never phantom ids
        # past total_chips; the callers validate totals against
        # _V5E_GRIDS separately).
        return [list(range(b, b + chips_per_worker))
                for b in range(0, total_chips - chips_per_worker + 1,
                               chips_per_worker)]
    (hx, hy), cx, cy = geo
    return [[(ax + i) * hy + (ay + j)
             for i in range(cx) for j in range(cy)]
            for ax in range(0, hx, cx) for ay in range(0, hy, cy)]


def _process_bounds(host: int, chips_per_worker: int,
                    taken: list[list[int]]) -> str | None:
    """``TPU_PROCESS_BOUNDS`` for workers owning the ``taken`` blocks
    (sorted id lists) of a ``host``-chip grid, or None when no coherent
    rectangular process grid is derivable — the blocks aren't aligned
    subgrids of a known host grid, or they don't fill a rows × cols
    box (a diagonal pick of 2 blocks would declare 4 process slots).
    The ONE place block-grid geometry turns into bounds, shared by
    ``tpu_worker_env`` and ``validate_tpu_request``."""
    geo = _carve_geometry(host, chips_per_worker)
    if geo is None:
        return None
    (_, hy), _, cy = geo
    key = [sorted(b) for b in _grid_blocks(host, chips_per_worker)]
    if any(t not in key for t in taken):
        return None
    nby = hy // cy                            # blocks per grid row
    idx = [key.index(t) for t in taken]
    bx = {i // nby for i in idx}
    by = {i % nby for i in idx}
    if len(bx) * len(by) != len(taken):
        return None
    return f"{len(bx)},{len(by)},1"


def _chips_for_rank(chips: list[int], rank: int,
                    chips_per_worker: int) -> list[int]:
    """Rank's slice of an explicit chip list.  A short list raises
    here rather than recycling modulo (the reference recycles GPU ids,
    process_manager.py:107-112, because CUDA contexts can share a
    device; TPU runtime processes cannot share a chip, so recycling
    would pin two workers to one chip and both would die inside the
    runtime).  The validated magic path rejects short lists earlier;
    this keeps the invariant for direct callers of
    ``tpu_worker_env``/``worker_env`` too."""
    base = rank * chips_per_worker
    if base + chips_per_worker > len(chips):
        raise ValueError(
            f"chip list {chips} too short for rank {rank} x "
            f"{chips_per_worker} chip(s)/worker: TPU runtime processes "
            f"cannot share a chip, so ids are never recycled")
    if len(set(chips)) != len(chips):
        raise ValueError(
            f"duplicate ids in chip list {chips}: TPU runtime "
            f"processes cannot share a chip")
    return chips[base:base + chips_per_worker]


def tpu_worker_env(rank: int, world_size: int, *,
                   chips_per_worker: int = 1,
                   chips: list[int] | None = None,
                   host_chips: int | None = None,
                   tpu_process_base_port: int = 8476,
                   base: dict | None = None) -> dict:
    """Env for a TPU worker owning ``chips_per_worker`` chips of a
    single-host slice (v5e-8 style).

    Uses the TPU runtime's standard multi-process-per-host contract:
    ``TPU_PROCESS_BOUNDS`` / ``TPU_CHIPS_PER_PROCESS_BOUNDS`` carve the
    chip grid, ``TPU_VISIBLE_CHIPS`` pins this worker's chips, and
    ``TPU_PROCESS_ADDRESSES`` lists every worker's TPU-runtime port.
    ``chips`` pins an explicit chip set — the analog of the
    reference's ``--gpu-ids`` assignment (reference:
    process_manager.py:107-112).  Single-chip workers may pin any
    distinct ids (non-contiguous is fine, e.g. ``2,3`` on a shared
    host); multi-chip workers must each own an aligned physical
    subgrid block (see ``_grid_blocks`` — enforced pre-spawn by
    ``validate_tpu_request``).  Default is the row-major grid carve.
    ``host_chips`` is the host's probed chip count: subgrid geometry
    must be carved from the HOST grid (a 4-chip job on a v5e-8 lives
    on the (2,4) grid, where a 2x2 block is {0,1,4,5}, not {0,1,2,3}).
    Multi-host pods need per-host launch instead (SURVEY §5.8 notes
    the reference has the same single-node assumption at
    worker.py:129).
    """
    env = dict(base if base is not None else os.environ)
    total_chips = world_size * chips_per_worker
    if chips_per_worker == 1:
        grid = _V5E_GRIDS.get(total_chips)
        if grid is None:
            raise ValueError(
                f"unsupported single-host chip count {total_chips}; "
                f"supported: {sorted(_V5E_GRIDS)}")
        px, py = grid
        env["TPU_PROCESS_BOUNDS"] = f"{px},{py},1"
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
        env["TPU_VISIBLE_CHIPS"] = (
            str(_chips_for_rank(chips, rank, 1)[0])
            if chips else str(rank))
    else:
        # One worker spanning several chips (e.g. 2 workers x 4 chips).
        # Geometry is carved from the HOST grid when known (else from
        # the requested total): default chips are the first
        # ``world_size`` blocks of the row-major carve, and
        # TPU_PROCESS_BOUNDS is the rectangle those blocks span in
        # block coordinates — the same _grid_blocks geometry
        # validate_tpu_request checks explicit lists against, so the
        # ids and the declared bounds derive from one carve.
        host = host_chips if host_chips in _V5E_GRIDS else total_chips
        cx, cy = _V5E_GRIDS.get(chips_per_worker, (1, chips_per_worker))
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"{cx},{cy},1"
        blocks = _grid_blocks(host, chips_per_worker)
        if chips:
            mine = _chips_for_rank(chips, rank, chips_per_worker)
        else:
            if world_size > len(blocks):
                raise ValueError(
                    f"{world_size} worker(s) × {chips_per_worker} "
                    f"chip(s)/worker exceed the host's {len(blocks)} "
                    f"subgrid block(s) of {chips_per_worker} chips")
            mine = blocks[rank]
        env["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in mine)
        taken = ([sorted(chips[r * chips_per_worker:
                               (r + 1) * chips_per_worker])
                  for r in range(world_size)] if chips
                 else [sorted(b) for b in blocks[:world_size]])
        # validate_tpu_request rejects non-rectangular picks pre-spawn;
        # a direct caller bypassing it (or an unknown host geometry)
        # gets the linear fallback carve instead of contradictory vars.
        env["TPU_PROCESS_BOUNDS"] = (
            _process_bounds(host, chips_per_worker, taken)
            or f"1,{world_size},1")
    env["TPU_PROCESS_ADDRESSES"] = ",".join(
        f"localhost:{tpu_process_base_port + r}" for r in range(world_size))
    env["TPU_PROCESS_PORT"] = str(tpu_process_base_port + rank)
    env["CLOUD_TPU_TASK_ID"] = str(rank)
    return env


def worker_env(rank: int, world_size: int, backend: str, *,
               chips_per_worker: int = 1, chips: list[int] | None = None,
               host_chips: int | None = None,
               base: dict | None = None) -> dict:
    if backend == "cpu":
        return cpu_worker_env(base)
    if backend == "tpu":
        return tpu_worker_env(rank, world_size,
                              chips_per_worker=chips_per_worker,
                              chips=chips, host_chips=host_chips,
                              base=base)
    raise ValueError(f"unknown backend {backend!r}")


def available_tpu_chips() -> int | None:
    """Best-effort count of this host's TPU chips, without initializing
    JAX (device probes belong to the workers).  Returns None when the
    count is unknowable cheaply.

    The reference validates its GPU-id list against
    ``torch.cuda.device_count()`` before spawning (reference:
    magic.py:454-488); this is the TPU analog — device nodes first,
    then the axon tunnel's pool list.
    """
    import glob

    accel = glob.glob("/dev/accel[0-9]*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    pool = os.environ.get("PALLAS_AXON_POOL_IPS")
    if pool:
        return len([p for p in pool.split(",") if p.strip()])
    return None


def validate_tpu_request(world_size: int, chips_per_worker: int,
                         chips: list[int] | None = None) -> int | None:
    """Fail fast (before any spawn) when the requested topology cannot
    fit this host's chips — N workers dying inside the TPU runtime is a
    much worse error message.  Returns the probed host chip count (or
    None when unknowable) so the caller can feed the SAME geometry
    into ``tpu_worker_env(host_chips=...)`` without a second probe.

    With an explicit ``chips`` list, mirrors the reference's pre-spawn
    GPU-id validation (reference: magic.py:454-488): every id must
    exist on this host, and the list must cover ``-n`` workers.  Two
    departures, both because TPU runtime processes cannot share a chip
    the way CUDA contexts share a GPU: short lists are rejected here
    (the reference's API layer would recycle ids modulo, mapping two
    processes onto one device) and so are duplicate ids.
    """
    need = world_size * chips_per_worker
    have = available_tpu_chips()
    if chips is not None:
        if len(chips) < need:
            raise ValueError(
                f"Not enough chip IDs specified. Need {need} "
                f"({world_size} worker(s) × {chips_per_worker} "
                f"chip(s)), got {len(chips)}. Either specify more "
                f"chip IDs or reduce -n.")
        used = chips[:need]
        dups = sorted({c for c in used if used.count(c) > 1})
        if dups:
            raise ValueError(
                f"duplicate chip IDs {dups}: TPU runtime processes "
                f"cannot share a chip")
        if have is not None:
            invalid = sorted({c for c in used if c >= have})
            if invalid:
                raise ValueError(
                    f"Invalid chip IDs: {invalid}. Available chips: "
                    f"{list(range(have))}")
        if chips_per_worker > 1 and _carve_geometry(have, chips_per_worker):
            # TPU_CHIPS_PER_PROCESS_BOUNDS declares a contiguous
            # (cx,cy) physical subgrid per worker; a TPU_VISIBLE_CHIPS
            # set that is not such a subgrid (e.g. '0,2,4,6')
            # contradicts that carve and the runtime may reject or
            # mis-map it.  Each worker's slice must be one of the
            # aligned subgrid blocks of the host grid, and the blocks
            # together must fill a rectangle of the block grid (the
            # process grid is rectangular).  Blocks are not always
            # consecutive ids: 4 chips/worker on a (2,4) v5e-8 is
            # {0,1,4,5} / {2,3,6,7}.  (Block reuse needs no check:
            # blocks partition the id space, so reuse implies
            # duplicate ids, rejected above.)  Unknown or non-v5e host
            # geometry skips these checks entirely — trust the user,
            # as with the availability check below; never re-anchor to
            # the request size (a (1,2) block at ids [2,3] is legal on
            # a real v5e-8 even though a 2-chip grid wouldn't hold it).
            blocks = [sorted(b)
                      for b in _grid_blocks(have, chips_per_worker)]
            taken = []
            for r in range(world_size):
                sl = used[r * chips_per_worker:(r + 1) * chips_per_worker]
                if sorted(sl) not in blocks:
                    raise ValueError(
                        f"chip IDs {sl} for worker {r} do not form a "
                        f"contiguous physical subgrid of "
                        f"{chips_per_worker} chips: multi-chip workers "
                        f"carve aligned subgrids, one of {blocks}")
                taken.append(sorted(sl))
            if _process_bounds(have, chips_per_worker, taken) is None:
                raise ValueError(
                    f"chip blocks {taken} do not fill a rectangle of "
                    f"the host's block grid: the TPU process grid is "
                    f"rectangular, so the workers' blocks must span a "
                    f"full rows × cols box (a diagonal pick like "
                    f"[0,1]+[6,7] declares 4 process slots for 2 "
                    f"workers)")
    if have is not None and need > have:
        # Suggest the largest world size that both fits the host AND
        # lands on a supported grid — advice the next attempt can
        # actually follow.
        fits = [w for w in range(have // chips_per_worker, 0, -1)
                if w * chips_per_worker in _V5E_GRIDS]
        hint = (f"Use -n {fits[0]}" if fits
                else "No supported topology fits; use --backend cpu")
        raise ValueError(
            f"requested {world_size} worker(s) × {chips_per_worker} "
            f"chip(s) = {need} TPU chips, but this host has {have}. "
            f"{hint} (or --backend cpu for a CPU world).")
    if need not in _V5E_GRIDS:
        raise ValueError(
            f"unsupported single-host chip count {need}; supported: "
            f"{sorted(_V5E_GRIDS)}")
    return have


def detect_backend() -> str:
    """'tpu' if this host has TPU chips, else 'cpu'.  Checked without
    initializing JAX in the coordinator (device probes are the workers'
    job): the TPU runtime's device nodes are the cheap signal."""
    for probe in ("/dev/accel0", "/dev/vfio/0"):
        if os.path.exists(probe):
            return "tpu"
    if os.environ.get("PALLAS_AXON_POOL_IPS"):  # axon-tunneled TPU
        return "tpu"
    return "cpu"
