"""Per-rank environment construction: backend + TPU topology assignment.

The reference assigns one CUDA GPU per rank via an explicit id list with
modulo recycling (reference: process_manager.py:107-112) and lets the
worker pin it (reference: worker.py:135-144).  On TPU the analog is chip
*partitioning*: a single host's chips are split among worker processes
with the TPU runtime's process-bounds environment, so each worker's JAX
sees only its own chip(s) and ``jax.distributed`` stitches them into one
world over ICI.

Also owns the CPU-backend env used by tests/CI — the analog of the
reference's CUDA→Gloo fallback (reference: worker.py:146-149): cross-
process gloo collectives give a real multi-process world on any box.
"""

from __future__ import annotations

import os

# v5e single-host chip grids by chip count (x, y); z is always 1 on v5e.
_V5E_GRIDS = {1: (1, 1), 2: (1, 2), 4: (2, 2), 8: (2, 4)}


def cpu_worker_env(base: dict | None = None) -> dict:
    """Env for a CPU-backend worker: force the CPU platform and gloo
    cross-process collectives; neutralize the container's TPU
    sitecustomize (which would otherwise grab the axon TPU platform in
    every python process)."""
    env = dict(base if base is not None else os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
    return env


def parse_chips(spec: str) -> list[int]:
    """Parse an explicit chip-id list (``"2,3"``) — the analog of the
    reference's ``--gpu-ids`` parse (reference: magic.py:456-459, with
    its bad-format message at magic.py:485-488)."""
    try:
        chips = [int(x.strip()) for x in spec.split(",")]
    except ValueError:
        raise ValueError(
            "Invalid chip IDs format. Use comma-separated integers "
            "(e.g. '0,1,3')") from None
    if not chips:
        raise ValueError("empty chip ID list")
    if any(c < 0 for c in chips):
        raise ValueError(f"chip IDs must be >= 0, got {chips}")
    return chips


def _chips_for_rank(chips: list[int], rank: int,
                    chips_per_worker: int) -> list[int]:
    """Rank's slice of an explicit chip list, with modulo recycling
    when the list is short (parity with the reference's
    process_manager.py:107-112 fallback; the validated magic path
    rejects short lists before this can engage)."""
    base = rank * chips_per_worker
    return [chips[(base + i) % len(chips)]
            for i in range(chips_per_worker)]


def tpu_worker_env(rank: int, world_size: int, *,
                   chips_per_worker: int = 1,
                   chips: list[int] | None = None,
                   tpu_process_base_port: int = 8476,
                   base: dict | None = None) -> dict:
    """Env for a TPU worker owning ``chips_per_worker`` chips of a
    single-host slice (v5e-8 style).

    Uses the TPU runtime's standard multi-process-per-host contract:
    ``TPU_PROCESS_BOUNDS`` / ``TPU_CHIPS_PER_PROCESS_BOUNDS`` carve the
    chip grid, ``TPU_VISIBLE_CHIPS`` pins this worker's chips, and
    ``TPU_PROCESS_ADDRESSES`` lists every worker's TPU-runtime port.
    ``chips`` pins an explicit (possibly non-contiguous) chip set —
    the analog of the reference's ``--gpu-ids`` assignment (reference:
    process_manager.py:107-112); default is chips 0..N-1.  Multi-host
    pods need per-host launch instead (SURVEY §5.8 notes the reference
    has the same single-node assumption at worker.py:129).
    """
    env = dict(base if base is not None else os.environ)
    total_chips = world_size * chips_per_worker
    if chips_per_worker == 1:
        grid = _V5E_GRIDS.get(total_chips)
        if grid is None:
            raise ValueError(
                f"unsupported single-host chip count {total_chips}; "
                f"supported: {sorted(_V5E_GRIDS)}")
        px, py = grid
        env["TPU_PROCESS_BOUNDS"] = f"{px},{py},1"
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
        env["TPU_VISIBLE_CHIPS"] = (
            str(_chips_for_rank(chips, rank, 1)[0])
            if chips else str(rank))
    else:
        # One worker spanning several chips (e.g. 2 workers x 4 chips).
        env["TPU_PROCESS_BOUNDS"] = f"1,{world_size},1"
        cx, cy = _V5E_GRIDS.get(chips_per_worker, (1, chips_per_worker))
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"{cx},{cy},1"
        mine = (_chips_for_rank(chips, rank, chips_per_worker)
                if chips else
                range(rank * chips_per_worker,
                      (rank + 1) * chips_per_worker))
        env["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in mine)
    env["TPU_PROCESS_ADDRESSES"] = ",".join(
        f"localhost:{tpu_process_base_port + r}" for r in range(world_size))
    env["TPU_PROCESS_PORT"] = str(tpu_process_base_port + rank)
    env["CLOUD_TPU_TASK_ID"] = str(rank)
    return env


def worker_env(rank: int, world_size: int, backend: str, *,
               chips_per_worker: int = 1, chips: list[int] | None = None,
               base: dict | None = None) -> dict:
    if backend == "cpu":
        return cpu_worker_env(base)
    if backend == "tpu":
        return tpu_worker_env(rank, world_size,
                              chips_per_worker=chips_per_worker,
                              chips=chips, base=base)
    raise ValueError(f"unknown backend {backend!r}")


def available_tpu_chips() -> int | None:
    """Best-effort count of this host's TPU chips, without initializing
    JAX (device probes belong to the workers).  Returns None when the
    count is unknowable cheaply.

    The reference validates its GPU-id list against
    ``torch.cuda.device_count()`` before spawning (reference:
    magic.py:454-488); this is the TPU analog — device nodes first,
    then the axon tunnel's pool list.
    """
    import glob

    accel = glob.glob("/dev/accel[0-9]*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    pool = os.environ.get("PALLAS_AXON_POOL_IPS")
    if pool:
        return len([p for p in pool.split(",") if p.strip()])
    return None


def validate_tpu_request(world_size: int, chips_per_worker: int,
                         chips: list[int] | None = None) -> None:
    """Fail fast (before any spawn) when the requested topology cannot
    fit this host's chips — N workers dying inside the TPU runtime is a
    much worse error message.

    With an explicit ``chips`` list, mirrors the reference's pre-spawn
    GPU-id validation (reference: magic.py:454-488): every id must
    exist on this host, and the list must cover ``-n`` workers.  Two
    departures, both because TPU runtime processes cannot share a chip
    the way CUDA contexts share a GPU: short lists are rejected here
    (the reference's API layer would recycle ids modulo, mapping two
    processes onto one device) and so are duplicate ids.
    """
    need = world_size * chips_per_worker
    have = available_tpu_chips()
    if chips is not None:
        if len(chips) < need:
            raise ValueError(
                f"Not enough chip IDs specified. Need {need} "
                f"({world_size} worker(s) × {chips_per_worker} "
                f"chip(s)), got {len(chips)}. Either specify more "
                f"chip IDs or reduce -n.")
        used = chips[:need]
        dups = sorted({c for c in used if used.count(c) > 1})
        if dups:
            raise ValueError(
                f"duplicate chip IDs {dups}: TPU runtime processes "
                f"cannot share a chip")
        if have is not None:
            invalid = sorted({c for c in used if c >= have})
            if invalid:
                raise ValueError(
                    f"Invalid chip IDs: {invalid}. Available chips: "
                    f"{list(range(have))}")
    if have is not None and need > have:
        # Suggest the largest world size that both fits the host AND
        # lands on a supported grid — advice the next attempt can
        # actually follow.
        fits = [w for w in range(have // chips_per_worker, 0, -1)
                if w * chips_per_worker in _V5E_GRIDS]
        hint = (f"Use -n {fits[0]}" if fits
                else "No supported topology fits; use --backend cpu")
        raise ValueError(
            f"requested {world_size} worker(s) × {chips_per_worker} "
            f"chip(s) = {need} TPU chips, but this host has {have}. "
            f"{hint} (or --backend cpu for a CPU world).")
    if need not in _V5E_GRIDS:
        raise ValueError(
            f"unsupported single-host chip count {need}; supported: "
            f"{sorted(_V5E_GRIDS)}")


def detect_backend() -> str:
    """'tpu' if this host has TPU chips, else 'cpu'.  Checked without
    initializing JAX in the coordinator (device probes are the workers'
    job): the TPU runtime's device nodes are the cheap signal."""
    for probe in ("/dev/accel0", "/dev/vfio/0"):
        if os.path.exists(probe):
            return "tpu"
    if os.environ.get("PALLAS_AXON_POOL_IPS"):  # axon-tunneled TPU
        return "tpu"
    return "cpu"
