"""Multi-host worker launch: plans + SSH command construction.

The reference is hard-wired single-node (``LOCAL_RANK = rank``,
localhost master — reference: worker.py:129, process_manager.py:60);
SURVEY §5.8/§7 calls multi-host out as the structural gap.  On TPU pods
the natural unit is **one worker process per host** (each owning all
local chips; ``jax.distributed`` stitches hosts over DCN and the TPU
runtime wires ICI within the slice), so a multi-host launch is just:
run the same worker argv on every host with the right rank and a
coordinator address reachable from all of them.

This module builds that as data first — :func:`make_launch_plan`
returns per-rank ``WorkerLaunch`` records (host, argv, env overrides) —
and :func:`ssh_argv` turns a record into an ``ssh`` command line.  The
:class:`~nbdistributed_tpu.manager.process_manager.ProcessManager`
executes plans: ``host == "local"`` spawns directly (how the
integration tests drive the full path in one box), anything else spawns
the ssh proxy process, whose lifetime/stdio/kill handling is identical
to a local child's.

Host specs are strings ``"host"`` or ``"host:workers"``; multiple
workers per host are supported for cpu/test backends only — TPU host
plans are strictly one worker per host (the TPU runtime's cross-host
wiring assumes it; single-host chip carving goes through
``ProcessManager.start_workers(chips_per_worker=...)``, not a plan) —
and ambiguous configs are refused loudly rather than mis-wired.
"""

from __future__ import annotations

import dataclasses
import shlex
import sys

from ..utils import knobs
from . import topology


@dataclasses.dataclass(frozen=True)
class HostSpec:
    host: str
    workers: int = 1


@dataclasses.dataclass(frozen=True)
class WorkerLaunch:
    rank: int
    host: str            # "local" = spawn directly on this machine
    argv: tuple          # worker module command line
    env: tuple           # ((key, value), ...) overrides to ship


def parse_hosts(spec: str) -> list[HostSpec]:
    """``"h1,h2:4,local:2"`` -> [HostSpec("h1",1), HostSpec("h2",4), ...]

    Duplicate hosts are rejected loudly: ``"h1,h1:2"`` is always a
    typo (the launch plan would assign two rank ranges to one box and,
    on TPU, double-book its chips), and the merged meaning the user
    intended is ambiguous — 1+2 workers or 2?
    """
    out = []
    seen: set[str] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, n = part.partition(":")
        if not host:
            raise ValueError(f"empty host in spec {spec!r}")
        try:
            workers = int(n) if n else 1
        except ValueError:
            raise ValueError(f"bad worker count {n!r} for host {host!r}")
        if workers < 1:
            raise ValueError(f"host {host!r}: workers must be >= 1")
        if host in seen:
            raise ValueError(
                f"host {host!r} listed more than once in {spec!r} — "
                f"merge the entries (e.g. {host}:N) instead of "
                f"repeating the host")
        seen.add(host)
        out.append(HostSpec(host, workers))
    if not out:
        raise ValueError(f"no hosts in spec {spec!r}")
    return out


def make_launch_plan(hosts: list[HostSpec], *, coordinator_host: str,
                     control_port: int, dist_port: int | None,
                     backend: str, python: str = sys.executable
                     ) -> list[WorkerLaunch]:
    """Assign ranks host-major and build each worker's argv + env.

    ``coordinator_host`` must be an address every listed host can reach;
    loopback with remote hosts is rejected (the classic silent-hang
    misconfig).
    """
    dup = {h.host for h in hosts
           if sum(1 for x in hosts if x.host == h.host) > 1}
    if dup:
        # parse_hosts already refuses duplicate spec entries; this
        # guards hand-built HostSpec lists taking the same wrong turn.
        raise ValueError(f"duplicate host(s) {sorted(dup)} in the plan "
                         "— each host appears once, with its worker "
                         "count")
    remote = [h for h in hosts if h.host != "local"]
    if remote and coordinator_host in ("127.0.0.1", "localhost", ""):
        raise ValueError(
            f"coordinator_host {coordinator_host!r} is loopback but the "
            f"plan has remote hosts {[h.host for h in remote]}: workers "
            "there would dial their own loopback. Pass the coordinator's "
            "reachable address (e.g. its pod/VM IP).")
    if backend == "tpu" and any(h.workers > 1 for h in hosts):
        raise ValueError(
            "multi-host TPU runs one worker per host (each owns the "
            "host's chips). For single-host chip carving use "
            "start_workers(chips_per_worker=...) instead of a host plan.")

    # The jax.distributed coordination service is hosted by *rank 0's
    # process*, so its address must be rank 0's host — not the kernel
    # machine (which runs no JAX process).  When rank 0 is "local" it
    # shares the kernel machine and the control-plane address works.
    # The port is picked on the coordinator; as with torchrun's
    # --master-port, it is assumed free on rank 0's host too.
    dist_host = coordinator_host if hosts[0].host == "local" \
        else hosts[0].host

    world = sum(h.workers for h in hosts)
    plan: list[WorkerLaunch] = []
    rank = 0
    for h in hosts:
        for local_rank in range(h.workers):
            argv = [python, "-m", "nbdistributed_tpu.runtime.worker",
                    "--rank", str(rank), "--world-size", str(world),
                    "--coordinator-host", coordinator_host,
                    "--control-port", str(control_port),
                    "--backend", backend]
            if dist_port is not None:
                argv += ["--dist-port", str(dist_port),
                         "--dist-host", dist_host]
            env: dict[str, str] = {
                # Host labels: feed per-link fault shaping, the
                # partition sentry's failure domains, and per-host
                # status grouping (ISSUE 6).  NBD_COORD_HOST is the
                # coordinator's OWN label (its env, else "local") —
                # the worker's half of every link pair; without it a
                # relabelled coordinator would shape frames on a pair
                # the workers never match.
                "NBD_HOST": h.host,
                "NBD_COORD_HOST": knobs.get_str("NBD_HOST") or "local",
            }
            if backend == "cpu":
                # Deterministic worker env regardless of what the
                # remote login shell (or, via the ssh proxy in tests,
                # the coordinator) exports: exactly one CPU device per
                # process, gloo across processes, no accelerator
                # plugin.  Empty string = unset for all three.
                env.update({"JAX_PLATFORMS": "cpu",
                            "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
                            "XLA_FLAGS": "",
                            "PALLAS_AXON_POOL_IPS": ""})
            # backend == "tpu", one worker per host: no carving env —
            # the worker owns every local chip and jax.distributed
            # handles cross-host wiring.
            plan.append(WorkerLaunch(rank=rank, host=h.host,
                                     argv=tuple(argv),
                                     env=tuple(sorted(env.items()))))
            rank += 1
    ranks = [l.rank for l in plan]
    if ranks != list(range(world)):
        # Unreachable by construction today; a refactor that breaks
        # the host-major assignment must fail HERE, not as a silent
        # half-wired world (two workers claiming one rank deadlocks
        # jax.distributed with no error).
        raise ValueError(f"internal error: launch plan ranks {ranks} "
                         f"are not exactly 0..{world - 1}")
    return plan


def ssh_argv(launch: WorkerLaunch, *, ssh: str = "ssh",
             ssh_opts: tuple = ("-o", "BatchMode=yes")) -> list[str]:
    """The local command that runs ``launch`` on its remote host.

    ``exec env K=V ... python -m ...`` under ssh, so killing the local
    ssh process signals the remote worker (ssh forwards the session
    teardown) and remote stdio streams back through the proxy's pipe.

    Caveat: the env rides the remote command line, so values (including
    NBD_AUTH_TOKEN, the control-plane shared secret) are visible to
    `ps` on the remote host for the worker's lifetime.  The token only
    gates the coordinator's listener — acceptable on single-tenant
    workers; shared remote hosts want an ssh-config-level SendEnv
    channel instead.
    """
    remote = "exec env " + " ".join(
        f"{k}={shlex.quote(v)}" for k, v in launch.env)
    remote += " " + " ".join(shlex.quote(a) for a in launch.argv)
    return [ssh, *ssh_opts, launch.host, remote]
