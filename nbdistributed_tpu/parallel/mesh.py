"""Named-axis mesh construction for dp/tp/sp topologies.

The reference's topology knob was ``--gpu-ids`` (reference:
process_manager.py:107-112); TPU-native topology is a logical mesh over
the global device set with named axes that sharding rules refer to
(SURVEY §5.6 maps the flag surface).  These helpers are seeded into
worker namespaces and used by the model/parallel stack.
"""

from __future__ import annotations

import numpy as np


def make_mesh(axis_sizes: dict[str, int] | None = None,
              devices=None):
    """Build a ``jax.sharding.Mesh``.

    ``axis_sizes`` maps axis name -> size, in layout-major order, e.g.
    ``{"dp": 2, "tp": 4}``.  A size of -1 means "whatever is left"
    (at most one axis).  Default: 1-D data-parallel mesh over all
    devices.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {"dp": n}
    sizes = dict(axis_sizes)
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wild:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by {fixed}")
        sizes[wild[0]] = n // fixed
    total = int(np.prod(list(sizes.values())))
    if total != n:
        raise ValueError(
            f"mesh {sizes} needs {total} devices but {n} are available")
    arr = np.asarray(devices).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def shard_batch(batch, mesh, axis: str = "dp"):
    """Place a host-local batch pytree onto the mesh, sharded on the
    leading dimension over ``axis`` (replicated over other axes)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental import multihost_utils

    spec = P(axis)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: multihost_utils.host_local_array_to_global_array(
                np.asarray(x), mesh, spec), batch)
    sharding = NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


def replicate(tree, mesh):
    """Replicate a pytree across the whole mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding),
                                  tree)
