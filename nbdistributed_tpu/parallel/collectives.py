"""Eager, notebook-friendly collectives over the global JAX world.

The reference's core capability is seeding ``torch.distributed`` into the
interactive namespace so users call ``dist.all_reduce(t)`` cell by cell
(reference: worker.py:160-177, README.md:97-125).  The TPU-native
equivalent is this module, seeded as ``dist`` (plus its functions
directly): each primitive is an XLA program over the mesh of **all**
global devices, compiled via ``shard_map`` so collectives ride ICI/DCN —
no NCCL/Gloo anywhere (data-plane replacement mapped out in SURVEY §2.3,
§5.8).

Semantics follow torch.distributed where they overlap: every process
passes a host-local value of identical shape; the result is the reduced /
gathered value as seen by this process.  All functions also work in a
single-process world (they become cheap identities), so the same notebook
runs on 1 chip or a pod.

These collectives are **eager**: in a multi-device world they cannot be
traced into ``jit``/``grad`` (they move host-local values into a global
XLA program) and raise a TypeError explaining the two supported
patterns — all-reduce eagerly between jitted halves, or ``shard_map`` +
``jax.lax.psum`` for in-program collectives.  The single-process/
single-device identity path still traces fine, so 1-chip notebooks can
jit straight through them.
"""

from __future__ import annotations

import functools
import time
from typing import Any

import numpy as np

from ..observability import metrics as _obs_metrics
from ..observability.spans import maybe_span as _maybe_span
from ..runtime.collective_guard import check as _guard_check
from ..runtime.collective_guard import done as _guard_done
from ..utils.compat import shard_map as _shard_map


def _jax():
    import jax
    return jax


def _instrumented(name: str):
    """Observability wrapper for an eager collective: a
    ``collective/<op>`` span while a trace is active (one flag check
    when not) and an always-on duration histogram in the process
    metrics registry.  Metrics are resolved once, at decoration time —
    the per-call cost is one ``observe``.  Composed ops (broadcast →
    all_reduce) record both levels, mirroring their span nesting."""
    reg = _obs_metrics.registry()
    hist = reg.histogram("nbd_collective_seconds",
                         "eager collective duration", {"op": name})
    calls = reg.counter("nbd_collectives_total",
                        "eager collective calls", {"op": name})

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                with _maybe_span(f"collective/{name}", kind="collective"):
                    out = fn(*args, **kwargs)
            finally:
                # Mark the guard's progress stream not-in-flight even
                # when the op raised (hazard error, interrupt) — the
                # watchdog must not keep seeing a long-dead entry as
                # "still inside".  Nested composite internals are
                # suppressed by the guard itself.
                _guard_done(name)
            calls.inc()
            hist.observe(time.perf_counter() - t0)
            return out
        return wrapped
    return deco


@functools.lru_cache(maxsize=None)
def _proc_mesh():
    """1-D mesh over every global device, axis name ``proc``."""
    jax = _jax()
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()), ("proc",))


def world_size() -> int:
    return _jax().process_count()


def rank() -> int:
    return _jax().process_index()


def device_world() -> int:
    return _jax().device_count()


def _to_global(x, mesh):
    """Stack per-process values on a leading ``proc`` axis as a global
    array (one shard per device)."""
    jax = _jax()
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental import multihost_utils

    x = jnp.asarray(x)
    local = jnp.broadcast_to(x[None], (jax.local_device_count(),) + x.shape)
    return multihost_utils.host_local_array_to_global_array(
        np.asarray(local), mesh, P("proc"))


def _reject_tracer(x, what: str):
    """Eager collectives move host-local values into a global array,
    which cannot happen mid-trace.  Without this guard the user sees
    XLA's opaque ``__array__() was called on traced array`` — turn it
    into an actionable error instead."""
    import jax.core

    if isinstance(x, jax.core.Tracer):
        raise TypeError(
            f"{what} is an eager collective and cannot be called inside "
            "jit/grad/vmap tracing. Either call it outside the jitted "
            "function (e.g. jit the local grad step, all-reduce the "
            "grads eagerly, then jit the optimizer update), or express "
            "the collective inside the program with jax.shard_map + "
            "jax.lax.psum over a mesh axis.")


_REDUCERS = {"sum": "psum", "mean": "pmean", "max": "pmax", "min": "pmin"}


@functools.lru_cache(maxsize=None)
def _reduce_fn(mesh, prim_name: str):
    """Jitted device-mesh reduction, cached per (mesh, op) so repeated
    eager calls hit the jit cache instead of retracing."""
    jax = _jax()
    from jax.sharding import PartitionSpec as P

    prim = getattr(jax.lax, prim_name)

    @jax.jit
    @functools.partial(_shard_map, mesh=mesh, in_specs=P("proc"),
                       out_specs=P())
    def f(a):
        # Each device holds one copy on the leading axis; drop it, then
        # reduce across the mesh axis.  XLA lowers this to an ICI/DCN
        # all-reduce.
        return prim(a[0], "proc")

    return f


@functools.lru_cache(maxsize=None)
def _gather_fn(mesh):
    jax = _jax()
    from jax.sharding import PartitionSpec as P

    # check_vma off: all_gather's output is replicated over "proc" but the
    # static varying-axes analysis cannot prove it.
    @jax.jit
    @functools.partial(_shard_map, mesh=mesh, in_specs=P("proc"),
                       out_specs=P(), check_vma=False)
    def f(a):
        return jax.lax.all_gather(a[0], "proc")

    return f


@_instrumented("all_reduce")
def all_reduce(x, op: str = "sum"):
    """Elementwise reduce across all ranks; every rank gets the result
    (torch ``dist.all_reduce`` analog, but functional).

    Rank semantics hold for any local device count: the underlying XLA
    all-reduce runs over every device, and the per-process duplicate
    copies are compensated (sum is rescaled; mean/max/min are invariant
    under duplication).  With one process the call is an identity.
    """
    _guard_check("all_reduce")
    jax = _jax()
    import jax.numpy as jnp

    if op not in _REDUCERS:
        raise ValueError(f"op must be one of {sorted(_REDUCERS)}")
    if jax.process_count() == 1 and jax.local_device_count() == 1:
        return jnp.asarray(x)  # identity — works even under tracing
    _reject_tracer(x, "all_reduce")

    mesh = _proc_mesh()
    garr = _to_global(x, mesh)
    out = _reduce_fn(mesh, _REDUCERS[op])(garr).addressable_data(0)
    local = jax.local_device_count()
    if op == "sum" and local > 1:
        # Each process contributed `local` copies; undo the inflation.
        if jnp.issubdtype(out.dtype, jnp.integer):
            out = out // local
        else:
            out = out / local
    return out


@_instrumented("all_gather")
def all_gather(x):
    """Gather per-rank values; returns a stacked array with leading
    dimension = number of ranks (``dist.all_gather`` analog).
    Lowered to an XLA all-gather over ICI/DCN; per-process duplicate
    rows (when a worker owns several devices) are sliced away."""
    _guard_check("all_gather")
    jax = _jax()
    import jax.numpy as jnp

    if jax.process_count() == 1 and jax.local_device_count() == 1:
        return jnp.asarray(x)[None]
    _reject_tracer(x, "all_gather")

    mesh = _proc_mesh()
    garr = _to_global(x, mesh)
    out = _gather_fn(mesh)(garr).addressable_data(0)
    local = jax.local_device_count()
    if local > 1:
        # Device order in the mesh groups local devices per process, so
        # one row per process is every `local`-th entry.
        out = out[::local]
    return out


@_instrumented("broadcast")
def broadcast(x, root: int = 0):
    """Every process returns root's value (``dist.broadcast`` analog).
    Implemented as mask-and-sum so any root works, not just process 0
    (``multihost_utils.broadcast_one_to_all`` only supports root 0)."""
    _guard_check("broadcast")
    _check_root(root, "broadcast")
    jax = _jax()
    import jax.numpy as jnp

    if jax.process_count() == 1:
        return jnp.asarray(x)  # identity — works even under tracing
    _reject_tracer(x, "broadcast")
    x = jnp.asarray(x)
    contribution = x if rank() == root else jnp.zeros_like(x)
    return all_reduce(contribution, op="sum")


@_instrumented("barrier")
def barrier(name: str = "nbd_barrier"):
    """Block until every process arrives (``dist.barrier`` analog;
    reference uses it for %sync at worker.py:213-215)."""
    _guard_check("barrier")
    jax = _jax()
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


@functools.lru_cache(maxsize=None)
def _reduce_scatter_fn(mesh):
    """True reduce-scatter (psum_scatter): each device receives its
    reduced chunk — half the wire traffic of all-reduce + local slice."""
    jax = _jax()
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @functools.partial(_shard_map, mesh=mesh, in_specs=P("proc"),
                       out_specs=P("proc"))
    def f(a):
        return jax.lax.psum_scatter(a[0], "proc", scatter_dimension=0,
                                    tiled=True)

    return f


@_instrumented("reduce_scatter")
def reduce_scatter(x, op: str = "sum"):
    """Reduce across processes, then return this process's equal chunk of
    the leading axis (``dist.reduce_scatter`` analog).

    For ``op="sum"`` with one device per process this is a real XLA
    reduce-scatter (psum_scatter — no full all-reduce on the wire);
    other ops / multi-device processes fall back to all-reduce+slice.
    """
    _guard_check("reduce_scatter")
    jax = _jax()
    import jax.numpy as jnp

    n = jax.process_count()
    if n == 1:
        return jnp.asarray(x)  # identity — works even under tracing
    _reject_tracer(x, "reduce_scatter")
    x = jnp.asarray(x)
    if x.shape[0] % n:
        raise ValueError(f"leading dim {x.shape[0]} not divisible by "
                         f"{n} processes")
    if op == "sum" and jax.local_device_count() == 1:
        mesh = _proc_mesh()
        garr = _to_global(x, mesh)
        return _reduce_scatter_fn(mesh)(garr).addressable_data(0)
    reduced = all_reduce(x, op=op)
    chunks = jnp.split(jnp.asarray(reduced), n, axis=0)
    return chunks[rank()]


@functools.lru_cache(maxsize=None)
def _quantized_all_reduce_fn(mesh, block: int):
    """EQuARX-style quantized all-reduce (Dryden et al. /
    arXiv:2506.17615 pattern, built from XLA collectives): fp32
    reduce-scatter, then each device block-quantizes its reduced shard
    to int8 (per-block absmax scales) and the expensive all-gather
    phase moves int8 + scales instead of fp32 — ~1.6x less wire
    traffic overall, more at lower bits.  One compiled program."""
    jax = _jax()
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @functools.partial(_shard_map, mesh=mesh, in_specs=P("proc"),
                       out_specs=P(), check_vma=False)
    def f(a):
        shard = jax.lax.psum_scatter(a[0], "proc", scatter_dimension=0,
                                     tiled=True)               # (m,) fp32
        blocks = shard.reshape(-1, block)
        absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
        scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
        qg = jax.lax.all_gather(q, "proc", tiled=True)
        sg = jax.lax.all_gather(scale.astype(jnp.float32), "proc",
                                tiled=True)
        return (qg.astype(jnp.float32) * sg).reshape(-1)

    return f


@_instrumented("all_reduce_quantized")
def all_reduce_quantized(x, op: str = "sum", *, block: int = 256):
    """Approximate all-reduce with int8-quantized gather phase.

    Same contract as :func:`all_reduce` (sum/mean) but the result is
    quantized to 8 bits blockwise after the reduction — relative error
    bounded by ~1/254 per block — in exchange for moving ~1.6× fewer
    bytes (the technique of EQuARX, arXiv:2506.17615, composed here
    from XLA's own collectives).  Intended for DCN-bound gradient
    exchange; use :func:`all_reduce` when exactness matters.
    """
    _guard_check("all_reduce_quantized")
    jax = _jax()
    import jax.numpy as jnp

    if op not in ("sum", "mean"):
        raise ValueError("all_reduce_quantized supports op sum|mean")
    if jax.process_count() == 1 and jax.local_device_count() == 1:
        return jnp.asarray(x)
    _reject_tracer(x, "all_reduce_quantized")
    x = jnp.asarray(x)
    orig_shape, orig_dtype = x.shape, x.dtype

    mesh = _proc_mesh()
    n_dev = mesh.devices.size
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % (n_dev * block)
    flat = jnp.pad(flat, (0, pad))
    out = _quantized_all_reduce_fn(mesh, block)(
        _to_global(flat, mesh)).addressable_data(0)
    local = jax.local_device_count()
    if local > 1:
        out = out / local  # per-process duplicate copies, as in all_reduce
    if op == "mean":
        out = out / world_size()
    if pad:
        out = out[:-pad]
    if jnp.issubdtype(orig_dtype, jnp.integer):
        # Truncation would bias quantization noise toward zero (e.g. a
        # true 3 dequantizing to 2.996 must not become 2).
        out = jnp.round(out)
    return out.reshape(orig_shape).astype(orig_dtype)


def _check_root(root: int, what: str) -> None:
    """torch.distributed raises on an invalid root; so do we — the
    mask-and-sum broadcast would otherwise silently yield zeros and
    the root-gated returns would yield None on every rank."""
    w = world_size()
    if not 0 <= root < w:
        raise ValueError(f"{what}: root {root} out of range for "
                         f"world size {w}")


@_instrumented("scatter")
def scatter(x, root: int = 0):
    """Rank ``root`` provides a stacked ``(world, ...)`` array; every
    rank returns its own row (``dist.scatter`` analog, functional).

    XLA's collectives are symmetric, so the one-sided scatter is a
    broadcast of root's stack + a local row slice — simple and
    correct; the extra wire traffic vs a true scatter is
    ``(world-1)/world`` of the stack, acceptable at notebook scale
    (use sharded arrays + ``jax.device_put`` for bulk data placement).
    Non-root ranks still pass a same-shape array (any values) — every
    process participates, as with all eager collectives here."""
    _guard_check("scatter")
    _check_root(root, "scatter")
    jax = _jax()
    import jax.numpy as jnp

    from ..runtime.collective_guard import nested as _guard_nested

    x = jnp.asarray(x)
    w = world_size()
    if x.shape[:1] != (w,):
        raise ValueError(
            f"scatter needs a ({w}, ...) stacked array (one row per "
            f"rank), got shape {x.shape}")
    if w == 1:
        return x[0]
    with _guard_nested():   # one user-level op = one counted op
        return broadcast(x, root=root)[rank()]


@_instrumented("gather")
def gather(x, root: int = 0):
    """Gather per-rank values to ``root``: root returns the stacked
    ``(world, ...)`` array, every other rank returns None
    (``dist.gather`` analog).  Implemented over the symmetric
    all-gather; see :func:`scatter` for the symmetry note."""
    _guard_check("gather")
    _check_root(root, "gather")
    from ..runtime.collective_guard import nested as _guard_nested
    with _guard_nested():
        out = all_gather(x)
    return out if rank() == root else None


@_instrumented("reduce")
def reduce(x, root: int = 0, op: str = "sum"):
    """Reduce across ranks to ``root``: root returns the reduced
    value, every other rank returns None (``dist.reduce`` analog,
    over the symmetric all-reduce)."""
    _guard_check("reduce")
    _check_root(root, "reduce")
    from ..runtime.collective_guard import nested as _guard_nested
    with _guard_nested():
        out = all_reduce(x, op=op)
    return out if rank() == root else None


class DistNamespace:
    """``dist``-style facade seeded into worker namespaces so users who
    know torch.distributed feel at home (reference seeds ``dist`` at
    worker.py:162)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    broadcast = staticmethod(broadcast)
    barrier = staticmethod(barrier)
    reduce_scatter = staticmethod(reduce_scatter)
    scatter = staticmethod(scatter)
    gather = staticmethod(gather)
    reduce = staticmethod(reduce)

    @staticmethod
    def get_rank() -> int:
        return rank()

    @staticmethod
    def get_world_size() -> int:
        return world_size()

    def __repr__(self) -> str:
        return (f"<nbdistributed_tpu dist: rank {rank()}/"
                f"{world_size()} processes, {device_world()} devices>")


def clear_mesh_cache() -> None:
    """Reset the cached mesh and jitted collectives (for tests that
    re-enter worlds)."""
    _proc_mesh.cache_clear()
    _reduce_fn.cache_clear()
    _gather_fn.cache_clear()
    _reduce_scatter_fn.cache_clear()
    _quantized_all_reduce_fn.cache_clear()
