"""Collective-matmul overlap: ring-decomposed ``all_gather -> matmul``
and ``matmul -> reduce_scatter`` for the Megatron sequence-parallel
tensor-parallel block.

Why this exists (TPU-first rationale): in the sequence-parallel TP
layout, activations enter the MLP/attention block sharded on the
sequence axis and must be all-gathered before the column-parallel
matmul; the row-parallel output is reduce-scattered back.  Issued as
monolithic collectives, the ICI transfer and the MXU GEMM serialize:
``t_total = t_comm + t_matmul``.  Decomposing both collectives into a
ring of ``ppermute`` hops interleaved with per-chunk GEMMs lets XLA's
async collective machinery run hop ``i+1`` while chunk ``i`` is on the
MXU, hiding up to all of ``t_comm`` behind compute (the "collective
matmul" of the scaling-book / Wang et al., ASPLOS'23).  XLA can fuse
this itself in some cases (``--xla_tpu_enable_async_collective_fusion``
pass); the explicit ring makes the overlap structural — guaranteed by
dataflow, not by a scheduler heuristic — and works under ``shard_map``
where the user owns the SPMD program.

Reference parity note: the reference has no tensor parallelism at all —
its TP story is users typing broadcasts by hand
(reference: README.md:115-125).  This module is beyond-parity TPU
machinery, composing with
:func:`~nbdistributed_tpu.parallel.tensor_parallel.make_tp_train_step`
(GSPMD path) as the hand-scheduled alternative for the hot block.

All functions run **inside shard_map** over the given axis and are
fully differentiable (the transpose of ``ppermute`` is ``ppermute``,
of ``dynamic_slice`` is ``dynamic_update_slice`` — the backward is a
ring program of the same shape).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size


def allgather_matmul(x, w, axis_name: str):
    """``all_gather(x, axis) @ w``, ring-decomposed.

    Inside ``shard_map``: ``x (m, K)`` is this shard's slice of the
    row-sharded (e.g. sequence-sharded) left operand; ``w (K, n)`` is
    this shard's column slice of the weight.  Returns ``(t*m, n)`` —
    the full-length rows times the local columns, i.e. the
    column-parallel Megatron matmul with sequence-parallel input.

    Chunk ``i`` hops the ring while chunk ``i-1`` multiplies: the
    ``ppermute`` and the GEMM at each step share no dataflow edge, so
    XLA schedules them concurrently (DMA vs MXU).
    """
    t = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    m = x.shape[0]
    fwd = [(i, (i + 1) % t) for i in range(t)]
    part0 = x @ w
    y = jnp.zeros((t * m, part0.shape[1]), part0.dtype)
    buf = x
    for i in range(t):
        # buf arrived over i hops of the +1 ring: it is shard
        # (me - i)'s chunk, and lands at that row offset.
        src = (me - i) % t
        part = part0 if i == 0 else buf @ w
        y = lax.dynamic_update_slice(y, part, (src * m, 0))
        if i < t - 1:
            buf = lax.ppermute(buf, axis_name, fwd)
    return y


def matmul_reducescatter(x, w, axis_name: str):
    """``reduce_scatter(x @ w, axis)``, ring-decomposed.

    Inside ``shard_map``: ``x (M, k)`` is this shard's slice of the
    column-sharded left operand (``k = K/t``), ``w (k, N)`` the
    matching row slice of the weight — the row-parallel Megatron
    matmul, whose partial products are summed over shards and row-
    scattered: returns ``(M/t, N)``, this shard's row chunk of the
    reduced result (sequence-parallel output layout).

    The accumulator for destination shard ``d`` starts at shard
    ``d+1``, visits every shard once (each adds its local partial for
    rows ``[d*M/t, (d+1)*M/t)``), and terminates at ``d`` — so each
    hop's transfer overlaps the next chunk's GEMM.
    """
    t = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    M = x.shape[0]
    if M % t:
        raise ValueError(f"leading dim {M} not divisible by axis size {t}")
    m = M // t
    fwd = [(i, (i + 1) % t) for i in range(t)]
    acc = None
    for i in range(t):
        j = (me - 1 - i) % t
        part = lax.dynamic_slice(x, (j * m, 0), (m, x.shape[1])) @ w
        acc = part if acc is None else acc + part
        if i < t - 1:
            acc = lax.ppermute(acc, axis_name, fwd)
    return acc


def megatron_sp_block(x, w_up, w_down, axis_name: str, act=jax.nn.gelu):
    """The canonical sequence-parallel TP MLP with both collectives
    ring-overlapped: ``reduce_scatter(act(all_gather(x) @ w_up) @
    w_down)``.

    Inside ``shard_map``: ``x (S/t, D)`` sequence-sharded activations,
    ``w_up (D, F/t)`` column-parallel, ``w_down (F/t, D)``
    row-parallel.  Returns ``(S/t, D)`` — same layout as the input, so
    blocks chain without extra collectives.
    """
    h = act(allgather_matmul(x, w_up, axis_name))
    return matmul_reducescatter(h, w_down, axis_name)
