"""Tensor parallelism: sharding-rule application and a combined-mesh
train-step builder.

The reference's TP story is "users broadcast params and type the
all_reduce themselves" (README.md:115-125).  Here TP is declarative:
parameter pytrees carry ``PartitionSpec`` rules (e.g.
``models.transformer.param_shardings``), this module places them on the
mesh, and XLA compiles the Megatron pattern (column-parallel matmul →
row-parallel matmul → one all-reduce) from the sharding lattice.
"""

from __future__ import annotations

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P


def apply_shardings(tree, mesh, rules):
    """Place ``tree`` on ``mesh`` according to a matching pytree of
    ``PartitionSpec`` rules."""
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree, rules,
        is_leaf=lambda x: isinstance(x, P))


def sharding_tree(mesh, rules):
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), rules,
        is_leaf=lambda x: isinstance(x, P))


def make_tp_train_step(loss_fn, optimizer, mesh, param_rules, *,
                       dp_axis: str = "dp", donate: bool = True,
                       opt_state_sh=None):
    """Combined dp×tp train step: params sharded by ``param_rules``
    (tp axes; ``None`` = fully replicated, i.e. pure DDP), batch sharded
    on ``dp_axis``.

    Optimizer-state sharding: with ``opt_state_sh=None`` the state
    passes through (optax states are zeros_like the params, so
    initializing from already-sharded params gives param-sharded state
    for free); passing an explicit ``NamedSharding`` pytree pins it —
    :mod:`~nbdistributed_tpu.parallel.zero` uses this to add the ZeRO-1
    dp axis, with this one step definition serving both."""
    repl = NamedSharding(mesh, P())
    param_sh = sharding_tree(mesh, param_rules) if param_rules is not None \
        else repl
    batch_sh = NamedSharding(mesh, P(dp_axis))

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(param_sh, opt_state_sh, batch_sh),
        out_shardings=(param_sh, opt_state_sh, repl),
        donate_argnums=(0, 1) if donate else ())
