"""Tensor parallelism: sharding-rule application and a combined-mesh
train-step builder.

The reference's TP story is "users broadcast params and type the
all_reduce themselves" (README.md:115-125).  Here TP is declarative:
parameter pytrees carry ``PartitionSpec`` rules (e.g.
``models.transformer.param_shardings``), this module places them on the
mesh, and XLA compiles the Megatron pattern (column-parallel matmul →
row-parallel matmul → one all-reduce) from the sharding lattice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P


def apply_shardings(tree, mesh, rules):
    """Place ``tree`` on ``mesh`` according to a matching pytree of
    ``PartitionSpec`` rules."""
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree, rules,
        is_leaf=lambda x: isinstance(x, P))


def sharding_tree(mesh, rules):
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), rules,
        is_leaf=lambda x: isinstance(x, P))


def make_tp_train_step(loss_fn, optimizer, mesh, param_rules, *,
                       dp_axis: str = "dp", donate: bool = True,
                       opt_state_sh=None, accum_steps: int = 1,
                       accum_rules=None, guard: bool = False):
    """Combined dp×tp train step: params sharded by ``param_rules``
    (tp axes; ``None`` = fully replicated, i.e. pure DDP), batch sharded
    on ``dp_axis``.

    Optimizer-state sharding: with ``opt_state_sh=None`` the state
    passes through (optax states are zeros_like the params, so
    initializing from already-sharded params gives param-sharded state
    for free); passing an explicit ``NamedSharding`` pytree pins it —
    :mod:`~nbdistributed_tpu.parallel.zero` uses this to add the ZeRO-1
    dp axis, with this one step definition serving both.

    ``accum_steps > 1`` splits the batch's leading axis into that many
    microbatches inside the compiled step (``lax.scan``, fp32 gradient
    accumulator) — same numerics as the full batch for mean losses,
    activation memory divided by ``accum_steps``.

    ``accum_rules``: optional pytree of ``PartitionSpec`` for the fp32
    accumulator (ZeRO-2; see :mod:`~nbdistributed_tpu.parallel.zero`).
    Without accumulation, gradients are transient inside the fused
    step and XLA already consumes them reduce-scattered when the
    optimizer state is ZeRO-sharded — the accumulator is the one
    place a *persistent* full-size gradient buffer exists, so it is
    the one place ZeRO-2 sharding buys memory (4 bytes/param/replica
    → /dp).

    ``guard=True`` (ISSUE 19) fuses a device-side integrity check into
    the step: the fp32 global grad-norm² (one extra reduction riding
    the same compiled program — no extra host sync) gates the update,
    so a non-finite gradient *skips* it and params/opt state come back
    bitwise unchanged.  The step then returns a 4-tuple
    ``(params, opt_state, loss, aux)`` with replicated device scalars
    ``aux = {"v": float32[3]}`` — the ``v`` lane packs ``[ok, loss,
    gnorm]`` for a single-transfer host resolve — that the host-side
    :class:`~nbdistributed_tpu.resilience.trainguard.TrainGuard`
    resolves one step late — the skip decision itself never leaves
    the device."""
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    repl = NamedSharding(mesh, P())
    param_sh = sharding_tree(mesh, param_rules) if param_rules is not None \
        else repl
    batch_sh = NamedSharding(mesh, P(dp_axis))

    def grads_of(params, batch):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn)(params, batch)

        d = mesh.shape[dp_axis]

        def split(x):
            B = x.shape[0]
            if B % (d * accum_steps):
                raise ValueError(
                    f"batch leading dim {B} not divisible by "
                    f"dp({d}) * accum_steps({accum_steps})")
            # Microbatch i = the i-th contiguous chunk of every
            # device's local shard, so the split is a device-local
            # reshape (a naive (accum, B/accum) reshape would need an
            # all-to-all to re-lay the dp shards every step).  Mean
            # losses are permutation-invariant, so numerics match the
            # full batch.
            mb = (x.reshape(d, accum_steps, B // (d * accum_steps),
                            *x.shape[1:])
                  .swapaxes(0, 1)
                  .reshape(accum_steps, B // accum_steps, *x.shape[1:]))
            return jax.lax.with_sharding_constraint(
                mb, NamedSharding(
                    mesh, P(None, dp_axis, *[None] * (x.ndim - 1))))

        micro = jax.tree_util.tree_map(split, batch)

        def pin_accum(t):
            if accum_rules is None:
                return t
            return jax.tree_util.tree_map(
                lambda a, r: jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, r)),
                t, accum_rules, is_leaf=lambda x: isinstance(x, P))

        def body(carry, mb):
            gsum, lsum = carry
            l, g = jax.value_and_grad(loss_fn)(params, mb)
            gsum = pin_accum(jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32), gsum, g))
            return (gsum, lsum + l), None

        zeros = pin_accum(jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params))
        (gsum, lsum), _ = jax.lax.scan(body, (zeros, jnp.float32(0.0)),
                                       micro)
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / accum_steps).astype(p.dtype), gsum, params)
        return lsum / accum_steps, grads

    def step(params, opt_state, batch):
        loss, grads = grads_of(params, batch)
        if not guard:
            updates, new_state = optimizer.update(grads, opt_state,
                                                  params)
            return optax.apply_updates(params, updates), new_state, loss
        # Fused finite check: the fp32 sum of squares over every grad
        # leaf is non-finite iff any leaf holds a NaN/inf (NaN
        # propagates through the sum; inf² = inf), and doubles as the
        # global grad-norm² — one reduction, computed inside the same
        # program, where the dp all-reduce already paid for the
        # gradients.  The optimizer update runs inside a scalar-pred
        # ``lax.cond``: the skip branch passes the OLD buffers through
        # bitwise intact, and the healthy branch pays no extra select
        # pass over params/opt state (a per-leaf ``where`` gate costs
        # ~20% of a CPU step in pure memory traffic).
        gn_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree_util.tree_leaves(grads))
        ok = jnp.isfinite(gn_sq) & jnp.isfinite(loss)

        def do_update(_):
            updates, new_state = optimizer.update(grads, opt_state,
                                                  params)
            return optax.apply_updates(params, updates), new_state

        def skip_update(_):
            return params, opt_state

        out_params, out_state = jax.lax.cond(ok, do_update, skip_update,
                                             None)
        # Packed verdict [ok, loss, gnorm] as the ONLY aux output:
        # the host resolves a whole step with one 12-byte transfer,
        # and the jit call materializes one extra array per step
        # instead of three.
        aux = {"v": jnp.stack([ok.astype(jnp.float32),
                               loss.astype(jnp.float32),
                               jnp.sqrt(gn_sq)])}
        return out_params, out_state, loss, aux

    out_sh = ((param_sh, opt_state_sh, repl, repl) if guard
              else (param_sh, opt_state_sh, repl))
    return jax.jit(
        step,
        in_shardings=(param_sh, opt_state_sh, batch_sh),
        out_shardings=out_sh,
        donate_argnums=(0, 1) if donate else ())
