"""Ulysses-style all-to-all sequence parallelism.

The second long-context strategy beside ring attention (ring.py): instead
of streaming K/V chunks around the ring, two ``all_to_all`` collectives
re-shard the activations from sequence-sharded to *head*-sharded and
back, so every device runs ordinary full-sequence attention on its slice
of heads (DeepSpeed-Ulysses pattern; the reference has no sequence
parallelism at all, SURVEY §5.7).

Trade-off vs ring: communication is 2 all-to-alls of the activations
(O(B·S·H·D / n) per device, one shot each way, ideal on ICI's all-to-all
bandwidth) instead of n ppermute hops, and the inner attention is a
plain local kernel — so it composes directly with the Pallas flash
kernel (ops/attention.py).  The constraint is that the head counts
(H *and* Hkv) must be divisible by the mesh axis size, which ring does
not require.  GQA is native: K/V all-to-all at Hkv heads (H/Hkv× less
traffic than pre-expanding), and the local attention keeps the group
ratio.

Layouts inside ``shard_map`` (local views, mesh axis size n; K/V the
same with H -> Hkv):

    (B, S/n, H, D)  --all_to_all(split H, concat S)-->  (B, S, H/n, D)
        ... full-sequence GQA attention over H/n q heads ...
    (B, S, H/n, D)  --all_to_all(split S, concat H)-->  (B, S/n, H, D)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from ..utils.compat import shard_map


@functools.lru_cache(maxsize=None)
def _ulysses_fn(mesh, axis: str, causal: bool, scale: float,
                use_flash: bool, batch_axis: str | None = None,
                head_axis: str | None = None,
                window: int | None = None,
                with_segments: bool = False):
    spec = P(batch_axis, axis, head_axis, None)
    inner = functools.partial(_ulysses_inner, axis=axis, causal=causal,
                              scale=scale, use_flash=use_flash,
                              window=window)
    in_specs = (spec, spec, spec)
    if with_segments:
        in_specs = in_specs + (P(batch_axis, axis),)
    return jax.jit(shard_map(
        inner, mesh=mesh, in_specs=in_specs, out_specs=spec,
        check_vma=False))


def ulysses_attention(q, k, v, mesh, *, axis: str = "sp",
                      causal: bool = True, scale: float | None = None,
                      use_flash: bool = False,
                      batch_axis: str | None = None,
                      head_axis: str | None = None,
                      window: int | None = None,
                      segment_ids=None):
    """Exact attention with Q/K/V sequence-sharded over ``mesh[axis]``,
    computed head-parallel after an all-to-all re-shard.

    q: (B, S, H, D) and k/v: (B, S, Hkv, D) global arrays, S sharded
    over ``mesh[axis]``; returns output with the same sharding.
    Requires ``H % n == 0`` and ``Hkv % n == 0`` — K/V are NOT
    expanded: their all-to-alls move ``H/Hkv``× less data than
    pre-expanding would, and the local attention runs GQA natively
    (each device holds H/n query heads against Hkv/n KV heads, the
    same group ratio).  ``use_flash=True`` runs the Pallas flash
    kernel as the local attention (TPU path; forward and blockwise
    backward); default is the XLA reference.

    ``batch_axis``/``head_axis``: mesh axes the batch and head dims are
    sharded over (dp/tp composition).  With ``head_axis`` the per-shard
    head counts ``H/tp`` and ``Hkv/tp`` are what the sequence
    all-to-alls split, so both must still be divisible by the ``axis``
    size; omitting these when activations ARE dp/tp-sharded makes GSPMD
    all-gather and compute attention replicated.
    """
    n = mesh.shape[axis]
    H, Hkv = q.shape[2], k.shape[2]
    t = mesh.shape[head_axis] if head_axis is not None else 1
    if head_axis is not None and (H % t or Hkv % t):
        raise ValueError(
            f"head_axis {head_axis!r} (size {t}) must divide both "
            f"H={H} and Hkv={Hkv}")
    if (H // t) % n != 0 or (Hkv // t) % n != 0:
        raise ValueError(
            f"ulysses_attention needs both per-shard head counts "
            f"divisible by the {axis!r} axis: H/t={H // t}, "
            f"Hkv/t={Hkv // t}, n={n}. Use ring_attention for head "
            "counts that don't split.")
    if H % Hkv != 0:
        raise ValueError(
            f"n_heads {H} not divisible by n_kv_heads {Hkv}")
    if v.shape[2] != Hkv:
        raise ValueError(
            f"k/v head counts differ: {Hkv} vs {v.shape[2]}")
    from ..ops.attention import check_window
    check_window(window, causal)
    if segment_ids is not None:
        # Packed-document masking: each device's local segment chunk is
        # all-gathered to full length inside the shard_map (tiny int32
        # vs the activation all-to-alls) and the local full-sequence
        # attention applies the mask.
        if segment_ids.shape != q.shape[:2]:
            raise ValueError(
                f"segment_ids shape {segment_ids.shape} != (B, S) "
                f"{q.shape[:2]}")
        if q.shape[1] != k.shape[1]:
            raise ValueError("segment_ids requires Sq == Sk")
    D = q.shape[-1]
    scale = scale if scale is not None else float(1.0 / np.sqrt(D))
    fn = _ulysses_fn(mesh, axis, causal, scale, use_flash,
                     batch_axis, head_axis, window,
                     with_segments=segment_ids is not None)
    if segment_ids is None:
        return fn(q, k, v)
    return fn(q, k, v, jnp.asarray(segment_ids, jnp.int32))


def _ulysses_inner(q, k, v, seg=None, *, axis: str, causal: bool,
                   scale: float, use_flash: bool,
                   window: int | None = None):
    from ..ops import attention_reference, flash_attention

    # seq-sharded -> head-sharded: gather the full sequence, keep H/n.
    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    # After the all-to-all each device holds the FULL sequence on its
    # head slice, so the sliding window is just the local kernels'
    # ordinary window argument — and packed-document segments are the
    # full-length ids, all-gathered from the sequence shards.
    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    seg_full = (None if seg is None else
                jax.lax.all_gather(seg, axis, axis=1, tiled=True))
    if use_flash:
        out = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                              window=window, segment_ids=seg_full)
    else:
        out = attention_reference(qh, kh, vh, causal=causal,
                                  scale=scale, window=window,
                                  segment_ids=seg_full)
    return heads_to_seq(out.astype(q.dtype))
