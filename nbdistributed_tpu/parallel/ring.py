"""Ring attention: exact attention over sequence-sharded inputs.

Long-context training shards the *sequence* axis across devices (the
reference has no sequence-parallel story at all: SURVEY §5.7).  Ring
attention keeps the O(S^2) score matrix virtual: each device holds one
sequence chunk of Q locally and streams K/V chunks around the ring via
``jax.lax.ppermute`` (ICI neighbor exchange), folding each visiting
chunk into an online-softmax accumulator — so communication overlaps
compute blockwise and peak memory stays O(S/n · S/n) per step.

This is the shard_map/ppermute formulation the scaling-book recipe
prescribes; the same math as the flash kernel's inner loop
(ops/attention.py), lifted from k-blocks to ring hops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30


@functools.lru_cache(maxsize=None)
def _ring_fn(mesh, axis: str, causal: bool, scale: float):
    """Jitted ring kernel, cached per (mesh, axis, causal, scale) so
    repeated training-loop calls hit the jit cache instead of retracing."""
    n = mesh.shape[axis]
    spec = P(None, axis, None, None)
    inner = functools.partial(_ring_inner, axis=axis, n=n, causal=causal,
                              scale=scale)
    return jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))


def ring_attention(q, k, v, mesh, *, axis: str = "sp",
                   causal: bool = True, scale: float | None = None):
    """Exact (causal) attention with Q/K/V sharded on ``axis`` along the
    sequence dimension.

    q/k/v: (B, S, H, D) global arrays whose S dimension is sharded over
    ``mesh[axis]``; returns attention output with the same sharding.
    n_kv_heads must equal n_heads here (expand GQA before sharding).
    """
    D = q.shape[-1]
    scale = scale if scale is not None else float(1.0 / np.sqrt(D))
    return _ring_fn(mesh, axis, causal, scale)(q, k, v)


def _ring_inner(q, k, v, *, axis: str, n: int, causal: bool, scale: float):
    B, Sq, H, Dh = q.shape
    my = jax.lax.axis_index(axis)
    qf = q.astype(jnp.float32) * scale
    acc = jnp.zeros((B, Sq, H, Dh), jnp.float32)
    m = jnp.full((B, H, Sq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Sq, 1), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(step, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (my - step) % n  # which chunk we currently hold
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal:
            qi = (my * Sq
                  + jax.lax.broadcasted_iota(jnp.int32, (Sq, Sq), 0))
            ki = (src * Sq
                  + jax.lax.broadcasted_iota(jnp.int32, (Sq, Sq), 1))
            s = jnp.where((ki <= qi)[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                       # (B,H,Sq,Sk)
        corr = jnp.exp(m - m_new)                    # (B,H,Sq,1)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        acc_new = acc * corr.transpose(0, 2, 1, 3) + pv
        # Rotate K/V to the next device; overlapped with the next
        # step's compute by XLA's async collective scheduling.
        k_next = jax.lax.ppermute(k_cur, axis, perm)
        v_next = jax.lax.ppermute(v_cur, axis, perm)
        return acc_new, m_new, l_new, k_next, v_next

    acc, m, l, _, _ = jax.lax.fori_loop(0, n, body, (acc, m, l, k, v))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)
