"""Ring attention: exact attention over sequence-sharded inputs.

Long-context training shards the *sequence* axis across devices (the
reference has no sequence-parallel story at all: SURVEY §5.7).  Ring
attention keeps the O(S^2) score matrix virtual: each device holds one
sequence chunk of Q locally and streams K/V chunks around the ring via
``jax.lax.ppermute`` (ICI neighbor exchange), folding each visiting
chunk into an online-softmax accumulator — so communication overlaps
compute blockwise and peak memory stays sub-quadratic per step.

GQA is native end-to-end: K/V ride the ring at ``n_kv_heads`` (hop
traffic ``H/Hkv``× smaller than pre-expanding) AND stay at Hkv inside
the local attention — the einsum path groups the query heads in the
einsums, and the Pallas kernels grid over (batch, kv-head) with the
group as a batch dim of the q block, so no expanded K/V buffer exists
anywhere, on the wire or in HBM.

Two inner paths:

* ``use_flash=False`` (default, any backend): grouped-einsum online
  softmax — differentiable through plain autodiff.
* ``use_flash=True`` (the TPU path): every hop runs the Pallas flash
  kernel (ops/attention.py) with chunk offsets for cross-chunk causal
  masking; hop results are folded by their logsumexp.  The custom VJP
  re-rings K/V through the blockwise Pallas backward — a ring hop is
  just a k-block at scale, and k-blocks are independent given the
  global (lse, delta) — so no (Sq, Sk) tensor exists in either
  direction, per hop or globally.

This is the shard_map/ppermute formulation the scaling-book recipe
prescribes; the same math as the flash kernel's inner loop, lifted
from k-blocks to ring hops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from ..utils.compat import shard_map

_NEG_INF = -1e30


@functools.lru_cache(maxsize=None)
def _ring_fn(mesh, axis: str, causal: bool, scale: float,
             use_flash: bool, schedule: str,
             batch_axis: str | None = None,
             head_axis: str | None = None,
             window: int | None = None,
             with_segments: bool = False):
    """Jitted ring kernel, cached per (mesh, axis, causal, scale, path)
    so repeated training-loop calls hit the jit cache instead of
    retracing.  ``batch_axis``/``head_axis`` put the embarrassingly
    parallel batch and head dims on their mesh axes (dp/tp) — the ring
    math never mixes them, so the inner is unchanged; without them the
    shard_map would declare B and H replicated and GSPMD would
    all-gather dp/tp-sharded activations at every call."""
    n = mesh.shape[axis]
    spec = P(batch_axis, axis, head_axis, None)
    if schedule == "zigzag":
        inner = _make_ring_flash_zigzag(axis, n, scale, window=window,
                                        with_segments=with_segments)
    elif use_flash:
        inner = _make_ring_flash(axis, n, causal, scale, window=window,
                                 with_segments=with_segments)
    else:
        inner = functools.partial(_ring_inner, axis=axis, n=n,
                                  causal=causal, scale=scale,
                                  window=window)
    in_specs = (spec, spec, spec)
    if with_segments:
        # Segment ids are per (batch, position): sequence-sharded like
        # q, replicated over heads.
        in_specs = in_specs + (P(batch_axis, axis),)
    return jax.jit(shard_map(
        inner, mesh=mesh, in_specs=in_specs, out_specs=spec,
        check_vma=False))


def ring_attention(q, k, v, mesh, *, axis: str = "sp",
                   causal: bool = True, scale: float | None = None,
                   use_flash: bool = False, schedule: str = "plain",
                   batch_axis: str | None = None,
                   head_axis: str | None = None,
                   window: int | None = None,
                   segment_ids=None):
    """Exact (causal) attention with Q/K/V sharded on ``axis`` along the
    sequence dimension.

    q: (B, S, H, D) and k/v: (B, S, Hkv, D) global arrays whose S
    dimension is sharded over ``mesh[axis]``; returns attention output
    with the same sharding.  ``H % Hkv == 0`` (grouped-query) — K/V are
    NOT expanded: they circulate the ring at Hkv heads.
    ``use_flash=True`` runs the Pallas flash kernel per hop (forward
    and backward); the default grouped-einsum path works on any
    backend.

    ``schedule="zigzag"`` is the load-balanced causal schedule: inputs
    must be in zigzag order (:func:`zigzag_shard` — device d holds
    global chunks d and 2n-1-d), and the output comes back in the same
    order (:func:`zigzag_unshard` restores it).  With plain chunking,
    causality idles device 0 on every hop but the first while device
    n-1 computes on all of them — the ring's wall-clock is the
    *unmasked* cost.  Zigzag gives every device ~2 half-chunk blocks
    of real work per hop, halving causal ring step time at scale.
    Requires ``causal=True`` and ``use_flash=True`` (only the Pallas
    path actually *skips* masked blocks; a masked einsum computes them
    anyway), and S divisible by 2n.

    ``batch_axis``/``head_axis``: mesh axes the batch and head dims are
    sharded over (dp/tp composition) — batch and heads are
    embarrassingly parallel through the ring, so these just extend the
    shard_map specs; omitting them when activations ARE dp/tp-sharded
    makes GSPMD all-gather and compute attention replicated.
    ``head_axis`` needs ``Hkv`` divisible by that axis (each shard then
    keeps whole GQA groups: q heads [t·H/tp, (t+1)·H/tp) attend exactly
    kv heads [t·Hkv/tp, (t+1)·Hkv/tp)).
    """
    H, D = q.shape[2], q.shape[-1]
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"n_heads {H} not divisible by n_kv_heads {Hkv}")
    if head_axis is not None and Hkv % mesh.shape[head_axis]:
        raise ValueError(
            f"head_axis {head_axis!r} (size {mesh.shape[head_axis]}) "
            f"must divide n_kv_heads {Hkv} so each shard keeps whole "
            f"GQA groups")
    if v.shape[2] != Hkv:
        raise ValueError(f"k/v head counts differ: {Hkv} vs {v.shape[2]}")
    if schedule not in ("plain", "zigzag"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if schedule == "zigzag":
        n = mesh.shape[axis]
        if not causal:
            raise ValueError("zigzag is a causal-balance schedule; "
                             "use schedule='plain' for non-causal")
        if not use_flash:
            raise ValueError(
                "zigzag requires use_flash=True: only the Pallas path "
                "skips masked blocks (a masked einsum computes them "
                "anyway, so zigzag would buy nothing)")
        if q.shape[1] % (2 * n):
            raise ValueError(f"zigzag needs S divisible by 2n="
                             f"{2 * n}, got S={q.shape[1]}")
    from ..ops.attention import check_window
    check_window(window, causal)
    if segment_ids is not None:
        # Packed-document masking: each device's q-chunk segments stay
        # local; the K-chunk segments ride the ring with K/V (a tiny
        # int32 extra rider).  Hops whose chunks share no segment
        # self-heal through the lse fold (weight 0).
        # Zigzag composes too: the segment array must be in zigzag
        # order like q/k/v (zigzag_shard it with them) — the fold
        # slices its half-chunks exactly as it slices K/V.
        if segment_ids.shape != q.shape[:2]:
            raise ValueError(
                f"segment_ids shape {segment_ids.shape} != (B, S) "
                f"{q.shape[:2]}")
        if q.shape[1] != k.shape[1]:
            raise ValueError("segment_ids requires Sq == Sk")
    scale = scale if scale is not None else float(1.0 / np.sqrt(D))
    fn = _ring_fn(mesh, axis, causal, scale, use_flash, schedule,
                  batch_axis, head_axis, window,
                  with_segments=segment_ids is not None)
    if segment_ids is None:
        return fn(q, k, v)
    return fn(q, k, v, jnp.asarray(segment_ids, jnp.int32))


def zigzag_order(S: int, n: int):
    """Permutation putting a (B, S, ...) sequence into zigzag layout:
    position p of the reordered sequence holds original index
    ``order[p]``.  Sharding the result contiguously over n devices
    gives device d the original chunks d and 2n-1-d."""
    if S % (2 * n):
        raise ValueError(f"S={S} not divisible by 2n={2 * n}")
    C = S // (2 * n)
    idx = []
    for d in range(n):
        idx.extend(range(d * C, (d + 1) * C))
        idx.extend(range((2 * n - 1 - d) * C, (2 * n - d) * C))
    return np.asarray(idx)


def zigzag_shard(x, n: int, axis: int = 1):
    """Reorder a global array's sequence axis into zigzag layout (do
    this once on the data, before sequence-sharding it)."""
    return jnp.take(x, jnp.asarray(zigzag_order(x.shape[axis], n)),
                    axis=axis)


def zigzag_unshard(x, n: int, axis: int = 1):
    """Inverse of :func:`zigzag_shard`."""
    order = zigzag_order(x.shape[axis], n)
    inv = np.empty_like(order)
    inv[order] = np.arange(len(order))
    return jnp.take(x, jnp.asarray(inv), axis=axis)


def _intervals_touch(q_ivals, k_ivals, window: int) -> bool:
    """Whether any (query position, key position) pair drawn from the
    given half-open global-index intervals is visible under the causal
    + sliding-window mask (``ki <= qi`` and ``ki > qi - window``).
    Only called with a real window — hop_plan early-returns the full
    ring otherwise."""
    for q0, q1 in q_ivals:
        for k0, k1 in k_ivals:
            if k0 <= q1 - 1 and k1 - 1 >= q0 - window + 1:
                return True
    return False


def hop_plan(n: int, s_local: int, window: int | None,
             schedule: str = "plain", *, sk_local: int | None = None):
    """The static set of ring steps that can contribute under a sliding
    window: step ``s`` gives device ``my`` the K/V chunk of device
    ``(my - s) % n``; a step is in the plan iff ANY device has a
    mask-visible (q-interval, k-interval) pair there (the plan must be
    device-uniform — every device executes the same SPMD program).

    Without a window every causal step contributes somewhere (device
    n-1 sees all of history), so the plan is ``range(n)``.  With a
    window of w tokens over chunks of C tokens, the plain schedule's
    plan collapses to a prefix of ``1 + ceil((w-1)/C)`` steps and the
    zigzag schedule's to a short prefix + suffix (zigzag pairs chunk d
    with chunk 2n-1-d, whose window neighbors arrive at ring distance
    n-1, n-2, ...) — O(window/C) hops instead of n, and K/V jump
    straight across skipped steps in one ``ppermute``.

    ``s_local`` is the per-device Q length; ``sk_local`` the per-device
    K length when they differ (cross-length attention in the plain
    schedule; zigzag requires them equal).
    """
    if window is None:
        return tuple(range(n))
    sk_local = s_local if sk_local is None else sk_local
    steps = []
    for s in range(n):
        for my in range(n):
            src = (my - s) % n
            if schedule == "zigzag":
                C = s_local // 2
                q_iv = [(my * C, (my + 1) * C),
                        ((2 * n - 1 - my) * C, (2 * n - my) * C)]
                k_iv = [(src * C, (src + 1) * C),
                        ((2 * n - 1 - src) * C, (2 * n - src) * C)]
            else:
                q_iv = [(my * s_local, (my + 1) * s_local)]
                k_iv = [(src * sk_local, (src + 1) * sk_local)]
            if _intervals_touch(q_iv, k_iv, window):
                steps.append(s)
                break
    return tuple(steps)


def _jump(arrs, axis: str, n: int, d: int):
    """Move every device's chunk ``d`` ring positions forward in ONE
    ppermute per array (a skipped-hop jump is a single collective, not
    d neighbor exchanges)."""
    if d % n == 0:
        return list(arrs)
    perm = [(j, (j + d) % n) for j in range(n)]
    return [jax.lax.ppermute(a, axis, perm) for a in arrs]


def _run_hops(plan, n: int, axis: str, my, fold, carry, riders,
              home: int = 0):
    """Shared hop-loop driver for every ring path (einsum/flash fwd,
    flash/zigzag bwd): run ``carry, riders = fold(carry, riders, src)``
    at each plan step with the K/V (and any gradient-accumulator)
    ``riders`` rotated between steps.

    Full plan -> the classic fori_loop of neighbor ppermutes (one
    compiled body, n trips).  Pruned plan (sliding window) -> unrolled,
    with a single ppermute jumping each gap.  ``home``: how many
    trailing riders (dk/dv accumulators) must end on their owning
    device — the fori path returns them home by construction (n
    rotations), the plan path jumps them back by ``-plan[-1]``.
    """
    riders = tuple(riders)
    if len(plan) == n:
        perm = [(j, (j + 1) % n) for j in range(n)]

        def body(step, state):
            c, r = state
            c, r = fold(c, r, (my - step) % n)
            return c, tuple(jax.lax.ppermute(x, axis, perm) for x in r)

        return jax.lax.fori_loop(0, n, body, (carry, riders))
    prev = 0
    for s in plan:
        riders = tuple(_jump(riders, axis, n, s - prev))
        prev = s
        carry, riders = fold(carry, riders, (my - s) % n)
    if home:
        riders = riders[:-home] + tuple(
            _jump(riders[-home:], axis, n, -plan[-1]))
    return carry, riders


def _ring_inner(q, k, v, seg=None, *, axis: str, n: int, causal: bool,
                scale: float, window: int | None = None):
    """Grouped-einsum online-softmax ring (local view inside shard_map).

    q: (B, Sq, H, D) local chunk; k/v: (B, Sk, Hkv, D) rotating chunks;
    ``seg``: optional (B, Sq) local segment ids (the K-side copy rides
    the ring as an extra rider — packed-document masking).
    """
    B, Sq, H, Dh = q.shape
    Hkv = k.shape[2]
    g = H // Hkv
    my = jax.lax.axis_index(axis)
    qf = (q.astype(jnp.float32) * scale).reshape(B, Sq, Hkv, g, Dh)
    acc = jnp.zeros((B, Sq, Hkv, g, Dh), jnp.float32)
    m = jnp.full((B, Hkv, g, Sq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, Hkv, g, Sq, 1), jnp.float32)

    def fold(carry, riders, src):
        acc, m, l = carry
        if seg is None:
            k_cur, v_cur = riders
            kseg_cur = None
        else:
            k_cur, v_cur, kseg_cur = riders
        Sk = k_cur.shape[1]
        s = jnp.einsum("bqkgd,bskd->bkgqs", qf,
                       k_cur.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal or seg is not None:
            keep = jnp.ones((1, Sq, Sk), bool)
            if causal:
                qi = (my * Sq + jax.lax.broadcasted_iota(
                    jnp.int32, (Sq, Sk), 0))
                ki = (src * Sk + jax.lax.broadcasted_iota(
                    jnp.int32, (Sq, Sk), 1))
                ck = ki <= qi
                if window is not None:
                    ck = ck & (ki > qi - window)
                keep = keep & ck[None]
            if seg is not None:
                keep = keep & (seg[:, :, None] == kseg_cur[:, None, :])
            s = jnp.where(keep[:, None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                       # (B,Hkv,g,Sq,Sk)
        corr = jnp.exp(m - m_new)                    # (B,Hkv,g,Sq,1)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bkgqs,bskd->bqkgd", p,
                        v_cur.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        return ((acc * corr.transpose(0, 3, 1, 2, 4) + pv, m_new,
                 l_new), riders)

    plan = hop_plan(n, Sq, window if causal else None,
                    sk_local=k.shape[1])
    riders = (k, v) if seg is None else (k, v, seg)
    (acc, m, l), _ = _run_hops(plan, n, axis, my, fold, (acc, m, l),
                               riders)
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2, 4)
    return out.reshape(B, Sq, H, Dh).astype(q.dtype)


# ----------------------------------------------------------------------
# Flash (Pallas) inner path

def _wrap_vjp(rf_fwd, rf_bwd, with_segments: bool):
    """The custom_vjp trailer shared by the plain and zigzag flash
    builders: custom_vjp needs a FIXED arity, so build the exact-arity
    wrapper per variant around the shared fwd/bwd bodies (rf_bwd always
    returns a 4-tuple whose last entry is the segment cotangent —
    float0 for int ids, None when absent — truncated to 3 for the
    segment-free variant)."""
    if with_segments:
        @jax.custom_vjp
        def rf(q, k, v, seg):
            return rf_fwd(q, k, v, seg)[0]

        rf.defvjp(lambda q, k, v, seg: rf_fwd(q, k, v, seg), rf_bwd)
        return rf

    @jax.custom_vjp
    def rf(q, k, v):
        return rf_fwd(q, k, v)[0]

    rf.defvjp(lambda q, k, v: rf_fwd(q, k, v),
              lambda res, g: rf_bwd(res, g)[:3])
    return rf


def _fold_hop(O, L, o_j, lse_j, B, Sq):
    """One online-softmax fold of a hop contribution (o_j, lse_j) into
    the running (O, L) — the numerically delicate core shared by the
    plain and zigzag schedules."""
    L_new = jnp.logaddexp(L, lse_j)
    w_old = _hop_weights(jnp.exp(L - L_new), B, Sq)
    w_j = _hop_weights(jnp.exp(lse_j - L_new), B, Sq)
    return O * w_old + o_j.astype(jnp.float32) * w_j, L_new


def _hop_weights(w, B, Sq):
    """(B*Hkv, group, Sq_pad) fold-layout weights -> (B, Sq, H, 1)
    (head h = kv_head * group + g, matching _fold_q_gqa)."""
    BHkv, group, Sq_pad = w.shape
    Hkv = BHkv // B
    return (w.reshape(B, Hkv, group, Sq_pad)
            .transpose(0, 3, 1, 2)
            .reshape(B, Sq_pad, Hkv * group)[:, :Sq, :, None])


def _make_ring_flash(axis: str, n: int, causal: bool, scale: float,
                     block_q: int | None = None,
                     block_k: int | None = None,
                     window: int | None = None,
                     with_segments: bool = False):
    """Builds the shard_map inner for the Pallas ring with exact
    gradients: forward folds per-hop (out, lse) pairs; backward re-rings
    K/V through the blockwise dq/dkv kernels using the saved global
    logsumexp (hops are independent given (lse, delta), exactly like
    k-blocks inside one kernel call).  ``with_segments``: the inner
    takes a fourth (B, Sq) segment-id chunk; its K-side copy rides the
    ring with K/V and each hop's kernel call applies the packed-
    document mask in both passes (a hop sharing no segment self-heals
    to weight 0 through the lse fold)."""
    from ..ops.attention import (_block_sizes, _flash_backward_folded,
                                 _flash_bwd_prep, _flash_forward,
                                 _use_interpret)


    def _rf_fwd(q, k, v, seg=None):
        B, Sq, H, D = q.shape
        Sk, Hkv = k.shape[1], k.shape[2]
        bq, bk = _block_sizes(block_q, block_k, Sq, Sk, D, H // Hkv)
        interp = _use_interpret()
        my = jax.lax.axis_index(axis)
        Sq_pad = -(-Sq // bq) * bq
        O = jnp.zeros((B, Sq, H, D), jnp.float32)
        L = jnp.full((B * Hkv, H // Hkv, Sq_pad), _NEG_INF, jnp.float32)

        def fold(carry, riders, src):
            # step 0 is always the diagonal chunk (src == my), so L is
            # real from the first fold and fully-masked later hops
            # (lse ~ -inf) get weight exp(-inf - L) = 0.
            O, L = carry
            if seg is None:
                k_cur, v_cur = riders
                kseg_cur = None
            else:
                k_cur, v_cur, kseg_cur = riders
            o_j, lse_j = _flash_forward(
                q, k_cur, v_cur, causal=causal, scale=scale,
                block_q=bq, block_k=bk, interpret=interp,
                offsets=(my * Sq, src * Sk), window=window,
                segment_ids=seg, kv_segment_ids=kseg_cur)
            return _fold_hop(O, L, o_j, lse_j, B, Sq), riders

        plan = hop_plan(n, Sq, window if causal else None,
                        sk_local=Sk)
        riders = (k, v) if seg is None else (k, v, seg)
        (O, L), _ = _run_hops(plan, n, axis, my, fold, (O, L), riders)
        out = O.astype(q.dtype)
        return out, (q, k, v, out, L, seg)

    def _rf_bwd(res, g):
        q, k, v, out, L, seg = res
        B, Sq, H, D = q.shape
        Sk, Hkv = k.shape[1], k.shape[2]
        bq, bk = _block_sizes(block_q, block_k, Sq, Sk, D, H // Hkv)
        interp = _use_interpret()
        my = jax.lax.axis_index(axis)
        # Hop-invariant work — the q/dO folds and the delta reduction —
        # happens once, not n times (only k/v change per hop).
        qt, got, delta = _flash_bwd_prep(q, out, g, bq, k.shape[2])
        dq0 = jnp.zeros((B, Sq, H, D), jnp.float32)
        dk0 = jnp.zeros(k.shape, jnp.float32)
        dv0 = jnp.zeros(v.shape, jnp.float32)

        def fold(dq, riders, src):
            # dk/dv accumulators ride WITH their chunk (trailing
            # riders): each chunk collects its gradient contributions
            # as it visits every device, then lands home.
            if seg is None:
                k_cur, v_cur, dk_cur, dv_cur = riders
                kseg_cur = None
            else:
                k_cur, v_cur, kseg_cur, dk_cur, dv_cur = riders
            dq_j, dk_j, dv_j = _flash_backward_folded(
                qt, got, delta, L, k_cur, v_cur, B=B, Sq=Sq,
                q_dtype=q.dtype, causal=causal, scale=scale,
                block_q=bq, block_k=bk, interpret=interp,
                offsets=(my * Sq, src * Sk), window=window,
                segment_ids=seg, kv_segment_ids=kseg_cur)
            rest = (dk_cur + dk_j.astype(dk_cur.dtype),
                    dv_cur + dv_j.astype(dv_cur.dtype))
            head = ((k_cur, v_cur) if seg is None
                    else (k_cur, v_cur, kseg_cur))
            return dq + dq_j.astype(jnp.float32), head + rest

        plan = hop_plan(n, Sq, window if causal else None,
                        sk_local=Sk)
        riders = ((k, v, dk0, dv0) if seg is None
                  else (k, v, seg, dk0, dv0))
        dq, out_riders = _run_hops(plan, n, axis, my, fold, dq0,
                                   riders, home=2)
        dk, dv = out_riders[-2], out_riders[-1]
        grads = (dq.astype(q.dtype), dk.astype(k.dtype),
                 dv.astype(v.dtype))
        if seg is None:
            return grads + (None,)
        return grads + (np.zeros(seg.shape, jax.dtypes.float0),)

    return _wrap_vjp(_rf_fwd, _rf_bwd, with_segments)


def _make_ring_flash_zigzag(axis: str, n: int, scale: float,
                            block_q: int | None = None,
                            block_k: int | None = None,
                            window: int | None = None,
                            with_segments: bool = False):
    """Zigzag causal ring (local view: the two half-chunks d and
    2n-1-d, concatenated).  Every hop runs four half-pair Pallas calls
    with exact global offsets; causal block-skip inside the kernel
    makes the never-attending pairs near-free, so per-hop work is ~2
    half-blocks on EVERY device — the load-balanced schedule.  Exact
    gradients via the same per-pair blockwise backward, with dk/dv
    half-accumulators riding the ring home."""
    from ..ops.attention import (_block_sizes, _flash_backward_folded,
                                 _flash_bwd_prep, _flash_forward,
                                 _use_interpret)


    def _offs(idx, C):
        """Global offsets of owner ``idx``'s two half-chunks."""
        return (idx * C, (2 * n - 1 - idx) * C)

    def _rf_fwd(q, k, v, seg=None):
        B, Sq, H, D = q.shape
        Hkv = k.shape[2]
        C = Sq // 2
        G = H // Hkv
        bq, bk = _block_sizes(block_q, block_k, C, C, D, H // Hkv)
        interp = _use_interpret()
        my = jax.lax.axis_index(axis)
        C_pad = -(-C // bq) * bq
        q_offs = _offs(my, C)
        qh = (q[:, :C], q[:, C:])
        qsegh = (None, None) if seg is None else (seg[:, :C], seg[:, C:])
        O = [jnp.zeros((B, C, H, D), jnp.float32) for _ in range(2)]
        L = [jnp.full((B * Hkv, G, C_pad), _NEG_INF, jnp.float32)
             for _ in range(2)]

        def fold(carry, riders, src):
            Oa, La, Ob, Lb = carry
            if seg is None:
                k_cur, v_cur = riders
                kseg_cur = None
            else:
                k_cur, v_cur, kseg_cur = riders
            k_offs = _offs(src, C)
            Os, Ls = [Oa, Ob], [La, Lb]
            # Step 0 folds real data first for both q halves: (qa, ka)
            # is qa's diagonal and (qb, ka) is fully unmasked, so each
            # L[qi] is finite from its first fold (fully-masked pairs
            # surface lse ~ -inf and weight to zero, as in the plain
            # schedule).
            for qi in range(2):
                for ki in range(2):
                    o_j, lse_j = _flash_forward(
                        qh[qi], k_cur[:, ki * C:(ki + 1) * C],
                        v_cur[:, ki * C:(ki + 1) * C],
                        causal=True, scale=scale, block_q=bq,
                        block_k=bk, interpret=interp,
                        offsets=(q_offs[qi], k_offs[ki]),
                        window=window,
                        segment_ids=qsegh[qi],
                        kv_segment_ids=(
                            None if kseg_cur is None else
                            kseg_cur[:, ki * C:(ki + 1) * C]))
                    Os[qi], Ls[qi] = _fold_hop(Os[qi], Ls[qi], o_j,
                                               lse_j, B, C)
            return (Os[0], Ls[0], Os[1], Ls[1]), riders

        # Windowed zigzag plans are a short prefix + suffix (chunk d's
        # pair 2n-1-d meets its window neighbors at ring distance n-1,
        # n-2, ...); K/V jump across the gap in one ppermute.
        plan = hop_plan(n, Sq, window, "zigzag")
        riders = (k, v) if seg is None else (k, v, seg)
        (Oa, La, Ob, Lb), _ = _run_hops(
            plan, n, axis, my, fold, (O[0], L[0], O[1], L[1]), riders)
        out = jnp.concatenate([Oa, Ob], axis=1).astype(q.dtype)
        return out, (q, k, v, out, La, Lb, seg)

    def _rf_bwd(res, g):
        q, k, v, out, La, Lb, seg = res
        B, Sq, H, D = q.shape
        Hkv = k.shape[2]
        C = Sq // 2
        bq, bk = _block_sizes(block_q, block_k, C, C, D, H // Hkv)
        interp = _use_interpret()
        my = jax.lax.axis_index(axis)
        q_offs = _offs(my, C)
        Ls = (La, Lb)
        qsegh = (None, None) if seg is None else (seg[:, :C], seg[:, C:])
        # Hoisted per-half backward prep (hop-invariant).
        prep = [_flash_bwd_prep(q[:, h * C:(h + 1) * C],
                                out[:, h * C:(h + 1) * C],
                                g[:, h * C:(h + 1) * C], bq, Hkv)
                for h in range(2)]
        dq0 = [jnp.zeros((B, C, H, D), jnp.float32) for _ in range(2)]
        dk0 = jnp.zeros(k.shape, jnp.float32)
        dv0 = jnp.zeros(v.shape, jnp.float32)

        def fold(carry, riders, src):
            dqa, dqb = carry
            if seg is None:
                k_cur, v_cur, dk_cur, dv_cur = riders
                kseg_cur = None
            else:
                k_cur, v_cur, kseg_cur, dk_cur, dv_cur = riders
            k_offs = _offs(src, C)
            dqs = [dqa, dqb]
            for qi in range(2):
                qt, got, delta = prep[qi]
                for ki in range(2):
                    dq_j, dk_j, dv_j = _flash_backward_folded(
                        qt, got, delta, Ls[qi],
                        k_cur[:, ki * C:(ki + 1) * C],
                        v_cur[:, ki * C:(ki + 1) * C],
                        B=B, Sq=C, q_dtype=q.dtype, causal=True,
                        scale=scale, block_q=bq, block_k=bk,
                        interpret=interp,
                        offsets=(q_offs[qi], k_offs[ki]),
                        window=window,
                        segment_ids=qsegh[qi],
                        kv_segment_ids=(
                            None if kseg_cur is None else
                            kseg_cur[:, ki * C:(ki + 1) * C]))
                    dqs[qi] = dqs[qi] + dq_j.astype(jnp.float32)
                    sl = slice(ki * C, (ki + 1) * C)
                    dk_cur = dk_cur.at[:, sl].add(
                        dk_j.astype(jnp.float32))
                    dv_cur = dv_cur.at[:, sl].add(
                        dv_j.astype(jnp.float32))
            head = ((k_cur, v_cur) if seg is None
                    else (k_cur, v_cur, kseg_cur))
            return (dqs[0], dqs[1]), head + (dk_cur, dv_cur)

        plan = hop_plan(n, Sq, window, "zigzag")
        riders = ((k, v, dk0, dv0) if seg is None
                  else (k, v, seg, dk0, dv0))
        (dqa, dqb), out_riders = _run_hops(
            plan, n, axis, my, fold, (dq0[0], dq0[1]), riders, home=2)
        dk, dv = out_riders[-2], out_riders[-1]
        dq = jnp.concatenate([dqa, dqb], axis=1)
        grads = (dq.astype(q.dtype), dk.astype(k.dtype),
                 dv.astype(v.dtype))
        if seg is None:
            return grads + (None,)
        return grads + (np.zeros(seg.shape, jax.dtypes.float0),)

    return _wrap_vjp(_rf_fwd, _rf_bwd, with_segments)
