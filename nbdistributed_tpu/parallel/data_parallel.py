"""Data-parallel training: the DDP capability, the XLA way.

The reference demonstrates DDP through user-space HF Accelerate in its
notebook (00_accelerate.ipynb cells 36-40) and hand-written all_reduce
loops (README.md:97-111).  TPU-native DDP needs no wrapper class at all:
replicate params, shard the batch on the ``dp`` mesh axis, and jit — the
gradient all-reduce is inserted by XLA from the sharding lattice.  This
module packages that recipe.
"""

from __future__ import annotations

from . import mesh as mesh_mod


def make_ddp_step(loss_fn, optimizer, mesh, *, dp_axis: str = "dp",
                  donate: bool = True, guard: bool = False):
    """Build a jitted DDP train step.

    ``loss_fn(params, batch) -> scalar``.  Params/opt state are
    replicated; the batch arrives sharded on ``dp_axis``; XLA turns the
    replicated-gradient requirement into an ICI all-reduce.

    DDP is the all-replicated special case of the tensor-parallel step
    builder — one step body to maintain (grad clipping, loss scaling,
    etc. land in one place).

    Returns ``step(params, opt_state, batch) -> (params, opt_state,
    loss)``; with ``guard=True`` (ISSUE 19) the step instead returns
    ``(params, opt_state, loss, aux)`` and skips the update on
    non-finite gradients — see
    :func:`~nbdistributed_tpu.parallel.tensor_parallel.make_tp_train_step`.
    """
    from . import tensor_parallel
    return tensor_parallel.make_tp_train_step(
        loss_fn, optimizer, mesh, param_rules=None, dp_axis=dp_axis,
        donate=donate, guard=guard)


def ddp_init(params, opt_state, mesh):
    """Replicate params + optimizer state across the mesh (the
    ``accelerator.prepare`` analog)."""
    return (mesh_mod.replicate(params, mesh),
            mesh_mod.replicate(opt_state, mesh))
