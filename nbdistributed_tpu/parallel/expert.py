"""Expert parallelism: capacity-based MoE dispatch over an ``ep`` mesh
axis.

The reference has no MoE/expert-parallel support (SURVEY §2.3: "Expert
parallel (EP/MoE) — Absent"); this module goes beyond parity with a
TPU-first design.  Instead of per-token gather/scatter (dynamic shapes
XLA cannot tile), routing is expressed as dense one-hot dispatch/combine
einsums with a fixed per-expert capacity — the GShard/Switch recipe:

* every shape is static, so the whole layer lives inside one ``jit``;
* expert weights carry a leading ``(n_experts,)`` axis sharded over the
  ``ep`` mesh axis, and a sharding constraint on the dispatched
  activations ``(E, C, D)`` makes GSPMD compile the token exchange as an
  ``all_to_all`` over ICI — the hand-written NCCL alltoall of
  GPU MoE stacks falls out of the sharding lattice instead;
* over-capacity tokens are dropped (they pass through the residual),
  bounding memory and keeping the MXU batched.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.compat import shard_map


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.bfloat16) -> dict:
    """Router + stacked SwiGLU expert weights (leading E axis)."""
    from ..utils import fan_in_normal

    kr, kg, ku, kd = jax.random.split(key, 4)

    def normal(k, shape, fan_in):
        return fan_in_normal(k, shape, fan_in, dtype)

    E, D, F = n_experts, d_model, d_ff
    return {
        # fp32 router: gating is numerically delicate and tiny.
        "router": jax.random.normal(kr, (D, E), jnp.float32) * 0.02,
        "w_gate": normal(kg, (E, D, F), D),
        "w_up": normal(ku, (E, D, F), D),
        "w_down": normal(kd, (E, F, D), F),
    }


def moe_param_shardings(ep_axis: str = "ep", tp_axis: str | None = None,
                        leading=()) -> dict:
    """PartitionSpec rules for :func:`init_moe_params` trees.  Experts
    shard over ``ep_axis``; optionally the ffn dim also shards over
    ``tp_axis`` (combined ep×tp).  ``leading`` prefixes extra axes (the
    models stack a (n_layers,) axis in front)."""
    lead = tuple(leading)
    return {
        "router": P(*lead, None, None),
        "w_gate": P(*lead, ep_axis, None, tp_axis),
        "w_up": P(*lead, ep_axis, None, tp_axis),
        "w_down": P(*lead, ep_axis, tp_axis, None),
    }


def compute_capacity(num_tokens: int, n_experts: int, top_k: int,
                     capacity_factor: float) -> int:
    """Per-expert token capacity C; multiple of 8 for TPU-friendly
    (8,128) tiling of the (E, C, D) dispatched activations."""
    cap = int(capacity_factor * top_k * num_tokens / n_experts)
    return max(8, -(-cap // 8) * 8)


def top_k_routing(logits, top_k: int):
    """Normalized top-k gates.  logits (T, E) fp32 ->
    gates (T, k), expert_idx (T, k), probs (T, E)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gates, expert_idx = jax.lax.top_k(probs, top_k)
    gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    return gates, expert_idx, probs


def make_dispatch(gates, expert_idx, n_experts: int, capacity: int,
                  token_mask=None):
    """Dense dispatch/combine tensors from routing decisions.

    Position of each (token, choice) inside its expert's capacity buffer
    is a cumulative count in choice-major order, so every token's first
    choice outranks any token's second choice — the Switch priority
    rule.  ``token_mask`` (T,) bool: masked-out tokens take NO capacity
    slot (they do not merely get zero gates — they are invisible to
    other tokens' slot competition).  Returns ``dispatch`` (T, E, C)
    {0,1} and ``combine`` (T, E, C) = dispatch * gate.
    """
    T, k = expert_idx.shape
    onehot = jax.nn.one_hot(expert_idx, n_experts,
                            dtype=jnp.float32)        # (T, k, E)
    if token_mask is not None:
        onehot = onehot * token_mask.astype(jnp.float32)[:, None, None]
    flat = onehot.transpose(1, 0, 2).reshape(k * T, n_experts)
    pos = jnp.cumsum(flat, axis=0) - flat             # (k*T, E)
    pos = pos.reshape(k, T, n_experts).transpose(1, 0, 2)  # (T, k, E)
    keep = onehot * (pos < capacity)                  # drop over-capacity
    slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                          dtype=jnp.float32)          # (T, k, E, C)
    slot = slot * keep[..., None]
    dispatch = jnp.sum(slot, axis=1)                  # (T, E, C)
    combine = jnp.sum(slot * gates[:, :, None, None], axis=1)
    return dispatch, combine


def load_balance_loss(probs, expert_idx, n_experts: int,
                      token_mask=None):
    """Switch-style auxiliary loss: n_experts * Σ_e f_e · P_e, where
    f_e = fraction of tokens whose FIRST choice is e and P_e = mean
    router probability of e.  Minimized (=1) at uniform routing.
    ``token_mask`` excludes masked-out tokens from both means."""
    first = jax.nn.one_hot(expert_idx[:, 0], n_experts, dtype=jnp.float32)
    if token_mask is None:
        f = jnp.mean(first, axis=0)
        p = jnp.mean(probs, axis=0)
    else:
        m = token_mask.astype(jnp.float32)[:, None]
        n = jnp.maximum(jnp.sum(m), 1.0)
        f = jnp.sum(first * m, axis=0) / n
        p = jnp.sum(probs * m, axis=0) / n
    return n_experts * jnp.sum(f * p)


def _expert_linear(xe, w, spec: str):
    """Per-expert einsum where ``w`` is a plain array or an int8
    weight-only quantized leaf ``{"q8", "s"}`` (models/quant.py).  The
    scales are per (expert, output-channel) — constant along the
    contraction dim — so they commute with the einsum exactly as in
    ``transformer.qlinear``: the dot reads raw int8 and the rescale is
    one fused multiply on the (E, C, out) activation."""
    from ..models.transformer import is_quantized
    if is_quantized(w):
        y = jnp.einsum(spec, xe, w["q8"].astype(xe.dtype))
        return (y.astype(jnp.float32) * w["s"]).astype(xe.dtype)
    return jnp.einsum(spec, xe, w)


def _route_sort(expert_idx, E: int, token_mask=None):
    """The ONE routing-sort prologue shared by the sparse and dropless
    paths: flatten (T, k) choice-major (choice-major ordering is what
    makes the Switch priority rule and mask semantics line up), relabel
    masked tokens to the sentinel expert E (sorting past every real
    segment), and stable-sort by expert.

    Returns (order, e_sorted, tok, counts): the argsort, the sorted
    expert ids, the source token id per sorted row, and the
    ``bincount(length=E+1)`` including the sentinel bin."""
    T, k = expert_idx.shape
    flat_e = expert_idx.T.reshape(-1)             # choice-major (kT,)
    if token_mask is not None:
        flat_e = jnp.where(jnp.tile(token_mask, k), flat_e, E)
    order = jnp.argsort(flat_e, stable=True)
    e_sorted = flat_e[order]
    counts = jnp.bincount(flat_e, length=E + 1)   # [..., masked bin]
    return order, e_sorted, (order % T).astype(jnp.int32), counts


def _ragged_expert_linear(xs, w, group_sizes, e_sorted):
    """``ragged_dot`` over expert segments, supporting int8 weight-only
    quantized leaves: the per-(expert, output-channel) scales become a
    per-ROW rescale gathered by each row's expert id (constant along
    the contraction dim, so the grouped dot still reads raw int8)."""
    from ..models.transformer import is_quantized
    if is_quantized(w):
        y = jax.lax.ragged_dot(xs, w["q8"].astype(xs.dtype),
                               group_sizes)
        s_rows = w["s"][jnp.clip(e_sorted, 0, w["s"].shape[0] - 1), 0]
        return (y.astype(jnp.float32) * s_rows).astype(xs.dtype)
    return jax.lax.ragged_dot(xs, w.astype(xs.dtype), group_sizes)


def _dropless_ffn(xt, params, gates, expert_idx, E: int,
                  token_mask=None):
    """MegaBlocks-style dropless expert compute: sort the (token,
    choice) pairs by expert and run the SwiGLU as grouped matmuls over
    the variable-size segments (``jax.lax.ragged_dot``) — every routed
    token is computed, no capacity buffer exists, and compute is
    exactly sum_e n_e GEMM rows (what the MXU would do with perfect
    per-expert batching).

    Masked tokens sort into a sentinel bin PAST every real segment
    (group_sizes covers only real experts, so ragged_dot's uncovered
    tail rows are zeros) and their gate weight is zeroed — both belts.
    """
    T, D = xt.shape
    order, e_sorted, tok, counts = _route_sort(expert_idx, E,
                                               token_mask)
    keep = e_sorted < E
    group_sizes = counts[:E].astype(jnp.int32)

    xs = jnp.where(keep[:, None], xt[tok], 0)     # (kT, D)
    h = (jax.nn.silu(_ragged_expert_linear(
            xs, params["w_gate"], group_sizes, e_sorted))
         * _ragged_expert_linear(xs, params["w_up"], group_sizes,
                                 e_sorted))
    rows = _ragged_expert_linear(h, params["w_down"], group_sizes,
                                 e_sorted)        # (kT, D)
    g_sorted = gates.T.reshape(-1)[order]
    w = jnp.where(keep, g_sorted, 0.0).astype(xt.dtype)
    return jnp.zeros((T, D), xt.dtype).at[tok].add(rows * w[:, None])


def _dropless_ffn_ep(xt, params, logits, top_k: int, E: int, mesh,
                     ep_axis: str, capacity_factor: float,
                     token_mask=None, capacity: int | None = None,
                     token_axes: tuple = ("dp",)):
    """Expert-parallel dropless: hierarchical per-token-shard routing
    feeding locally dropless ``ragged_dot`` segments — no global
    collective anywhere on the token path.

    True dropless dispatch (variable per-expert group sizes) cannot
    cross an SPMD shard boundary — a static bound is needed somewhere.
    Earlier revisions bounded a global (ep, Cs, D) exchange buffer and
    let GSPMD compile the token movement, but the routing sort ran on
    the GLOBALLY flattened (kT,) choice array: with tokens sharded
    over a data axis, GSPMD lowers that sort (and the sorted (kT, D)
    row gather feeding the buffer) as all-gather-shaped collectives —
    fine at bench scale, quadratic wire cost at pod scale.

    This version keeps every step shard-local (a ``shard_map`` over
    the token axes × ``ep_axis``):

    * tokens stay sharded over ``token_axes`` (activations between
      layers are replicated over ``ep``, so each (token-shard, ep)
      device already holds its token block — dispatch needs NO
      exchange at all, only a local sort of ``kT/n_dp`` choices);
    * each device selects the rows routed to ITS ``E/ep`` experts into
      a static ``(Cs, D)`` buffer, ``Cs = ceil(cf·k·T_loc/ep)`` pooled
      over the shard's experts (an explicit per-expert ``capacity``
      pools to ``(E/ep)·capacity``) — drops only at whole-(token-
      shard, ep) overflow, vanishing once the bound reaches
      ``k·T_loc``;
    * the SwiGLU runs as three ``ragged_dot`` grouped matmuls over the
      variable-size local expert segments (every received row
      computed);
    * combine is one ``psum`` over ``ep`` of the (T_loc, D) partial
      outputs — the single collective in the layer, riding ICI.

    Takes the raw router ``logits`` rather than precomputed
    gates/indices: ``lax.top_k`` lowers to XLA's TopK custom call,
    which GSPMD does not partition over sharded rows (it all-gathers
    the (T, E) probs) — running the top-k on each shard's local
    logits block inside the shard_map keeps routing collective-free
    and is exact (top-k is row-wise).

    The ep-redundant sort (each ep shard re-sorts its token block's
    choices) trades ``n_ep``× duplicated O(kT_loc log kT_loc) integer
    work for zero token-exchange collectives — integer sorts are noise
    next to the expert GEMMs on the MXU.  ``token_axes`` names the
    mesh axes the flattened token dim is sharded over (axes absent
    from the mesh are ignored; a token count not divisible by the
    token-shard product falls back to replicated-token semantics).
    """
    from ..models.transformer import is_quantized

    T, D = xt.shape
    k = top_k
    n_ep = mesh.shape[ep_axis]
    if E % n_ep:
        raise ValueError(f"n_experts {E} not divisible by ep axis "
                         f"size {n_ep}")
    E_loc = E // n_ep
    tok_axes = tuple(a for a in token_axes
                     if a in mesh.shape and a != ep_axis)
    n_tok = 1
    for a in tok_axes:
        n_tok *= mesh.shape[a]
    if n_tok == 1 or T % n_tok:
        tok_axes, n_tok = (), 1
    T_loc = T // n_tok
    kT_loc = k * T_loc
    # Same formula as the per-expert paths, pooled at shard level:
    # "experts" = shards, so the bound is ceil(cf·k·T_loc/ep) rounded
    # to 8.  An explicit ``capacity`` keeps its dense/sparse meaning —
    # per-EXPERT — and pools to E_loc·capacity per shard, so a caller
    # switching dispatch modes with a tuned per-expert value gets at
    # least the headroom the other modes gave (plus the pooling).
    Cs = (E_loc * capacity if capacity is not None
          else compute_capacity(T_loc, n_ep, k, capacity_factor))
    Cs = min(Cs, kT_loc)   # a shard never receives more than kT rows

    def wspec(w):
        if is_quantized(w):
            return {"q8": P(ep_axis, None, None),
                    "s": P(ep_axis, None, None)}
        return P(ep_axis, None, None)

    tok_entry = tok_axes if tok_axes else None
    mask = (jnp.ones((T,), bool) if token_mask is None else token_mask)

    def local_ffn(x, lg, tm, wg, wu, wd):
        # x (T_loc, D); lg (T_loc, E) router logits; wg/wu/wd local
        # (E_loc, ...).  Routing (softmax + top-k + sort) is computed
        # here, on the shard's rows — row-wise ops, exact vs global.
        j = jax.lax.axis_index(ep_axis)
        g, ei, _ = top_k_routing(lg, k)
        order, e_sorted, tok, counts = _route_sort(ei, E, tm)
        counts_e = counts[:E]
        starts_e = jnp.cumsum(counts_e) - counts_e        # (E,)
        lo = j * E_loc
        # This shard's segment is rows [starts_e[lo], starts_e[lo] +
        # sum of its expert counts): expert ids ascending => shard
        # segments contiguous in the sorted order.
        start_shard = starts_e[lo]
        in_shard = (e_sorted >= lo) & (e_sorted < lo + E_loc)
        pos = jnp.arange(kT_loc, dtype=jnp.int32) - start_shard
        keep = in_shard & (pos < Cs)
        slot = jnp.where(keep, pos, Cs).astype(jnp.int32)
        xs = jnp.where(keep[:, None], x[tok], 0)
        buf = jnp.zeros((Cs, D), x.dtype).at[slot].set(
            xs, mode="drop")                              # (Cs, D)
        # Per-local-expert group sizes after the Cs cut: expert e's
        # rows sit at within-shard positions [off_e, off_e + n_e).
        # (dynamic_slice: ``lo`` is a traced axis_index.)
        off_e = jax.lax.dynamic_slice(starts_e, (lo,),
                                      (E_loc,)) - start_shard
        n_e = jax.lax.dynamic_slice(counts_e, (lo,), (E_loc,))
        gs = (jnp.clip(off_e + n_e, 0, Cs)
              - jnp.clip(off_e, 0, Cs)).astype(jnp.int32)
        # Row -> local expert id (rows past the covered total are
        # zeros and land on the clipped last id).
        e_row = jnp.minimum(
            jnp.searchsorted(jnp.cumsum(gs), jnp.arange(Cs),
                             side="right"),
            E_loc - 1)
        h = (jax.nn.silu(_ragged_expert_linear(buf, wg, gs, e_row))
             * _ragged_expert_linear(buf, wu, gs, e_row))
        out = _ragged_expert_linear(h, wd, gs, e_row)     # (Cs, D)
        g_sorted = g.T.reshape(-1)[order]
        wgt = jnp.where(keep, g_sorted, 0.0).astype(x.dtype)
        rows = jnp.take(out, slot, axis=0, mode="fill", fill_value=0)
        y = jnp.zeros((T_loc, D), x.dtype).at[tok].add(
            rows * wgt[:, None])
        return jax.lax.psum(y, ep_axis)                   # combine

    return shard_map(
        local_ffn, mesh=mesh,
        in_specs=(P(tok_entry, None), P(tok_entry, None),
                  P(tok_entry),
                  wspec(params["w_gate"]), wspec(params["w_up"]),
                  wspec(params["w_down"])),
        out_specs=P(tok_entry, None), check_vma=False)(
        xt, logits, mask, params["w_gate"],
        params["w_up"], params["w_down"])


def sparse_slots(expert_idx, E: int, C: int, token_mask=None):
    """Sort/segment routing: the same Switch priority rule as
    :func:`make_dispatch` without materializing any (T, E, C) tensor.

    Flattening (T, k) choice-major and stable-sorting by expert
    preserves choice-major order within each expert segment, so the
    rank inside the segment equals the dense path's cumulative-count
    position — drops are bit-identical.  ``token_mask`` (T,) bool:
    masked-out tokens are re-labeled to a sentinel expert E, sorting
    past every real segment — they take no capacity slot, exactly as
    in the dense path.  Returns, in sorted order: ``slot`` (kT,) int32
    index into the flat (E*C,) capacity buffer (== E*C for
    dropped/masked entries, for ``mode="drop"`` scatters), ``tok``
    (kT,) source token ids, ``keep`` (kT,) bool, and ``order`` (the
    argsort, for carrying gates along).
    """
    order, e_sorted, tok, counts = _route_sort(expert_idx, E,
                                               token_mask)
    k, T = expert_idx.shape[1], expert_idx.shape[0]
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(k * T, dtype=jnp.int32) - starts[e_sorted]
    keep = (pos < C) & (e_sorted < E)
    slot = jnp.where(keep, e_sorted * C + pos, E * C).astype(jnp.int32)
    return slot, tok, keep, order


def moe_ffn(x, params: dict, *, top_k: int = 2,
            capacity_factor: float = 1.25, mesh=None,
            ep_axis: str = "ep", dispatch_mode: str = "dense",
            token_mask=None, capacity: int | None = None,
            token_axes: tuple = ("dp",)):
    """Mixture-of-experts SwiGLU feed-forward.

    x: (..., D) -> (same shape, aux_loss scalar).  When ``mesh`` (with an
    ``ep`` axis) is given, the dispatched activations are sharding-
    constrained so GSPMD places each expert's (C, D) block on its ``ep``
    shard — compiling dispatch/combine into all_to_all collectives.

    ``dispatch_mode`` selects how tokens reach the (E, C, D) capacity
    buffer (expert compute is identical):

    * ``"dense"`` — one-hot dispatch/combine einsums (the oracle).
      FLOPs: 2·T·E·C·D each way; with E·C ≈ cf·k·T that is
      O(cf·k·T²·D) — **quadratic in token count** — plus the
      (T, k, E, C) slot one-hot in memory.  Fine at small T; the
      dispatch einsums (4·T·E·C·D) overtake the experts themselves
      (6·E·C·D·d_ff) once T > 1.5·d_ff — ~21.5k tokens for Mixtral,
      independent of cf and k (both scale dispatch and experts
      alike).
    * ``"sparse"`` — sort/segment routing: stable-sort the kT (token,
      choice) pairs by expert, take the first C per segment (the same
      priority rule, bit-identical drops), move rows by gather/scatter.
      Cost: O(kT log kT) sort + 2·kT·D copied elements — **linear in
      token count**, no T×E×C tensor anywhere.  Same shardings
      constrained under a mesh.

    * ``"dropless"`` — MegaBlocks-style: no per-expert capacity
      buffer.  Tokens sort by expert and the SwiGLU runs as three
      ``jax.lax.ragged_dot`` grouped matmuls over the variable-size
      expert segments — every token reaches every expert it routed
      to, so there are NO drops and ``capacity_factor``/``capacity``
      are ignored.  Equals the dense oracle whenever the oracle's
      capacity is lossless; under tight capacity it is the *better*
      answer (the one capacity only approximates).  Over an ``ep``
      mesh axis it becomes the hierarchical shard-capacity hybrid
      (:func:`_dropless_ffn_ep`): routing sorts stay local to each
      token shard (``token_axes`` names the mesh axes the flattened
      token dim is sharded over, default ``("dp",)``), each
      (token-shard, ep) device selects its experts' rows into a
      static ``(Cs, D)`` buffer (``Cs = ceil(cf·k·T_loc/ep)``; an
      explicit per-expert ``capacity`` pools to ``(E/ep)·capacity``)
      feeding locally dropless ragged segments, and combine is one
      ``psum`` over ``ep`` — no global all-gather/all-to-all on the
      token path.  Per-expert slack pools across each shard's E/ep
      experts, so drops only occur at whole-shard overflow.

    ``token_mask`` (bool, shape ``x.shape[:-1]``): masked-out tokens
    contribute nothing — zero output, no capacity slot consumed, and
    no effect on the aux loss — so active tokens route exactly as if
    the masked ones did not exist (at equal ``capacity``).  Batched
    speculative decoding uses this to keep finished streams from
    perturbing live ones.  ``capacity`` overrides the
    ``capacity_factor`` formula (needed when comparing runs whose
    token counts differ).
    """
    if dispatch_mode not in ("dense", "sparse", "dropless"):
        raise ValueError(f"unknown dispatch_mode {dispatch_mode!r}")
    orig_shape = x.shape
    D = orig_shape[-1]
    xt = x.reshape(-1, D)
    T = xt.shape[0]
    E = params["router"].shape[-1]
    C = (capacity if capacity is not None
         else compute_capacity(T, E, top_k, capacity_factor))
    mask_t = (None if token_mask is None
              else token_mask.reshape(-1))

    logits = xt.astype(jnp.float32) @ params["router"]

    if (dispatch_mode == "dropless" and mesh is not None
            and ep_axis in mesh.shape):
        # Routing (top-k) happens per token shard inside the
        # hierarchical path's shard_map (lax.top_k's TopK custom call
        # is not GSPMD-partitioned — see _dropless_ffn_ep).  The aux
        # loss needs only the FIRST choice, which argmax (a plain
        # partitionable reduce) computes identically (both break ties
        # toward the lowest index).
        probs = jax.nn.softmax(logits, axis=-1)
        first = jnp.argmax(probs, axis=-1).astype(jnp.int32)[:, None]
        aux = load_balance_loss(probs, first, E, token_mask=mask_t)
        y = _dropless_ffn_ep(xt, params, logits, top_k, E,
                             mesh, ep_axis, capacity_factor,
                             token_mask=mask_t, capacity=capacity,
                             token_axes=token_axes)
        return y.reshape(orig_shape), aux

    gates, expert_idx, probs = top_k_routing(logits, top_k)
    aux = load_balance_loss(probs, expert_idx, E, token_mask=mask_t)

    if dispatch_mode == "dropless":
        y = _dropless_ffn(xt, params, gates, expert_idx, E,
                          token_mask=mask_t)
        return y.reshape(orig_shape), aux

    if dispatch_mode == "sparse":
        slot, tok, keep, order = sparse_slots(expert_idx, E, C,
                                              token_mask=mask_t)
        g_sorted = gates.T.reshape(-1)[order]
        xe = jnp.zeros((E * C, D), x.dtype).at[slot].set(
            xt[tok], mode="drop").reshape(E, C, D)
    else:
        dispatch, combine = make_dispatch(gates, expert_idx, E, C,
                                          token_mask=mask_t)
        xe = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), xt)
    if mesh is not None and ep_axis in mesh.shape:
        sh = NamedSharding(mesh, P(ep_axis, None, None))
        xe = jax.lax.with_sharding_constraint(xe, sh)
    h = (jax.nn.silu(_expert_linear(xe, params["w_gate"], "ecd,edf->ecf"))
         * _expert_linear(xe, params["w_up"], "ecd,edf->ecf"))
    ye = _expert_linear(h, params["w_down"], "ecf,efd->ecd")
    if mesh is not None and ep_axis in mesh.shape:
        ye = jax.lax.with_sharding_constraint(ye, sh)
    if dispatch_mode == "sparse":
        w = jnp.where(keep, g_sorted, 0.0).astype(x.dtype)
        # mode="fill": dropped entries (slot == E*C) read zeros —
        # symmetric with the scatter's mode="drop", not reliant on the
        # gate weight alone to cancel them.
        rows = jnp.take(ye.reshape(E * C, D), slot, axis=0,
                        mode="fill", fill_value=0)
        y = jnp.zeros((T, D), x.dtype).at[tok].add(rows * w[:, None])
    else:
        y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), ye)
    return y.reshape(orig_shape), aux
