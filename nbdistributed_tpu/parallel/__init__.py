"""Parallelism library: interactive collectives, mesh helpers, and the
DP/TP/SP building blocks seeded into worker namespaces (SURVEY §2.3)."""
