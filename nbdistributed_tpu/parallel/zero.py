"""ZeRO-1: optimizer state sharded across the data-parallel axis.

Implements the "automatic cross-replica sharding of weight update"
technique (Xu et al., arXiv:2004.13336 — retrieved in PAPERS.md) the
XLA-native way: the optimizer state's *shardings* carry a ``dp`` axis,
and XLA compiles the classic ZeRO-1 schedule from the sharding lattice
alone — gradients reduce-scatter instead of all-reduce, each replica
updates only its shard of the Adam moments, and the updated params
all-gather back.  No manual collectives, no wrapper optimizer: the
exact train-step code of
:func:`~nbdistributed_tpu.parallel.tensor_parallel.make_tp_train_step`
with different ``in_shardings``/``out_shardings``.

Memory: Adam moments drop from 2×params per replica to 2×params/dp —
the dominant optimizer-memory term at scale.  Composes with tensor
parallelism: state leaves inherit the param's tp spec and the dp axis
lands on the first free, divisible dimension.
"""

from __future__ import annotations

import jax
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .tensor_parallel import sharding_tree


def _add_dp(spec: P, shape, dp_axis: str, dp_size: int) -> P:
    """Extend a param's spec with ``dp_axis`` on the first axis that is
    unsharded and divisible; replicated over dp if none qualifies."""
    ext = tuple(spec) + (None,) * (len(shape) - len(spec))
    for i, (dim, s) in enumerate(zip(shape, ext)):
        if s is None and dim and dim % dp_size == 0:
            return P(*ext[:i], dp_axis, *ext[i + 1:])
    return P(*ext)


def zero1_state_shardings(optimizer, params, param_rules, mesh, *,
                          dp_axis: str = "dp", param_sh=None):
    """A pytree of ``NamedSharding`` matching ``optimizer.init(params)``:
    param-shaped leaves (Adam moments, ...) get the param's spec plus a
    ``dp`` axis; non-param leaves (step counts, ...) replicate.

    ``param_sh``: pre-built ``sharding_tree(mesh, param_rules)``, if the
    caller already has one."""
    dp_size = mesh.shape[dp_axis]
    state_shapes = jax.eval_shape(optimizer.init, params)
    # Param-shaped rules as NamedSharding leaves: PartitionSpec is a
    # tuple subclass and would be flattened as a container by
    # tree_map_params' *rest traversal.
    if param_sh is None:
        param_sh = sharding_tree(mesh, param_rules)
    repl = NamedSharding(mesh, P())

    def shard_state_leaf(leaf, psh):
        return NamedSharding(
            mesh, _add_dp(psh.spec, leaf.shape, dp_axis, dp_size))

    return optax.tree_map_params(
        optimizer, shard_state_leaf, state_shapes, param_sh,
        transform_non_params=lambda leaf: repl)


def make_zero1_train_step(loss_fn, optimizer, mesh, param_rules, params,
                          *, dp_axis: str = "dp", donate: bool = True):
    """dp×tp train step with ZeRO-1 optimizer-state sharding.

    Same signature family as ``make_tp_train_step`` plus ``params``
    (an example pytree, needed to shape the optimizer state).  Returns
    ``(step, init)``: ``init(params)`` builds the dp-sharded optimizer
    state; ``step(params, opt_state, batch)`` is the jitted update —
    the *same* step definition as ``make_tp_train_step``, with the
    state shardings pinned to the ZeRO-1 layout.
    """
    from .tensor_parallel import make_tp_train_step

    if param_rules is None:
        # Pure DDP: fully replicated params (the canonical ZeRO-1 case).
        param_rules = jax.tree_util.tree_map(
            lambda p: P(*[None] * getattr(p, "ndim", 0)), params)
    param_sh = sharding_tree(mesh, param_rules)
    state_sh = zero1_state_shardings(optimizer, params, param_rules,
                                     mesh, dp_axis=dp_axis,
                                     param_sh=param_sh)

    def init(params):
        return jax.jit(optimizer.init, out_shardings=state_sh)(params)

    step = make_tp_train_step(loss_fn, optimizer, mesh, param_rules,
                              dp_axis=dp_axis, donate=donate,
                              opt_state_sh=state_sh)
    return step, init
