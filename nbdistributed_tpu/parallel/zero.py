"""ZeRO-1: optimizer state sharded across the data-parallel axis.

Implements the "automatic cross-replica sharding of weight update"
technique (Xu et al., arXiv:2004.13336 — retrieved in PAPERS.md) the
XLA-native way: the optimizer state's *shardings* carry a ``dp`` axis,
and XLA compiles the classic ZeRO-1 schedule from the sharding lattice
alone — gradients reduce-scatter instead of all-reduce, each replica
updates only its shard of the Adam moments, and the updated params
all-gather back.  No manual collectives, no wrapper optimizer: the
exact train-step code of
:func:`~nbdistributed_tpu.parallel.tensor_parallel.make_tp_train_step`
with different ``in_shardings``/``out_shardings``.

Memory: Adam moments drop from 2×params per replica to 2×params/dp —
the dominant optimizer-memory term at scale.  Composes with tensor
parallelism: state leaves inherit the param's tp spec and the dp axis
lands on the first free, divisible dimension.
"""

from __future__ import annotations

import jax
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .tensor_parallel import sharding_tree


def _add_dp(spec: P, shape, dp_axis: str, dp_size: int) -> P:
    """Extend a param's spec with ``dp_axis`` on the first axis that is
    unsharded and divisible; replicated over dp if none qualifies."""
    ext = tuple(spec) + (None,) * (len(shape) - len(spec))
    for i, (dim, s) in enumerate(zip(shape, ext)):
        if s is None and dim and dim % dp_size == 0:
            return P(*ext[:i], dp_axis, *ext[i + 1:])
    return P(*ext)


def zero1_state_shardings(optimizer, params, param_rules, mesh, *,
                          dp_axis: str = "dp", param_sh=None):
    """A pytree of ``NamedSharding`` matching ``optimizer.init(params)``:
    param-shaped leaves (Adam moments, ...) get the param's spec plus a
    ``dp`` axis; non-param leaves (step counts, ...) replicate.

    ``param_sh``: pre-built ``sharding_tree(mesh, param_rules)``, if the
    caller already has one."""
    dp_size = mesh.shape[dp_axis]
    state_shapes = jax.eval_shape(optimizer.init, params)
    # Param-shaped rules as NamedSharding leaves: PartitionSpec is a
    # tuple subclass and would be flattened as a container by
    # tree_map_params' *rest traversal.
    if param_sh is None:
        param_sh = sharding_tree(mesh, param_rules)
    repl = NamedSharding(mesh, P())

    def shard_state_leaf(leaf, psh):
        return NamedSharding(
            mesh, _add_dp(psh.spec, leaf.shape, dp_axis, dp_size))

    return optax.tree_map_params(
        optimizer, shard_state_leaf, state_shapes, param_sh,
        transform_non_params=lambda leaf: repl)


def make_zero1_train_step(loss_fn, optimizer, mesh, param_rules, params,
                          *, dp_axis: str = "dp", donate: bool = True,
                          guard: bool = False):
    """dp×tp train step with ZeRO-1 optimizer-state sharding.

    Same signature family as ``make_tp_train_step`` plus ``params``
    (an example pytree, needed to shape the optimizer state).  Returns
    ``(step, init)``: ``init(params)`` builds the dp-sharded optimizer
    state; ``step(params, opt_state, batch)`` is the jitted update —
    the *same* step definition as ``make_tp_train_step``, with the
    state shardings pinned to the ZeRO-1 layout.  ``guard=True``
    (ISSUE 19) composes the integrity-guarded step variant — the
    skip-on-non-finite ``where`` selects per *shard*, so the ZeRO
    layout is preserved bitwise on a skipped update too.
    """
    # ZeRO-1 is exactly ZeRO-2 without an accumulator (accum_steps=1):
    # one setup path, so a sharding fix can never drift between them.
    return make_zero2_train_step(loss_fn, optimizer, mesh, param_rules,
                                 params, accum_steps=1,
                                 dp_axis=dp_axis, donate=donate,
                                 guard=guard)


def zero2_accum_rules(params, param_rules, mesh, *,
                      dp_axis: str = "dp"):
    """dp-extended ``PartitionSpec`` pytree for the fp32 gradient
    accumulator: each param's spec plus the dp axis on the first free,
    divisible dimension (same placement rule as the ZeRO-1 moments)."""
    dp_size = mesh.shape[dp_axis]
    if param_rules is None:
        param_rules = jax.tree_util.tree_map(
            lambda p: P(*[None] * getattr(p, "ndim", 0)), params)
    return jax.tree_util.tree_map(
        lambda p, spec: _add_dp(spec, p.shape, dp_axis, dp_size),
        params, param_rules, is_leaf=lambda x: isinstance(x, P))


def make_zero2_train_step(loss_fn, optimizer, mesh, param_rules, params,
                          *, accum_steps: int, dp_axis: str = "dp",
                          donate: bool = True, guard: bool = False):
    """ZeRO-2: ZeRO-1's sharded optimizer state **plus** a dp-sharded
    fp32 gradient accumulator.

    Under GSPMD, classic ZeRO-2 "gradient sharding" is mostly
    subsumed: in a fused train step gradients are transient values
    that XLA already consumes reduce-scattered when the optimizer
    state carries the dp axis (the ZeRO-1 schedule).  The exception is
    gradient **accumulation**, whose fp32 accumulator is a persistent
    full-parameter-size buffer per replica (4 bytes/param) — exactly
    the buffer torch ZeRO-2 shards.  This builder pins that
    accumulator to the ZeRO layout, cutting it to 4/dp bytes/param,
    with numerics identical to the unsharded accumulator (tested).

    With ``accum_steps == 1`` there is no accumulator and this is
    ZeRO-1 exactly.  Returns ``(step, init)`` like
    :func:`make_zero1_train_step`.
    """
    from .tensor_parallel import make_tp_train_step

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if param_rules is None:
        param_rules = jax.tree_util.tree_map(
            lambda p: P(*[None] * getattr(p, "ndim", 0)), params)
    param_sh = sharding_tree(mesh, param_rules)
    state_sh = zero1_state_shardings(optimizer, params, param_rules,
                                     mesh, dp_axis=dp_axis,
                                     param_sh=param_sh)
    accum = (zero2_accum_rules(params, param_rules, mesh,
                               dp_axis=dp_axis)
             if accum_steps > 1 else None)

    def init(params):
        return jax.jit(optimizer.init, out_shardings=state_sh)(params)

    step = make_tp_train_step(loss_fn, optimizer, mesh, param_rules,
                              dp_axis=dp_axis, donate=donate,
                              opt_state_sh=state_sh,
                              accum_steps=accum_steps,
                              accum_rules=accum, guard=guard)
    return step, init
