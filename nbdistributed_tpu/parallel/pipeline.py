"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference has no pipeline parallelism (SURVEY §2.3: "Absent"; its
users could only hand-roll stages with ``%%rank`` groups and point-to-
point sends).  This module is the TPU-idiomatic version: stages are a
*mesh axis*, not processes — stage parameters live sharded over the
``pp`` axis, the whole schedule is one XLA program under ``shard_map``,
and activations hop stage-to-stage with ``lax.ppermute`` over ICI.  The
schedule is a ``lax.scan`` (compiler-friendly control flow: one trace,
no Python loop over steps), so compile time is O(1) in the number of
microbatches.

Semantics: ``stage_fn`` is applied ``n_stages`` times in sequence, so

    pipeline_forward(f, params, x, ...) ==  f(p[S-1], ... f(p[0], x))

(the unit tests assert equality with the sequential loop to float
tolerance — reduction order differs, so bitwise identity is not
guaranteed).  The usual GPipe bubble applies: utilisation is
``n_micro / (n_micro + n_stages - 1)`` — raise ``n_microbatches`` to
amortise it.  Differentiable end-to-end: ``ppermute``'s transpose is the
reverse permute, so ``jax.grad`` through a pipelined loss just works.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def shard_stage_params(stage_params, mesh, axis: str = "pp"):
    """Place stage-stacked parameters (every leaf carries a leading
    ``n_stages`` axis) so each pipeline stage holds only its own slice."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), stage_params)


def pipeline_forward(stage_fn, stage_params, x, mesh, *, axis: str = "pp",
                     n_microbatches: int | None = None):
    """Run ``x`` through ``n_stages`` sequential applications of
    ``stage_fn``, pipelined over the ``axis`` mesh axis.

    Args:
      stage_fn: ``(params_one_stage, activation) -> activation`` with the
        activation shape preserved (homogeneous stages, e.g. transformer
        blocks).
      stage_params: pytree whose leaves have leading dim ``n_stages``,
        sharded over ``axis`` (see :func:`shard_stage_params`).
      x: the global batch, leading dim divisible by ``n_microbatches``.
      n_microbatches: defaults to ``n_stages``.  More microbatches →
        smaller pipeline bubble.

    Returns the output batch, replicated over ``axis``.
    """
    n_stages = mesh.shape[axis]
    n_micro = n_microbatches if n_microbatches is not None else n_stages
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(
            f"batch {batch} not divisible by {n_micro} microbatches")
    xs = x.reshape(n_micro, batch // n_micro, *x.shape[1:])
    n_steps = n_micro + n_stages - 1
    multi_stage = n_stages > 1

    def spmd(params, xs):
        stage = jax.lax.axis_index(axis)
        # shard_map leaves a length-1 stage axis on local shards.
        local = jax.tree_util.tree_map(lambda a: a[0], params)

        def step(recv, t):
            # Stage 0 consumes the next microbatch while it exists (the
            # clamp only feeds don't-care work into drain steps whose
            # outputs are never collected); other stages consume what
            # the previous stage sent last step.
            x_in = jnp.where(stage == 0,
                             xs[jnp.minimum(t, n_micro - 1)], recv)
            y = stage_fn(local, x_in)
            out = jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y))
            if multi_stage:
                recv = jax.lax.ppermute(
                    y, axis,
                    [(i, i + 1) for i in range(n_stages - 1)])
            return recv, out

        _, outs = jax.lax.scan(step, jnp.zeros_like(xs[0]),
                               jnp.arange(n_steps))
        # Only the last stage produced real outputs; sum-replicate them
        # so every stage returns the full result.
        return jax.lax.psum(outs, axis)

    outs = jax.shard_map(
        spmd, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
        check_vma=False)(stage_params, xs)
    # Microbatch m exits the last stage at step m + n_stages - 1.
    return outs[n_stages - 1:].reshape(batch, *x.shape[1:])


def make_pipeline_loss(stage_fn, loss_tail, mesh, *, axis: str = "pp",
                       n_microbatches: int | None = None):
    """Compose a pipelined forward with a loss head.

    ``loss_tail(final_activation, batch) -> scalar``.  The returned
    ``loss(stage_params, x, batch)`` differentiates end-to-end (the
    backward pass pipelines in reverse through the transposed
    ppermutes).
    """

    @jax.jit
    def loss(stage_params, x, batch):
        y = pipeline_forward(stage_fn, stage_params, x, mesh, axis=axis,
                             n_microbatches=n_microbatches)
        return loss_tail(y, batch)

    return loss
