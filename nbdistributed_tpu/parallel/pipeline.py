"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference has no pipeline parallelism (SURVEY §2.3: "Absent"; its
users could only hand-roll stages with ``%%rank`` groups and point-to-
point sends).  This module is the TPU-idiomatic version: stages are a
*mesh axis*, not processes — stage parameters live sharded over the
``pp`` axis, the whole schedule is one XLA program under ``shard_map``,
and activations hop stage-to-stage with ``lax.ppermute`` over ICI.  The
schedule is a ``lax.scan`` (compiler-friendly control flow: one trace,
no Python loop over steps), so compile time is O(1) in the number of
microbatches.

Semantics: ``stage_fn`` is applied ``n_stages`` times in sequence, so

    pipeline_forward(f, params, x, ...) ==  f(p[S-1], ... f(p[0], x))

(the unit tests assert equality with the sequential loop to float
tolerance — reduction order differs, so bitwise identity is not
guaranteed).  The usual GPipe bubble applies: utilisation is
``n_micro / (n_micro + n_stages - 1)`` — raise ``n_microbatches`` to
amortise it.  Differentiable end-to-end: ``ppermute``'s transpose is the
reverse permute, so ``jax.grad`` through a pipelined loss just works.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.compat import shard_map


def shard_stage_params(stage_params, mesh, axis: str = "pp"):
    """Place stage-stacked parameters (every leaf carries a leading
    ``n_stages`` axis) so each pipeline stage holds only its own slice."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), stage_params)


def pipeline_forward(stage_fn, stage_params, x, mesh, *, axis: str = "pp",
                     n_microbatches: int | None = None):
    """Run ``x`` through ``n_stages`` sequential applications of
    ``stage_fn``, pipelined over the ``axis`` mesh axis.

    Args:
      stage_fn: ``(params_one_stage, activation) -> activation`` with the
        activation shape preserved (homogeneous stages, e.g. transformer
        blocks).
      stage_params: pytree whose leaves have leading dim ``n_stages``,
        sharded over ``axis`` (see :func:`shard_stage_params`).
      x: the global batch, leading dim divisible by ``n_microbatches``.
      n_microbatches: defaults to ``n_stages``.  More microbatches →
        smaller pipeline bubble.

    Returns the output batch, replicated over ``axis``.
    """
    n_stages = mesh.shape[axis]
    n_micro = n_microbatches if n_microbatches is not None else n_stages
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(
            f"batch {batch} not divisible by {n_micro} microbatches")
    xs = x.reshape(n_micro, batch // n_micro, *x.shape[1:])
    n_steps = n_micro + n_stages - 1
    multi_stage = n_stages > 1

    def spmd(params, xs):
        stage = jax.lax.axis_index(axis)
        # shard_map leaves a length-1 stage axis on local shards.
        local = jax.tree_util.tree_map(lambda a: a[0], params)

        def step(recv, t):
            # Stage 0 consumes the next microbatch while it exists (the
            # clamp only feeds don't-care work into drain steps whose
            # outputs are never collected); other stages consume what
            # the previous stage sent last step.
            x_in = jnp.where(stage == 0,
                             xs[jnp.minimum(t, n_micro - 1)], recv)
            y = stage_fn(local, x_in)
            out = jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y))
            if multi_stage:
                recv = jax.lax.ppermute(
                    y, axis,
                    [(i, i + 1) for i in range(n_stages - 1)])
            return recv, out

        _, outs = jax.lax.scan(step, jnp.zeros_like(xs[0]),
                               jnp.arange(n_steps))
        # Only the last stage produced real outputs; sum-replicate them
        # so every stage returns the full result.
        return jax.lax.psum(outs, axis)

    outs = shard_map(
        spmd, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
        check_vma=False)(stage_params, xs)
    # Microbatch m exits the last stage at step m + n_stages - 1.
    return outs[n_stages - 1:].reshape(batch, *x.shape[1:])


def make_pipeline_1f1b(stage_fn, loss_tail, mesh, *, axis: str = "pp",
                       n_microbatches: int | None = None,
                       batch_axis: str | None = None):
    """One-forward-one-backward (1F1B / PipeDream-flush) training
    schedule: a jitted ``(stage_params, x, batch) -> (loss, grads)``.

    GPipe via autodiff (``jax.grad`` of :func:`pipeline_forward`) runs
    all M forward microbatches, then replays all M backwards — every
    stage must hold M microbatches of residuals, so activation memory
    grows with the microbatch count that was supposed to shrink the
    bubble.  1F1B interleaves: each scan tick does one forward sub-step
    (activations ``ppermute`` up) and one backward sub-step (cotangents
    ``ppermute`` down), with stage ``s`` forwarding microbatch
    ``t - s`` and backwarding microbatch ``t - 2(S-1) + s``.  A saved
    input lives exactly ``2(S-1-s)`` ticks, so the in-flight buffer is
    ``2S - 1`` microbatch inputs regardless of M — **activation memory
    O(S) instead of O(M)**, which is the schedule's point.  The bubble
    fraction itself matches GPipe's flush (``(S-1)`` idle ticks at each
    end: ``2(S-1) / (M + 2(S-1))`` of the combined fwd+bwd timeline) —
    non-interleaved 1F1B trades no compute, only memory.

    Backward sub-steps recompute the stage forward from the saved
    *input* (`jax.vjp` at use-time) rather than storing VJP residuals —
    per-stage activation checkpointing, the standard pairing with 1F1B.

    Honest accounting for THIS (dense-SPMD scan) realization: every
    tick computes both sub-steps on every device — masked warmup/drain
    work is not free the way it is in a sparse per-device runtime — so
    the scan runs ``M + 2(S-1)`` full-work ticks where
    autodiff-GPipe-with-remat replays ``~M + S - 1``: 1F1B here costs
    ``O(S)`` extra chunk-units in exchange for the O(S)-vs-O(M)
    activation memory, the right trade exactly when M >> S (the regime
    where microbatching pays at all).  The same arithmetic is why the
    *interleaved* (virtual-chunk) 1F1B variant is deliberately absent:
    its bubble win exists only when idle ticks cost nothing, but an
    SPMD scan must execute every (device, tick) slot — with V virtual
    chunks the dense schedule runs ``M + 2(VS-1)`` ticks of unreduced
    per-tick work, strictly worse.  A sparse interleaved schedule
    needs per-device program divergence that shard_map's single traced
    program cannot express.

    Contract: ``loss_tail(y_micro, batch_micro) -> scalar`` must be a
    per-microbatch loss whose full-batch value is the mean over
    microbatches (true for mean-reduced losses over equal microbatch
    sizes); ``batch`` is any pytree with leading batch dim.  Gradients
    match ``jax.grad`` of the sequential/GPipe loss to float tolerance.
    """
    full = make_pipeline_1f1b_full(
        stage_fn, lambda tp, y, b: loss_tail(y, b), mesh, axis=axis,
        n_microbatches=n_microbatches, batch_axis=batch_axis)

    def plain_loss_and_grads(stage_params, x, batch):
        # `full` is already jit-wrapped; a second jax.jit here would
        # only add a trace layer and a duplicate cache entry.
        loss, stage_grads, _tail, _dx = full({}, stage_params, x,
                                             batch)
        return loss, stage_grads

    return plain_loss_and_grads


def make_pipeline_1f1b_full(stage_fn, tail_fn, mesh, *,
                            axis: str = "pp",
                            n_microbatches: int | None = None,
                            dx_sink=None, dx_init=None,
                            batch_axis: str | None = None):
    """The general 1F1B machinery: gradients for the loss tail's own
    parameters and for the pipeline *input*, on top of the stage
    gradients — what a full model (embedding below the pipelined
    region, norm + head + loss above it) needs to train end-to-end
    under the schedule.

    ``tail_fn(tail_params, y_micro, batch_micro) -> scalar`` is the
    per-microbatch loss head; its parameter gradients accumulate on
    the last stage and are psum-replicated.  ``dx_sink(acc, dx_micro,
    batch_micro) -> acc`` (with ``dx_init()`` building the initial
    accumulator) folds each microbatch's input-cotangent as it exits
    stage 0's backward — e.g. an embedding scatter-add — so no O(M)
    dx buffer ever exists; omit both to skip input gradients.

    Returns a jitted ``(tail_params, stage_params, x, batch) ->
    (loss, stage_grads, tail_grads, dx_acc)`` (``dx_acc`` is None
    without a sink).  Schedule, memory bound, and cost accounting: see
    :func:`make_pipeline_1f1b`, which is this with an empty tail.

    ``batch_axis``: a ``dp`` mesh axis the microbatch *rows* are
    sharded over (DP × PP): each dp group pipelines its own batch
    shard, and loss/stage/tail/dx gradients are mean-reduced across
    the groups — the per-shard-mean of a mean-reduced loss equals the
    global mean at equal shard sizes, exactly the DDP convention.
    """
    n_stages = mesh.shape[axis]
    n_micro_default = n_microbatches
    if (dx_sink is None) != (dx_init is None):
        raise ValueError("pass both dx_sink and dx_init, or neither")

    @jax.jit
    def loss_and_grads(tail_params, stage_params, x, batch):
        S = n_stages
        M = n_micro_default if n_micro_default is not None else S
        B = x.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} "
                             f"microbatches")
        if batch_axis is not None:
            d = mesh.shape[batch_axis]
            if (B // M) % d:
                raise ValueError(
                    f"per-microbatch rows {B // M} (batch {B} / "
                    f"{M} microbatches) not divisible by "
                    f"{batch_axis}={d} — shard_map would fail with an "
                    f"opaque sharding error")
        xs = x.reshape(M, B // M, *x.shape[1:])
        bt = jax.tree_util.tree_map(
            lambda a: a.reshape(M, B // M, *a.shape[1:]), batch)
        T = M + 2 * (S - 1)
        A = 2 * S - 1  # in-flight saved inputs: O(S), NOT O(M)
        multi = S > 1

        def spmd(tp, params, xs, bt):
            stage = jax.lax.axis_index(axis)
            local = jax.tree_util.tree_map(lambda a: a[0], params)
            g0 = jax.tree_util.tree_map(jnp.zeros_like, local)
            tg0 = jax.tree_util.tree_map(jnp.zeros_like, tp)
            dx0 = dx_init() if dx_init is not None else jnp.float32(0.0)

            def tick(carry, t):
                f_recv, b_recv, buf, grads, tg, dxa, loss_acc = carry
                # ---- forward sub-step: stage s runs microbatch t-s.
                m_f = t - stage
                act_f = (m_f >= 0) & (m_f < M)
                x_in = jnp.where(stage == 0,
                                 xs[jnp.clip(m_f, 0, M - 1)], f_recv)
                y = stage_fn(local, x_in)
                if multi:
                    f_recv = jax.lax.ppermute(
                        y, axis,
                        [(i, i + 1) for i in range(S - 1)])
                # Save this tick's input for its backward, 2(S-1-s)
                # ticks later; slot reuse is safe because lifetimes
                # never exceed A ticks.
                buf = buf.at[t % A].set(
                    jnp.where(act_f, x_in, buf[t % A]))

                # ---- backward sub-step: stage s re-derives microbatch
                # t - 2(S-1) + s from its saved input (recompute VJP).
                m_b = t - 2 * (S - 1) + stage
                act_b = (m_b >= 0) & (m_b < M)
                slot = (t - 2 * (S - 1) + 2 * stage) % A
                x_sav = buf[slot]
                y_b, vjp = jax.vjp(stage_fn, local, x_sav)
                # Last stage seeds the cotangent from the loss head on
                # its recomputed output; earlier stages use what the
                # next stage sent down.
                mb_idx = jnp.clip(m_b, 0, M - 1)
                bt_m = jax.tree_util.tree_map(lambda a: a[mb_idx], bt)
                loss_m, lt_vjp = jax.vjp(
                    lambda tp_, y_: tail_fn(tp_, y_, bt_m), tp, y_b)
                dtp, cot_seed = lt_vjp(jnp.float32(1.0) / M)
                last_b = act_b & (stage == S - 1)
                tg = jax.tree_util.tree_map(
                    lambda g, d: g + jnp.where(last_b, d, 0), tg, dtp)
                cot = jnp.where(stage == S - 1, cot_seed, b_recv)
                dp, dx = vjp(cot.astype(y_b.dtype))
                grads = jax.tree_util.tree_map(
                    lambda g, d: g + jnp.where(act_b, d, 0), grads, dp)
                if dx_sink is not None:
                    # Fold stage 0's input-cotangent immediately (other
                    # stages / inactive ticks fold zeros — a no-op), so
                    # the input gradient never needs an O(M) buffer.
                    dxa = dx_sink(
                        dxa, jnp.where(act_b & (stage == 0), dx, 0),
                        bt_m)
                loss_acc = loss_acc + jnp.where(last_b, loss_m / M, 0.0)
                if multi:
                    b_recv = jax.lax.ppermute(
                        dx, axis,
                        [(i, i - 1) for i in range(1, S)])
                return (f_recv, b_recv, buf, grads, tg, dxa,
                        loss_acc), None

            buf0 = jnp.zeros((A,) + xs.shape[1:], xs.dtype)
            (_, _, _, grads, tg, dxa, loss_acc), _ = jax.lax.scan(
                tick, (jnp.zeros_like(xs[0]), jnp.zeros_like(xs[0]),
                       buf0, g0, tg0, dx0, jnp.float32(0.0)),
                jnp.arange(T))
            # Loss and tail grads live on the last stage, the dx
            # accumulator on stage 0; psum replicates each (all other
            # stages contributed zeros).  Stage grads are each stage's
            # own slice (restacked via the pp out_spec).
            loss = jax.lax.psum(loss_acc, axis)
            tg = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, axis), tg)
            dxa = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, axis), dxa)
            if batch_axis is not None:
                # DP x PP: every dp group pipelined its own batch
                # shard; mean-reduce everything across the groups
                # (equal shard sizes -> the global-batch mean).
                loss = jax.lax.pmean(loss, batch_axis)
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, batch_axis), grads)
                tg = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, batch_axis), tg)
                dxa = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, batch_axis), dxa)
            grads = jax.tree_util.tree_map(lambda g: g[None], grads)
            return loss, grads, tg, dxa

        # Microbatch ROWS (axis 1 of the (M, mb, ...) reshape) carry
        # the dp sharding when batch_axis is set.
        data_spec = (P(None, batch_axis) if batch_axis is not None
                     else P())
        loss, stage_grads, tail_grads, dxa = shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), P(axis), data_spec, data_spec),
            out_specs=(P(), P(axis), P(), P()), check_vma=False)(
            tail_params, stage_params, xs, bt)
        return (loss, stage_grads, tail_grads,
                dxa if dx_sink is not None else None)

    return loss_and_grads



def make_pipeline_loss(stage_fn, loss_tail, mesh, *, axis: str = "pp",
                       n_microbatches: int | None = None,
                       remat: bool = False):
    """Compose a pipelined forward with a loss head.

    ``loss_tail(final_activation, batch) -> scalar``.  The returned
    ``loss(stage_params, x, batch)`` differentiates end-to-end (the
    backward pass pipelines in reverse through the transposed
    ppermutes).  ``remat=True`` checkpoints each stage application, so
    the GPipe backward stores M microbatch *inputs* per stage instead
    of M sets of stage-internal residuals — the intermediate memory
    point between plain GPipe (O(M·residuals)) and
    :func:`make_pipeline_1f1b` (O(S·inputs)).
    """
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    @jax.jit
    def loss(stage_params, x, batch):
        y = pipeline_forward(fn, stage_params, x, mesh, axis=axis,
                             n_microbatches=n_microbatches)
        return loss_tail(y, batch)

    return loss
