"""Pallas flash-decode: single-token attention against the KV cache.

The decode step's hot op is bandwidth-bound: every generated token
reads the whole (B, T, Hkv, D) cache once.  This kernel fuses the
masked online-softmax into that single streaming pass — no (B, H, T)
score tensor ever hits HBM — with one program per (batch, kv-head)
whose query block is the GQA *group* (all H/Hkv query heads sharing
that KV head), so the per-block matmuls are (group, D) @ (D, block_k):
the same shape decode GQA is compute-bound on.

Same recurrence as the prefill flash kernel (attention.py), lifted to
the cache layout + per-batch valid-length masking (cache slots
t <= pos[b] attend; later slots are unwritten).  On non-TPU backends
the kernel runs in interpreter mode, so tests exercise the identical
code path everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ._common import NEG_INF as _NEG_INF
from ._common import use_interpret as _use_interpret


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *,
                   block_k: int, seq_k: int, scale: float):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    q = q_ref[0, 0].astype(jnp.float32) * scale        # (group, D)
    valid = pos_ref[b] + 1                              # keys [0, valid)

    group = q.shape[0]
    acc = jnp.zeros((group, q.shape[-1]), jnp.float32)
    m = jnp.full((group, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((group, 1), jnp.float32)

    # Only blocks intersecting [0, valid) contribute; block starts are
    # clamped in the body, so the count uses the unclamped grid.
    num_iters = jnp.minimum(
        jax.lax.div(valid + block_k - 1, block_k),
        jax.lax.div(seq_k + block_k - 1, block_k))

    def body(kb, carry):
        acc, m, l = carry
        # The final block of a non-block-multiple cache reads the
        # overlapping window [seq_k - block_k, seq_k) — always in
        # bounds — and masks out the keys the previous block already
        # folded in, so any T works at full block width.
        start = jnp.minimum(kb * block_k, seq_k - block_k)
        k_blk = k_ref[0, pl.ds(start, block_k), 0].astype(
            jnp.float32)                                # (Bk, D)
        v_blk = v_ref[0, pl.ds(start, block_k), 0].astype(
            jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # (group, Bk)
        ki = (start
              + jax.lax.broadcasted_iota(jnp.int32, (group, block_k), 1))
        keep = (ki < valid) & (ki >= kb * block_k)
        s = jnp.where(keep, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, num_iters, body, (acc, m, l))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_k", "scale", "interpret"))
def _decode_call(q, kc, vc, pos, *, block_k: int, scale: float,
                 interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Hkv, group, D = q.shape
    T = kc.shape[1]
    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               seq_k=T, scale=scale)
    # pos rides as a prefetched scalar array (SMEM on real TPU) —
    # the kernel indexes it by the batch program id.
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, Hkv),
            in_specs=[
                pl.BlockSpec((1, 1, group, D),
                             lambda b, h, pos: (b, h, 0, 0)),   # q
                pl.BlockSpec((1, T, 1, D),
                             lambda b, h, pos: (b, 0, h, 0)),   # k cache
                pl.BlockSpec((1, T, 1, D),
                             lambda b, h, pos: (b, 0, h, 0)),   # v cache
            ],
            out_specs=pl.BlockSpec((1, 1, group, D),
                                   lambda b, h, pos: (b, h, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, group, D), q.dtype),
        interpret=interpret,
    )(pos, q, kc, vc)


def flash_decode_attention(q, kc, vc, pos, *, scale: float | None = None,
                           block_k: int = 128):
    """Fused decode attention: one new token per sequence against the
    cache.

    q: (B, H, D) — this step's queries (S = 1 squeezed);
    kc/vc: (B, T, Hkv, D) cache buffers (slots beyond ``pos`` unwritten);
    pos: (B,) int32 — the global position of the new token per
    sequence (cache slots ``t <= pos[b]`` attend).
    Returns (B, H, D).  Any cache length works at full block width —
    a non-multiple tail is handled by an overlapping, masked final
    block read inside the kernel.
    """
    B, H, D = q.shape
    T, Hkv = kc.shape[1], kc.shape[2]
    if H % Hkv:
        raise ValueError(f"n_heads {H} not divisible by n_kv_heads {Hkv}")
    group = H // Hkv
    scale = scale if scale is not None else float(1.0 / np.sqrt(D))
    block_k = min(block_k, T)
    qg = q.reshape(B, Hkv, group, D)
    out = _decode_call(qg, kc, vc, jnp.asarray(pos, jnp.int32),
                       block_k=block_k, scale=float(scale),
                       interpret=_use_interpret())
    return out.reshape(B, H, D)
