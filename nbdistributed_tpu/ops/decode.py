"""Pallas flash-decode: single-token attention against the KV cache.

The decode step's hot op is bandwidth-bound: every generated token
reads the whole (B, Hkv, T, D) heads-major cache once.  This kernel
fuses the masked online-softmax into that single streaming pass — no
(B, H, T) score tensor ever hits HBM — with one program per (batch,
kv-head) whose query block is the GQA *group* (all H/Hkv query heads
sharing that KV head), so the per-block matmuls are
(group, D) @ (D, block_k): the same shape decode GQA is compute-bound
on.  The heads-major layout keeps (T, D) as each block's minor dims,
which Mosaic's block-shape rules require (a seq-major (B, T, Hkv, D)
cache puts the tiny Hkv in the sublane slot and fails to lower on
real TPU hardware).

Same recurrence as the prefill flash kernel (attention.py), lifted to
the cache layout + per-batch valid-length masking (cache slots
t <= pos[b] attend; later slots are unwritten).  On non-TPU backends
the kernel runs in interpreter mode, so tests exercise the identical
code path everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ._common import NEG_INF as _NEG_INF
from ._common import use_interpret as _use_interpret


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_s, m_s, l_s, *, block_k: int, seq_k: int,
                   scale: float, num_kb: int,
                   window: int | None = None,
                   ks_ref=None, vs_ref=None, lse_ref=None):
    """One grid step = one (batch, kv-head, k-block).  The k axis rides
    the grid (sequential on-core), so only a (block_k, D) window of the
    cache is ever staged in VMEM — context length is bounded by HBM,
    not VMEM — with the online-softmax state carried in scratch.

    With ``ks_ref``/``vs_ref`` (per-token scale blocks, (Bk, 1)), the
    cache arrives int8 and the scales commute through both matmuls:
    ``q . (q8_k * s_k)`` rescales the score columns, and
    ``p @ (q8_v * s_v)`` folds ``s_v`` into ``p`` — the cache streams
    from HBM at half width, the math is exact given the quantization.
    """
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    kb = pl.program_id(2)
    valid = pos_ref[b] + 1                              # keys [0, valid)
    # The sp-sharded caller passes LOCAL positions that can exceed
    # this shard's cache length (a later global position means "every
    # local key attends") — clamp the upper bound to seq_k so the
    # padded tail of a partial final block never enters the softmax.
    # The window's lower bound stays on the UNCLAMPED position: it is
    # offset-invariant in local coordinates only as valid - window.
    valid_k = jnp.minimum(valid, seq_k)
    # Sliding window: only keys in [valid - window, valid) attend;
    # blocks entirely below the window are skipped like blocks past
    # the valid length.
    lo = valid - window if window is not None else 0

    @pl.when(kb == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    # lo < valid_k: an sp-sharded caller's overshooting position can
    # put the whole window past this shard's slice (lo >= valid_k) —
    # without this clause such a block runs with an empty mask and its
    # all -NEG_INF scores make p == 1 everywhere (m_new == NEG_INF),
    # averaging garbage rows into acc; today the cross-shard combine
    # happens to flush it (exp(lse−m) underflows to 0 because NEG_INF
    # is finite), but correctness must not hang on an underflow.
    @pl.when((kb * block_k < valid_k)
             & ((kb + 1) * block_k > lo)
             & (lo < valid_k))
    def _block():
        q = q_ref[0, 0].astype(jnp.float32) * scale     # (group, D)
        k_blk = k_ref[0, 0].astype(jnp.float32)         # (Bk, D)
        v_blk = v_ref[0, 0].astype(jnp.float32)
        # A final block that extends past seq_k is padded by Pallas
        # with undefined data (NaN in interpret mode, garbage memory on
        # hardware).  The score mask below already discards those
        # columns of s, but the p @ v matmul would still compute
        # 0 * NaN = NaN through the padded v rows — so zero the
        # out-of-bounds rows explicitly before they enter any matmul.
        kpad = (kb * block_k
                + jax.lax.broadcasted_iota(jnp.int32, (block_k, 1), 0))
        in_bounds = kpad < seq_k                        # (Bk, 1)
        v_blk = jnp.where(in_bounds, v_blk, 0.0)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # (group, Bk)
        if ks_ref is not None:
            # Per-token K scales rescale the score columns.
            s = s * ks_ref[0, 0, :, 0][None, :]
        ki = (kb * block_k
              + jax.lax.broadcasted_iota(jnp.int32,
                                         (q.shape[0], block_k), 1))
        # < valid_k also masks the padded tail of a non-multiple T
        # (valid_k <= seq_k by construction, even for the sp-sharded
        # caller's overshooting positions) — including any NaN columns
        # of s from padded k rows (jnp.where does not propagate the
        # unselected branch).
        s = jnp.where((ki < valid_k) & (ki >= lo), s, _NEG_INF)
        m_prev, l_prev = m_s[...], l_s[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        # The softmax normalizer sums the UNSCALED probabilities; only
        # the V contraction takes the per-token V scale.
        l_s[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        if vs_ref is not None:
            # Zero out-of-bounds scale rows for the same reason as
            # v_blk above: p is 0 there, but 0 * NaN/garbage = NaN.
            vs = jnp.where(in_bounds[:, 0],
                           vs_ref[0, 0, :, 0], 0.0)[None, :]
            pv = p * vs
        else:
            pv = p
        acc_s[...] = acc_s[...] * corr + jax.lax.dot_general(
            pv, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[...] = m_new

    @pl.when(kb == num_kb - 1)
    def _finalize():
        o_ref[0, 0] = (acc_s[...]
                       / jnp.maximum(l_s[...], 1e-30)).astype(o_ref.dtype)
        if lse_ref is not None:
            # log-sum-exp of the masked scores; an all-masked shard
            # (this query attends to nothing here — the sp-sharded
            # cache case) reports NEG_INF so the cross-shard combine
            # weighs it zero.
            lse_ref[0, 0] = jnp.where(
                l_s[...] > 0.0, m_s[...] + jnp.log(
                    jnp.maximum(l_s[...], 1e-30)), _NEG_INF)


@functools.partial(jax.jit,
                   static_argnames=("block_k", "scale", "interpret",
                                    "window", "return_lse"))
def _decode_call(q, kc, vc, pos, *, block_k: int, scale: float,
                 interpret: bool, window: int | None = None,
                 k_s=None, v_s=None, return_lse: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Hkv, group, D = q.shape
    T = kc.shape[2]
    num_kb = -(-T // block_k)
    quantized = k_s is not None

    def _kernel(pos_ref, *refs):
        lse_ref = None
        if return_lse:
            *refs, a, m, l = refs
            *refs, o_ref, lse_ref = refs
        else:
            *refs, o_ref, a, m, l = refs
        if quantized:
            q_ref, k_ref, v_ref, ks_ref, vs_ref = refs
        else:
            (q_ref, k_ref, v_ref), ks_ref, vs_ref = refs, None, None
        _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, a, m, l,
                       block_k=block_k, seq_k=T, scale=scale,
                       num_kb=num_kb, window=window, ks_ref=ks_ref,
                       vs_ref=vs_ref, lse_ref=lse_ref)

    in_specs = [
        pl.BlockSpec((1, 1, group, D),
                     lambda b, h, kb, pos: (b, h, 0, 0)),  # q
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, h, kb, pos: (b, h, kb, 0)),  # k
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, h, kb, pos: (b, h, kb, 0)),  # v
    ]
    args = [pos, q, kc, vc]
    if quantized:
        # Scales live as (B, Hkv, T, 1), same heads-major layout as
        # K/V: every block's last two dims are (token-block, minor) and
        # satisfy Mosaic's (8-divisible | equal) rule.
        in_specs += [
            pl.BlockSpec((1, 1, block_k, 1),
                         lambda b, h, kb, pos: (b, h, kb, 0)),  # k_s
            pl.BlockSpec((1, 1, block_k, 1),
                         lambda b, h, kb, pos: (b, h, kb, 0)),  # v_s
        ]
        args += [k_s, v_s]

    out_specs = pl.BlockSpec((1, 1, group, D),
                             lambda b, h, kb, pos: (b, h, 0, 0))
    out_shape = jax.ShapeDtypeStruct((B, Hkv, group, D), q.dtype)
    if return_lse:
        # The lse plane keeps a trailing unit dim so its block's last
        # two dims equal the array's — Mosaic's block-shape rule (the
        # same pattern as the int8 scale planes).
        out_specs = [out_specs,
                     pl.BlockSpec((1, 1, group, 1),
                                  lambda b, h, kb, pos: (b, h, 0, 0))]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((B, Hkv, group, 1),
                                          jnp.float32)]

    # pos rides as a prefetched scalar array (SMEM on real TPU) —
    # the kernel indexes it by the batch program id.  The k axis is the
    # innermost grid dim: sequential on-core, scratch carries state.
    return pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, Hkv, num_kb),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((group, D), jnp.float32),   # acc
                pltpu.VMEM((group, 1), jnp.float32),   # running max
                pltpu.VMEM((group, 1), jnp.float32),   # normalizer
            ],
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(*args)


# (T, head_dim, gqa_group) -> block_k, measured on a live chip by
# tune_flash.py's decode sweep.  Consulted when the caller passes no
# explicit block_k; empty entries fall back to 128.  Decode is
# HBM-streaming-bound, so the block size mostly trades grid overhead
# against VMEM residency of the (block_k, D) cache window.  Seeded
# from ops/tuned_blocks.json (see ops/_tuned.py).
from ._tuned import load as _load_tuned

DECODE_TUNED_BLOCKS: dict = _load_tuned()[1]
_DEFAULT_BLOCK_K = 128


def flash_decode_attention(q, kc, vc, pos, *, scale: float | None = None,
                           block_k: int | None = None,
                           window: int | None = None,
                           k_s=None, v_s=None,
                           return_lse: bool = False):
    """Fused decode attention: one new token per sequence against the
    cache.

    q: (B, H, D) — this step's queries (S = 1 squeezed);
    kc/vc: (B, Hkv, T, D) heads-major cache buffers (slots beyond
    ``pos`` unwritten);
    pos: (B,) int32 — the global position of the new token per
    sequence (cache slots ``t <= pos[b]`` attend); ``window`` further
    restricts to the last ``window`` positions (sliding-window
    models) with out-of-band blocks skipped, not just masked.
    Returns (B, H, D).  Any cache length works at full block width —
    a non-multiple tail is handled by an overlapping, masked final
    block read inside the kernel.

    ``k_s``/``v_s`` (both or neither, (B, Hkv, T, 1) fp32): per-token
    per-kv-head scales for an **int8 cache** — kc/vc arrive int8 and
    stream from HBM at half width; the scales commute through the two
    matmuls inside the kernel (see models/quant.py for the cache
    quantizer).

    ``return_lse=True`` additionally returns the per-query-head
    log-sum-exp of the masked scores, (B, H) fp32 (``NEG_INF`` for a
    query that attends to nothing) — the combiner a sequence-sharded
    cache needs: shards compute locally and merge as
    ``o = Σ exp(lse_i − m)·o_i / Σ exp(lse_i − m)`` (see
    ``models/generate._flash_decode_on_mesh``).
    """
    B, H, D = q.shape
    Hkv, T = kc.shape[1], kc.shape[2]
    if H % Hkv:
        raise ValueError(f"n_heads {H} not divisible by n_kv_heads {Hkv}")
    if (k_s is None) != (v_s is None):
        raise ValueError("pass both k_s and v_s, or neither")
    group = H // Hkv
    scale = scale if scale is not None else float(1.0 / np.sqrt(D))
    if block_k is None:
        block_k = DECODE_TUNED_BLOCKS.get((T, D, group),
                                          _DEFAULT_BLOCK_K)
    block_k = min(block_k, T)
    qg = q.reshape(B, Hkv, group, D)
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    out = _decode_call(qg, kc, vc, jnp.asarray(pos, jnp.int32),
                       block_k=block_k, scale=float(scale),
                       interpret=_use_interpret(), window=window,
                       k_s=k_s, v_s=v_s, return_lse=return_lse)
    if return_lse:
        o, lse = out
        return o.reshape(B, H, D), lse.reshape(B, H)
    return out.reshape(B, H, D)
