"""Persisted tuned block tables for the Pallas kernels.

``tune_flash.py`` sweeps block sizes on a live chip and calls
:func:`save`; ``ops.attention`` / ``ops.decode`` call :func:`load` at
import so every later process (bench worker, user notebook) picks the
tuned sizes up automatically — the tuning lands without a human
pasting tables, which matters because the accelerator tunnel windows
are unattended (see tpu_watch.sh).

JSON schema (tuple keys are comma-joined ints — JSON has no tuples)::

    {"flash":  {"Sq,Sk,D,group": [block_q, block_k], ...},
     "decode": {"T,D,group": block_k, ...},
     "measured_at": "...", "device": "..."}
"""

from __future__ import annotations

import json
import os

PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "tuned_blocks.json")


def _parse_key(s: str) -> tuple:
    return tuple(int(x) for x in s.split(","))


def load(path: str | None = None):
    """Returns (flash_table, decode_table); both empty when the file
    is absent or unreadable (the kernels then use their defaults)."""
    try:
        with open(path or PATH) as f:
            raw = json.load(f)
        flash = {_parse_key(k): tuple(int(b) for b in v)
                 for k, v in raw.get("flash", {}).items()}
        decode = {_parse_key(k): int(v)
                  for k, v in raw.get("decode", {}).items()}
        return flash, decode
    except (OSError, ValueError, TypeError, AttributeError):
        # AttributeError covers wrong-schema files (top level or a
        # sub-table not a dict): a malformed table must degrade to
        # kernel defaults, never break import of ops.attention/decode.
        return {}, {}


def save(flash: dict, decode: dict, meta: dict | None = None,
         path: str | None = None) -> str:
    """Atomically write the tables; returns the path written."""
    path = path or PATH
    raw = {"flash": {",".join(map(str, k)): list(map(int, v))
                     for k, v in flash.items()},
           "decode": {",".join(map(str, k)): int(v)
                      for k, v in decode.items()}}
    raw.update(meta or {})
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(raw, f, indent=1)
    os.replace(tmp, path)
    return path
