"""Shared kernel policy/constants for the Pallas ops (one definition —
the attention and decode kernels must mask and backend-switch
identically)."""

from __future__ import annotations

import jax

NEG_INF = -1e30  # softmax mask value (finite: -inf breaks exp(-inf-m))


def use_interpret() -> bool:
    """Pallas interpreter mode off-TPU, so every backend runs the same
    kernel code path."""
    return jax.default_backend() != "tpu"
