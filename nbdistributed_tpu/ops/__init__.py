"""TPU compute ops: Pallas kernels with reference fallbacks."""

from .attention import attention_reference, flash_attention
from .decode import flash_decode_attention
from .xent import chunked_softmax_xent, shifted_chunked_xent

__all__ = ["attention_reference", "chunked_softmax_xent",
           "flash_attention", "flash_decode_attention",
           "shifted_chunked_xent"]
