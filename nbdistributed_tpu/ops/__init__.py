"""TPU compute ops: Pallas kernels with reference fallbacks."""

from .attention import attention_reference, flash_attention
from .decode import flash_decode_attention

__all__ = ["attention_reference", "flash_attention",
           "flash_decode_attention"]
