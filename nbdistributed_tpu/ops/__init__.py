"""TPU compute ops: Pallas kernels with reference fallbacks."""

from .attention import attention_reference, flash_attention

__all__ = ["attention_reference", "flash_attention"]
