"""Chunked-vocab softmax cross-entropy: the logits never materialize.

The standard next-token loss computes ``logits = x @ W`` at (N, V)
then ``log_softmax`` over V — two (N, V) fp32 buffers that dominate
training memory at LM scale (B8 S2048 V32000: ~2.1 GB each, doubled
again in the backward).  At 1B scale on a 16 G chip this is the wall
that caps the train batch (bench.py's MFU ladder).

This module computes the same loss with the vocabulary processed in
chunks inside a ``lax.scan`` whose body is ``jax.checkpoint``-ed:

- forward: an online logsumexp (flash-attention-style running max +
  rescaled sum) plus the target logit, carried across chunks — peak
  extra memory is ONE (N, chunk) block;
- backward: autodiff of the checkpointed scan recomputes each chunk's
  logits and accumulates dx and dW chunk by chunk — again one
  (N, chunk) block live, never the full (N, V).

The result is bit-comparable to the naive path up to fp32
reassociation (tests assert loss and grads to 1e-5).

Scope: this is the single-device / data-parallel memory optimization.
Under tensor parallelism the lm_head is already vocab-sharded
(P(None, "tp")) and each shard's logits block is V/tp wide — use the
standard path there (the scan's stacked-weight layout would fight the
GSPMD sharding).  Reference for the capability bar: the upstream
framework has no training loss at all (nbdistributed is the notebook
runtime; SURVEY.md §2) — this is a beyond-parity component of the
training stack.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_softmax_xent(x, W, targets, valid=None, chunk: int = 8192):
    """Mean next-token NLL of ``targets`` under ``softmax(x @ W)``,
    without materializing the (N, V) logits.

    x: (N, D) activations (any float dtype; logits are computed in
    that dtype then accumulated in fp32, matching the naive path's
    ``(x @ W).astype(float32)``).
    W: (D, V) dense head weights.
    targets: (N,) int — target column per row.
    valid: optional (N,) bool — rows excluded from the mean (packed
    document boundaries); the mean divides by the surviving count.
    chunk: vocabulary block width (the V axis is zero-padded up to a
    multiple; padded columns are masked to -inf so they never affect
    the logsumexp).
    """
    N, D = x.shape
    V = W.shape[1]
    n_chunks = -(-V // chunk)
    pad = n_chunks * chunk - V
    # Zero-pad only when chunk does not divide V: dynamic_slice CLAMPS
    # an out-of-range start (the last ragged chunk would silently read
    # overlapping columns), so the ragged case pays one W-sized copy.
    # Callers wanting zero-copy pick a chunk that divides V (bench.py
    # uses vocab_size // 4).
    Wp = jnp.pad(W, ((0, 0), (0, pad))) if pad else W
    targets = targets.astype(jnp.int32)

    @jax.checkpoint
    def body(carry, ci):
        m, s, tl = carry
        # Slice the chunk inside the body: W streams block by block
        # (no stacked (n_chunks, D, chunk) copy), and the slice's
        # transpose accumulates dW chunk-wise straight into the
        # (already required) param-gradient buffer.
        Wck = jax.lax.dynamic_slice_in_dim(Wp, ci * chunk, chunk,
                                           axis=1)    # (D, chunk)
        logits = (x @ Wck).astype(jnp.float32)        # (N, chunk)
        col0 = ci * chunk
        col_ok = (col0 + jnp.arange(chunk)) < V
        logits = jnp.where(col_ok[None, :], logits, -jnp.inf)
        m2 = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m2) + jnp.sum(
            jnp.exp(logits - m2[:, None]), axis=-1)
        idx = targets - col0
        in_ch = (idx >= 0) & (idx < chunk)
        got = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        tl = jnp.where(in_ch, got, tl)
        return (m2, s, tl), None

    init = (jnp.full((N,), -jnp.inf, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.zeros((N,), jnp.float32))
    (m, s, tl), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    nll = jnp.log(s) + m - tl               # per-row -log p[target]
    if valid is None:
        return jnp.mean(nll)
    keep = valid.astype(nll.dtype)
    return jnp.sum(nll * keep) / jnp.maximum(jnp.sum(keep), 1)


def shifted_chunked_xent(hidden, W, tokens, segment_ids=None,
                         chunk: int = 8192):
    """The logits-shift wrapper over :func:`chunked_softmax_xent`:
    positions 0..S-2 of ``hidden`` (B, S, D) predict tokens[:, 1:],
    with packed-document boundary targets dropped exactly like
    ``shifted_xent`` (transformer.py) — the two paths share the
    shift/mask contract and the tests pin them equal."""
    B, S, D = hidden.shape
    x = hidden[:, :-1].reshape(B * (S - 1), D)
    targets = tokens[:, 1:].reshape(B * (S - 1))
    valid = None
    if segment_ids is not None:
        valid = (segment_ids[:, :-1]
                 == segment_ids[:, 1:]).reshape(B * (S - 1))
    return chunked_softmax_xent(x, W, targets, valid, chunk)
