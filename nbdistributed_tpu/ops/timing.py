"""Tunnel-honest kernel timing: the canonical chained-scan pattern.

Single source of truth for the measurement protocol bench.py's flash
cell, ``tune_flash.py``, and ``tools/probe_timing.py`` all rely on —
the constants here are load-bearing (BENCH_ATTEMPTS_r05.md): if the
chain lengths, accumulation factor, or fresh-input scheme drift
between the bench and the preflight probe, the probe's noise profile
stops being evidence about the bench's numbers.

The protocol (see .claude/skills/verify/SKILL.md "honest timing"):

- Each measured call runs ``n`` iterations of ``step`` chained through
  the scan CARRY (a real data dependency no scheduler can elide), all
  inside ONE jitted program.
- Per-call time is the (long - short chain) difference divided by the
  iteration delta: the fixed dispatch+fetch round-trip cancels.
- Each chain length is the MEDIAN of ``reps`` timed calls, every call
  on a DIFFERENT input value (a program+input result cache can never
  serve one) and ending in a host VALUE fetch (``block_until_ready``
  is async-acked by the axon tunnel).
"""

from __future__ import annotations

import time

import jax

# The carry accumulates step(c) * CARRY_FACTOR: 1/64 is > ulp at
# magnitude 1 in bf16, so every scan iteration sees genuinely
# different values.  FRESH_FACTOR scales each timed call's input so no
# two calls (including the compile warm-up) share input values.
CARRY_FACTOR = 0.015625
FRESH_FACTOR = 0.03125


def chain_program(step, n: int):
    """One jitted program: ``n`` iterations of ``c + step(c) *
    CARRY_FACTOR`` chained through the scan carry."""
    def body(c, _):
        return c + step(c) * CARRY_FACTOR, None

    return jax.jit(lambda q: jax.lax.scan(body, q, None, length=n)[0])


def median_fresh_s(g, x, reps: int = 5):
    """Median wall-time of ``reps`` fresh-input calls of ``g`` (plus
    the raw samples); compiles+warms on ``x`` first."""
    float(g(x).sum())                     # compile + one run
    ts = []
    for i in range(reps):
        xi = x * (1.0 + FRESH_FACTOR * (i + 1))
        t0 = time.time()
        float(g(xi).sum())                # host value fetch
        ts.append(time.time() - t0)
    return sorted(ts)[len(ts) // 2], ts


def chained_delta_ms(step, x, n1: int = 2, n2: int = 18,
                     reps: int = 5):
    """Per-call milliseconds of ``step`` via the chained-delta
    protocol.  Returns ``(ms, samples)`` where ``samples`` carries the
    raw per-rep wall times for both chain lengths; ``ms`` <= 0 means
    measurement noise won — callers must retry or report None, never
    publish the number."""
    hi, hs = median_fresh_s(chain_program(step, n2), x, reps)
    lo, ls = median_fresh_s(chain_program(step, n1), x, reps)
    ms = (hi - lo) / (n2 - n1) * 1e3
    return ms, {"lo_s": [round(t, 4) for t in ls],
                "hi_s": [round(t, 4) for t in hs]}
