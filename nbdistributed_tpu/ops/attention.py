"""Fused attention for TPU: Pallas flash-attention forward + reference path.

The reference framework has no first-party kernels (its compute is
whatever users type into cells), but a TPU-native framework's hot op is
attention, so this module provides:

* :func:`flash_attention` — blockwise online-softmax attention as a
  Pallas TPU kernel (forward), tiled for the MXU (128-lane blocks),
  with a custom VJP whose backward recomputes through the reference
  path.  No O(S^2) residuals are *saved across* the forward, but the
  recomputing backward itself materializes the (B,H,S,S) score matrix —
  training memory is O(S^2) in the backward until a blockwise Pallas
  backward lands; the kernel's memory advantage is forward/inference.
* :func:`attention_reference` — pure-jnp attention, numerically exact,
  used for the backward pass, for CPU execution, and as the test oracle.

Supports causal masking and grouped-query attention (n_kv_heads <
n_heads).  Layout: (batch, seq, heads, head_dim) — the native layout for
sequence-sharded training.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ._common import NEG_INF as _NEG_INF
from ._common import use_interpret as _shared_use_interpret


# ----------------------------------------------------------------------
# Reference implementation (oracle + backward + CPU path)

def attention_reference(q, k, v, *, causal: bool = True,
                        scale: float | None = None):
    """Exact attention.  q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D) with
    H % Hkv == 0 (grouped-query)."""
    B, Sq, H, D = q.shape
    _, Sk, Hkv, _ = k.shape
    if H % Hkv:
        raise ValueError(f"n_heads {H} not divisible by n_kv_heads {Hkv}")
    group = H // Hkv
    scale = scale if scale is not None else 1.0 / np.sqrt(D)

    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)

    # (B, H, Sq, Sk)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        logits = jnp.where(ki <= qi, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ----------------------------------------------------------------------
# Pallas forward kernel

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_k: int,
                  seq_k_valid: int, causal: bool, scale: float,
                  block_q: int):
    """One (batch*head, q-block) program: stream K/V blocks with the
    online-softmax recurrence (running max m, normalizer l, accumulator).

    ``seq_k`` is the (block-padded) buffer length; ``seq_k_valid`` the
    real key count — keys at or beyond it are masked out, so inputs of
    any length are handled exactly (the wrapper pads to block multiples).
    """
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale          # (Bq, D)
    q_idx = pl.program_id(1)

    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)

    num_k_blocks = pl.cdiv(seq_k, block_k)
    if causal:
        # Blocks strictly above the diagonal contribute nothing.
        last_block = jax.lax.div(
            (q_idx + 1) * block_q - 1, block_k) + 1
        num_iters = jnp.minimum(num_k_blocks, last_block)
    else:
        num_iters = num_k_blocks

    mask_keys = seq_k_valid < seq_k

    def body(kb, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k)].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k)].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (Bq, Bk)
        if causal or mask_keys:
            qi = (q_idx * block_q
                  + jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 0))
            ki = (kb * block_k
                  + jax.lax.broadcasted_iota(jnp.int32,
                                             (block_q, block_k), 1))
            keep = ki < seq_k_valid
            if causal:
                keep = keep & (ki <= qi)
            s = jnp.where(keep, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                        # (Bq, Bk)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, num_iters, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, scale: float,
                   block_q: int, block_k: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Sq, H, D = q.shape
    _, Sk, Hkv, _ = k.shape
    group = H // Hkv

    # Pad both sequence axes to block multiples; padded keys are masked
    # inside the kernel (dynamic-slice clamping would otherwise re-read
    # earlier rows), padded query rows are sliced off below.
    Sq_pad = -(-Sq // block_q) * block_q
    Sk_pad = -(-Sk // block_k) * block_k
    if Sq_pad != Sq:
        q = jnp.pad(q, ((0, 0), (0, Sq_pad - Sq), (0, 0), (0, 0)))
    if Sk_pad != Sk:
        k = jnp.pad(k, ((0, 0), (0, Sk_pad - Sk), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, Sk_pad - Sk), (0, 0), (0, 0)))

    # Kernel operates per (batch*head): fold B and H together and move
    # seq next-to-last so blocks are (seq, head_dim) MXU tiles.
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Sq_pad, D)
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, Sk_pad, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, Sk_pad, D)

    grid = (B * H, Sq_pad // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, seq_k=Sk_pad, seq_k_valid=Sk,
        causal=causal, scale=scale, block_q=block_q)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, Sq_pad, D), q.dtype),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda bh, qb: (bh, qb, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, Sk_pad, D), lambda bh, qb: (bh, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, Sk_pad, D), lambda bh, qb: (bh, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, block_q, D),
                                   lambda bh, qb: (bh, qb, 0),
                                   memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(qt, kt, vt)
    out = out.reshape(B, H, Sq_pad, D).transpose(0, 2, 1, 3)
    return out[:, :Sq] if Sq_pad != Sq else out


_use_interpret = _shared_use_interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    scale: float | None = None, block_q: int = 128,
                    block_k: int = 128):
    """Flash attention: fused, O(S) memory forward.

    q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D).  On non-TPU backends the
    Pallas kernel runs in interpreter mode (slow but exact), so tests
    exercise the same code path everywhere.
    """
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def _resolved_scale(scale, D):
    return scale if scale is not None else 1.0 / np.sqrt(D)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    D = q.shape[-1]
    Sq = q.shape[1]
    bq = min(block_q, Sq)
    bk = min(block_k, k.shape[1])
    out = _flash_forward(q, k, v, causal=causal,
                         scale=_resolved_scale(scale, D),
                         block_q=bq, block_k=bk,
                         interpret=_use_interpret())
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, residuals, g):
    """Backward by recomputation through the reference path — the
    flash-attention trade: no O(S^2) tensors survive the forward."""
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(
            q_, k_, v_, causal=causal,
            scale=_resolved_scale(scale, q.shape[-1])), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
