"""Fused attention for TPU: Pallas flash-attention forward + reference path.

The reference framework has no first-party kernels (its compute is
whatever users type into cells), but a TPU-native framework's hot op is
attention, so this module provides:

* :func:`flash_attention` — blockwise online-softmax attention as a
  Pallas TPU kernel, tiled for the MXU (128-lane blocks), with a
  blockwise Pallas backward (separate dQ and dK/dV kernels driven by
  the saved per-row logsumexp): no (B,H,S,S) score tensor is ever
  materialized in either direction, so training memory is O(S)
  end-to-end — the flash-attention trade in both passes.
* :func:`attention_reference` — pure-jnp attention, numerically exact,
  used for CPU execution and as the test oracle (including grad
  checks against the Pallas backward).

Supports causal masking and grouped-query attention (n_kv_heads <
n_heads).  Layout: (batch, seq, heads, head_dim) — the native layout for
sequence-sharded training.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ._common import NEG_INF as _NEG_INF
from ._common import use_interpret as _shared_use_interpret


# ----------------------------------------------------------------------
# Reference implementation (oracle + backward + CPU path)

def check_window(window, causal: bool) -> None:
    """The one window-argument validator, shared by every attention
    entry point (reference, flash, ring, Ulysses)."""
    if window is None:
        return
    if not causal:
        raise ValueError("sliding window implies causal attention")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def attention_reference(q, k, v, *, causal: bool = True,
                        scale: float | None = None,
                        window: int | None = None,
                        segment_ids=None, kv_segment_ids=None):
    """Exact attention.  q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D) with
    H % Hkv == 0 (grouped-query).  ``window``: sliding-window size —
    query row i attends keys in [i - window + 1, i] (Mistral-style;
    requires ``causal=True``).

    ``segment_ids`` (B, Sq) int: packed-document masking — a query
    attends only keys with the SAME segment id (``kv_segment_ids``
    defaults to ``segment_ids``, which requires Sq == Sk).  With
    ``causal=True`` the diagonal is always in-segment, so every row
    has at least one key; rows masked everywhere (possible only
    non-causally) are undefined — keep packed masking causal.
    """
    B, Sq, H, D = q.shape
    _, Sk, Hkv, _ = k.shape
    if H % Hkv:
        raise ValueError(f"n_heads {H} not divisible by n_kv_heads {Hkv}")
    check_window(window, causal)
    if segment_ids is not None and kv_segment_ids is None:
        if Sq != Sk:
            raise ValueError("segment_ids with Sq != Sk needs explicit "
                             "kv_segment_ids")
        kv_segment_ids = segment_ids
    group = H // Hkv
    scale = scale if scale is not None else 1.0 / np.sqrt(D)

    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)

    # (B, H, Sq, Sk); the keep mask stays broadcast-shaped — (Sq, Sk)
    # for the batch-invariant causal band, batch-extended only when
    # segments actually vary per row.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    keep = None
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        keep = ki <= qi
        if window is not None:
            keep = keep & (ki > qi - window)
        keep = keep[None, None]                      # (1, 1, Sq, Sk)
    if segment_ids is not None:
        seg = (jnp.asarray(segment_ids)[:, :, None]
               == jnp.asarray(kv_segment_ids)[:, None, :])  # (B, Sq, Sk)
        keep = seg[:, None] if keep is None else keep & seg[:, None]
    if keep is not None:
        logits = jnp.where(keep, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ----------------------------------------------------------------------
# Shared in-kernel masking / causal block-range helpers
#
# One definition for the offset-causal math used by the forward and both
# backward kernels — forward and backward must never disagree on which
# (qi, ki) pairs attend.

def _causal_k_iters(q_off, k_off, q_idx, block_q, block_k, num_k_blocks):
    """How many leading k-blocks a causal q-block can see: the largest
    key this block's last row may attend is q_off - k_off + last row."""
    qmax = q_off - k_off + (q_idx + 1) * block_q - 1
    return jnp.clip(jax.lax.div(qmax, block_k) + 1, 0, num_k_blocks)


def _causal_first_q_block(k_idx, q_off, k_off, block_q, block_k,
                          num_q_blocks):
    """First q-block whose rows can attend this k-block: rows before
    the block's first (offset) key never see it."""
    first_qi = jnp.maximum(k_idx * block_k + k_off - q_off, 0)
    return jnp.minimum(jax.lax.div(first_qi, block_q), num_q_blocks)


def _window_first_k_block(q_off, k_off, q_idx, block_q, block_k,
                          window, num_k_blocks):
    """With a sliding window, the earliest key this q block's first
    row can see is its position - window + 1."""
    lo = q_off - k_off + q_idx * block_q - window + 1
    return jnp.clip(jax.lax.div(lo, block_k), 0, num_k_blocks)


def _window_last_q_block(k_idx, q_off, k_off, block_q, block_k,
                         window, num_q_blocks):
    """With a sliding window, the last q row that can see this
    k-block's final key sits window - 1 rows after it."""
    hi_qi = (k_idx * block_k + block_k - 1) + k_off - q_off + window - 1
    return jnp.clip(jax.lax.div(hi_qi, block_q) + 1, 0, num_q_blocks)


def _keep_mask(q_idx, kb, *, block_q, block_k, q_off, k_off,
               seq_k_valid, causal, seq_q_valid=None, window=None,
               qseg=None, kseg=None):
    """(block_q, block_k) bool: which score entries are real — inside
    the valid key range, (optionally) inside the valid query range,
    at-or-below the offset causal diagonal, (optionally) within the
    sliding window, and (optionally) in the same packed-document
    segment (``qseg`` (block_q, 1) vs ``kseg`` (1, block_k))."""
    qi = (q_idx * block_q
          + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))
    ki = (kb * block_k
          + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))
    keep = ki < seq_k_valid
    if seq_q_valid is not None:
        keep = keep & (qi < seq_q_valid)
    if causal:
        keep = keep & (ki + k_off <= qi + q_off)
        if window is not None:
            keep = keep & (ki + k_off > qi + q_off - window)
    if qseg is not None:
        keep = keep & (qseg == kseg)
    return keep


# ----------------------------------------------------------------------
# Pallas forward kernel

def _flash_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                  block_k: int, seq_k: int, seq_k_valid: int,
                  causal: bool, scale: float, block_q: int,
                  window: int | None = None,
                  qseg_ref=None, kseg_ref=None):
    """One (batch*kv-head, q-block) program: stream K/V blocks with the
    online-softmax recurrence (running max m, normalizer l, accumulator).

    GQA is native: the program's q block carries all ``group = H/Hkv``
    query heads sharing this KV head — K/V are staged once per group
    (never expanded to H heads).  The group is processed by a *static
    Python unroll* with rank-2 dots, NOT a batched rank-3 dot_general:
    rank-2 is the only dot shape Mosaic reliably lowers (JAX's own TPU
    flash kernel holds to the same rule) — do not reintroduce batched
    dots here.

    ``seq_k`` is the (block-padded) buffer length; ``seq_k_valid`` the
    real key count — keys at or beyond it are masked out, so inputs of
    any length are handled exactly (the wrapper pads to block multiples).
    ``offs_ref`` holds (q_offset, k_offset): global positions of this
    chunk's first query/key row, so causal masking works when the
    inputs are one chunk of a larger sequence (ring attention hops);
    both are 0 for ordinary whole-sequence calls.  Rows whose keys are
    entirely masked self-heal through the online recurrence (their
    garbage acc/l is wiped by corr = exp(-inf) at the first real block)
    and surface lse ~ -inf, which the ring hop-combine weights to zero.
    Besides the output block, writes the per-row logsumexp (m + log l)
    — the only residual the blockwise backward needs.
    """
    from jax.experimental import pallas as pl

    G, D = q_ref.shape[1], q_ref.shape[3]
    q_idx = pl.program_id(1)
    q_off, k_off = offs_ref[0], offs_ref[1]
    # Per-group state as tuples of 2D arrays and a static Python loop
    # over the (small, static) group: every matmul stays rank-2 —
    # the only dot shape Mosaic is guaranteed to lower (JAX's own TPU
    # flash kernel holds to the same rule).
    qs = tuple(q_ref[0, g].astype(jnp.float32) * scale
               for g in range(G))                     # G x (Bq, D)
    accs = tuple(jnp.zeros((block_q, D), jnp.float32) for _ in range(G))
    ms = tuple(jnp.full((block_q, 1), _NEG_INF, jnp.float32)
               for _ in range(G))
    ls = tuple(jnp.zeros((block_q, 1), jnp.float32) for _ in range(G))

    num_k_blocks = pl.cdiv(seq_k, block_k)
    first_iter = 0
    if causal:
        num_iters = _causal_k_iters(q_off, k_off, q_idx, block_q,
                                    block_k, num_k_blocks)
        if window is not None:
            first_iter = _window_first_k_block(q_off, k_off, q_idx,
                                               block_q, block_k,
                                               window, num_k_blocks)
    else:
        num_iters = num_k_blocks

    mask_keys = seq_k_valid < seq_k
    has_seg = qseg_ref is not None
    qseg_blk = qseg_ref[0] if has_seg else None       # (Bq, 1)
    need_mask = causal or mask_keys or has_seg

    def body(kb, carry):
        accs, ms, ls = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k)].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k)].astype(jnp.float32)
        if need_mask:
            kseg_blk = (kseg_ref[0, :, pl.ds(kb * block_k, block_k)]
                        if has_seg else None)          # (1, Bk)
            keep = _keep_mask(q_idx, kb, block_q=block_q,
                              block_k=block_k, q_off=q_off, k_off=k_off,
                              seq_k_valid=seq_k_valid, causal=causal,
                              window=window, qseg=qseg_blk,
                              kseg=kseg_blk)
        new_acc, new_m, new_l = [], [], []
        for g in range(G):
            s = jax.lax.dot_general(
                qs[g], k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)   # (Bq, Bk)
            if need_mask:
                s = jnp.where(keep, s, _NEG_INF)
            m_new = jnp.maximum(ms[g],
                                jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)                    # (Bq, Bk)
            corr = jnp.exp(ms[g] - m_new)
            new_l.append(ls[g] * corr
                         + jnp.sum(p, axis=-1, keepdims=True))
            new_acc.append(accs[g] * corr + jax.lax.dot_general(
                p, v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
            new_m.append(m_new)
        return tuple(new_acc), tuple(new_m), tuple(new_l)

    accs, ms, ls = jax.lax.fori_loop(first_iter, num_iters, body,
                                     (accs, ms, ls))
    for g in range(G):
        l_safe = jnp.maximum(ls[g], 1e-30)
        o_ref[0, g] = (accs[g] / l_safe).astype(o_ref.dtype)
        lse_ref[0, g] = (ms[g] + jnp.log(l_safe))[:, 0]


def _fold_heads(x, S_pad):
    """(B, S, Hkv, D) → (B*Hkv, S_pad, D), zero-padding the seq axis.
    The per-(batch, kv-head) layout gives every kernel program
    contiguous (seq, head_dim) MXU tiles."""
    B, S, H, D = x.shape
    if S_pad != S:
        x = jnp.pad(x, ((0, 0), (0, S_pad - S), (0, 0), (0, 0)))
    return x.transpose(0, 2, 1, 3).reshape(B * H, S_pad, D)


def _fold_q_gqa(x, Hkv: int, S_pad: int):
    """(B, S, H, D) → (B*Hkv, group, S_pad, D): query heads grouped
    under the KV head they attend (head h ↔ kv head h // group), so a
    kernel program over (batch, kv-head) sees its whole group as a
    leading batch dim."""
    B, S, H, D = x.shape
    group = H // Hkv
    if S_pad != S:
        x = jnp.pad(x, ((0, 0), (0, S_pad - S), (0, 0), (0, 0)))
    return (x.reshape(B, S_pad, Hkv, group, D)
            .transpose(0, 2, 3, 1, 4)
            .reshape(B * Hkv, group, S_pad, D))


def _unfold_q_gqa(x, B, Hkv, S):
    """(B*Hkv, group, S_pad, D) → (B, S, H, D), dropping seq padding."""
    _, group, S_pad, D = x.shape
    return (x.reshape(B, Hkv, group, S_pad, D)
            .transpose(0, 3, 1, 2, 4)
            .reshape(B, S_pad, Hkv * group, D)[:, :S])


def _unfold_heads(x, B, H, S):
    """(B*H, S_pad, D) → (B, S, H, D), dropping seq padding."""
    x = x.reshape(B, H, x.shape[1], -1).transpose(0, 2, 1, 3)
    return x[:, :S]


def _offsets_array(offsets):
    if offsets is None:
        return jnp.zeros((2,), jnp.int32)
    q_off, k_off = offsets
    return jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])


def _seg_planes(segment_ids, kv_segment_ids, Sq_pad, Sk_pad):
    """Stage packed-document segment ids for the kernels.

    Returns (qseg (B, Sq_pad, 1), kseg (B, 1, Sk_pad)) int32 — layouts
    whose last-two block dims satisfy Mosaic's (8-divisible | equal)
    rule for per-q-block and full-row staging respectively.

    The pad sentinels (-1 queries / -2 keys) are belt-and-braces, not
    load-bearing: padded KEYS are always excluded by _keep_mask's
    ``ki < seq_k_valid`` term regardless of segment values, and padded
    QUERY rows are sliced off by the wrappers — so user segment ids
    may be any integers (equality defines membership), including
    negatives that happen to collide with a sentinel."""
    qs = jnp.asarray(segment_ids, jnp.int32)
    ks = jnp.asarray(kv_segment_ids, jnp.int32)
    qs = jnp.pad(qs, ((0, 0), (0, Sq_pad - qs.shape[1])),
                 constant_values=-1)
    ks = jnp.pad(ks, ((0, 0), (0, Sk_pad - ks.shape[1])),
                 constant_values=-2)
    return qs[:, :, None], ks[:, None, :]


def _flash_forward(q, k, v, *, causal: bool, scale: float,
                   block_q: int, block_k: int, interpret: bool,
                   offsets=None, window: int | None = None,
                   segment_ids=None, kv_segment_ids=None):
    """Returns (out (B,Sq,H,D), lse (B*Hkv, group, Sq_pad) float32).

    K/V are staged at their native Hkv heads — the GQA group rides the
    q block as a batch dim, so no repeated-KV buffer ever exists.
    ``offsets`` — optional (q_offset, k_offset) traced scalars giving
    the global position of row 0 of q and of k/v, for chunk-of-a-
    larger-sequence calls (ring attention).  ``segment_ids`` — packed-
    document masking (see :func:`attention_reference`).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Sq, H, D = q.shape
    _, Sk, Hkv, _ = k.shape
    group = H // Hkv

    # Pad both sequence axes to block multiples; padded keys are masked
    # inside the kernel (dynamic-slice clamping would otherwise re-read
    # earlier rows), padded query rows are sliced off below.
    Sq_pad = -(-Sq // block_q) * block_q
    Sk_pad = -(-Sk // block_k) * block_k

    qt = _fold_q_gqa(q, Hkv, Sq_pad)      # (B*Hkv, G, Sq_pad, D)
    kt = _fold_heads(k, Sk_pad)           # (B*Hkv, Sk_pad, D)
    vt = _fold_heads(v, Sk_pad)

    grid = (B * Hkv, Sq_pad // block_q)
    has_seg = segment_ids is not None
    in_specs = [
        pl.BlockSpec((1, group, block_q, D),
                     lambda bh, qb, offs: (bh, 0, qb, 0)),
        pl.BlockSpec((1, Sk_pad, D),
                     lambda bh, qb, offs: (bh, 0, 0)),
        pl.BlockSpec((1, Sk_pad, D),
                     lambda bh, qb, offs: (bh, 0, 0)),
    ]
    args = [qt, kt, vt]
    if has_seg:
        qseg, kseg = _seg_planes(segment_ids, kv_segment_ids,
                                 Sq_pad, Sk_pad)
        # Segments are per (batch, position): the index map recovers
        # the batch row from the folded batch*kv-head program id.
        in_specs += [
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qb, offs: (bh // Hkv, qb, 0)),
            pl.BlockSpec((1, 1, Sk_pad),
                         lambda bh, qb, offs: (bh // Hkv, 0, 0)),
        ]
        args += [qseg, kseg]

    base = functools.partial(
        _flash_kernel, block_k=block_k, seq_k=Sk_pad, seq_k_valid=Sk,
        causal=causal, scale=scale, block_q=block_q, window=window)

    def kernel(offs_ref, *refs):
        if has_seg:
            (q_r, k_r, v_r, qs_r, ks_r, o_r, l_r) = refs
            base(offs_ref, q_r, k_r, v_r, o_r, l_r,
                 qseg_ref=qs_r, kseg_ref=ks_r)
        else:
            (q_r, k_r, v_r, o_r, l_r) = refs
            base(offs_ref, q_r, k_r, v_r, o_r, l_r)

    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((B * Hkv, group, Sq_pad, D), q.dtype),
            jax.ShapeDtypeStruct((B * Hkv, group, Sq_pad), jnp.float32),
        ],
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, group, block_q, D),
                             lambda bh, qb, offs: (bh, 0, qb, 0)),
                pl.BlockSpec((1, group, block_q),
                             lambda bh, qb, offs: (bh, 0, qb)),
            ],
        ),
        interpret=interpret,
    )(_offsets_array(offsets), *args)
    return _unfold_q_gqa(out, B, Hkv, Sq), lse


# ----------------------------------------------------------------------
# Pallas backward kernels (FlashAttention-2 style)
#
# With the saved logsumexp L_i the softmax row is reconstructible
# blockwise as p = exp(s - L), so the backward is two streaming passes
# that never materialize (Sq, Sk):
#   delta_i = sum_d dO_id * O_id                    (tiny, plain XLA)
#   dV_j    = sum_i p_ij dO_i
#   dS_ij   = p_ij (dO_i . V_j - delta_i)
#   dQ_i    = scale * sum_j dS_ij K_j
#   dK_j    = scale * sum_i dS_ij Q_i
# The dQ kernel grids over q-blocks streaming K/V; the dK/dV kernel
# grids over k-blocks streaming Q/dO (starting at the diagonal block
# when causal — earlier q rows cannot attend to this k block).

def _flash_bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         dta_ref, dq_ref, *, block_k: int, seq_k: int,
                         seq_k_valid: int, causal: bool, scale: float,
                         block_q: int, window: int | None = None,
                         qseg_ref=None, kseg_ref=None):
    from jax.experimental import pallas as pl

    G, D = q_ref.shape[1], q_ref.shape[3]
    q_idx = pl.program_id(1)
    q_off, k_off = offs_ref[0], offs_ref[1]
    # Static per-group unroll, rank-2 dots only (see _flash_kernel).
    qs = tuple(q_ref[0, g].astype(jnp.float32) * scale
               for g in range(G))
    dos = tuple(do_ref[0, g].astype(jnp.float32) for g in range(G))
    lses = tuple(lse_ref[0, g][:, None] for g in range(G))
    deltas = tuple(dta_ref[0, g][:, None] for g in range(G))

    num_k_blocks = pl.cdiv(seq_k, block_k)
    first_iter = 0
    if causal:
        num_iters = _causal_k_iters(q_off, k_off, q_idx, block_q,
                                    block_k, num_k_blocks)
        if window is not None:
            first_iter = _window_first_k_block(q_off, k_off, q_idx,
                                               block_q, block_k,
                                               window, num_k_blocks)
    else:
        num_iters = num_k_blocks

    has_seg = qseg_ref is not None
    qseg_blk = qseg_ref[0] if has_seg else None       # (Bq, 1)

    def body(kb, dq_accs):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k)].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k)].astype(jnp.float32)
        kseg_blk = (kseg_ref[0, :, pl.ds(kb * block_k, block_k)]
                    if has_seg else None)              # (1, Bk)
        keep = _keep_mask(q_idx, kb, block_q=block_q, block_k=block_k,
                          q_off=q_off, k_off=k_off,
                          seq_k_valid=seq_k_valid, causal=causal,
                          window=window, qseg=qseg_blk, kseg=kseg_blk)
        out = []
        for g in range(G):
            s = jax.lax.dot_general(
                qs[g], k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)   # (Bq, Bk)
            s = jnp.where(keep, s, _NEG_INF)
            p = jnp.exp(s - lses[g])                  # (Bq, Bk)
            dp = jax.lax.dot_general(
                dos[g], v_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)   # (Bq, Bk)
            ds = p * (dp - deltas[g])
            out.append(dq_accs[g] + jax.lax.dot_general(
                ds, k_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        return tuple(out)

    dqs = jax.lax.fori_loop(
        first_iter, num_iters, body,
        tuple(jnp.zeros((block_q, D), jnp.float32) for _ in range(G)))
    for g in range(G):
        dq_ref[0, g] = (dqs[g] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(offs_ref, k_ref, v_ref, q_ref, do_ref, lse_ref,
                          dta_ref, dk_ref, dv_ref, dk_s, dv_s, *,
                          block_q: int, seq_q: int, seq_q_valid: int,
                          seq_k_valid: int, causal: bool, scale: float,
                          block_k: int, group: int,
                          window: int | None = None,
                          qseg_ref=None, kseg_ref=None):
    """dK/dV for one k-block.  The GQA group rides the *grid* (innermost
    dim, sequential on-core): each step stages only one head's
    (Sq_pad, D) q/dO plane — the same per-program VMEM footprint as an
    MHA kernel — and accumulates this k-block's dk/dv across the group
    in fp32 scratch, writing out on the last head."""
    from jax.experimental import pallas as pl

    k_blk = k_ref[0].astype(jnp.float32)              # (Bk, D)
    v_blk = v_ref[0].astype(jnp.float32)
    k_idx = pl.program_id(1)
    g = pl.program_id(2)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(g == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    num_q_blocks = pl.cdiv(seq_q, block_q)
    last_block = num_q_blocks
    if causal:
        first_block = _causal_first_q_block(k_idx, q_off, k_off,
                                            block_q, block_k,
                                            num_q_blocks)
        if window is not None:
            last_block = _window_last_q_block(k_idx, q_off, k_off,
                                              block_q, block_k,
                                              window, num_q_blocks)
    else:
        first_block = 0

    has_seg = qseg_ref is not None
    kseg_blk = kseg_ref[0] if has_seg else None       # (1, Bk)

    def body(qb, carry):
        dk_acc, dv_acc = carry
        q_blk = (q_ref[0, 0, pl.ds(qb * block_q, block_q)]
                 .astype(jnp.float32) * scale)        # (Bq, D)
        do_blk = do_ref[0, 0, pl.ds(qb * block_q, block_q)].astype(
            jnp.float32)
        # lse/delta arrive with a trailing unit dim (see the caller:
        # Mosaic requires the last two block dims be (8k, 128k) or
        # equal to the array dims — (1, Sq_pad) with group > 1 is
        # neither, (Sq_pad, 1) matching the array is).
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]   # (Bq, 1)
        delta = dta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        qseg_blk = (qseg_ref[0, pl.ds(qb * block_q, block_q)]
                    if has_seg else None)             # (Bq, 1)
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (Bq, Bk)
        # seq_q_valid: padded q rows carry a meaningless lse — mask
        # them here so they contribute nothing to dk/dv.
        keep = _keep_mask(qb, k_idx, block_q=block_q, block_k=block_k,
                          q_off=q_off, k_off=k_off,
                          seq_k_valid=seq_k_valid, causal=causal,
                          seq_q_valid=seq_q_valid, window=window,
                          qseg=qseg_blk, kseg=kseg_blk)
        s = jnp.where(keep, s, _NEG_INF)
        p = jnp.exp(s - lse)                          # (Bq, Bk)
        dv_new = dv_acc + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (Bk, D)
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (Bq, Bk)
        ds = p * (dp - delta)
        dk_new = dk_acc + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (Bk, D)
        return dk_new, dv_new

    zero = jnp.zeros((block_k, k_blk.shape[-1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_block, last_block, body,
                               (zero, zero))
    dk_s[...] += dk
    dv_s[...] += dv

    @pl.when(g == group - 1)
    def _finalize():
        # q_blk was pre-scaled, so dk already carries the
        # d(s)/d(k) = scale * q chain term.
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _flash_bwd_prep(q, o, g, block_q: int, Hkv: int):
    """Fold the hop-invariant backward inputs once: q/dO in the grouped
    kernel layout plus delta_i = rowsum(dO * O) (one elementwise pass
    XLA fuses; padded rows give 0).  Split out so ring attention can
    hoist this out of its per-hop loop instead of redoing it n times."""
    Sq_pad = -(-q.shape[1] // block_q) * block_q
    qt = _fold_q_gqa(q, Hkv, Sq_pad)      # (B*Hkv, G, Sq_pad, D)
    got = _fold_q_gqa(g, Hkv, Sq_pad)
    ot = _fold_q_gqa(o, Hkv, Sq_pad)
    delta = jnp.sum(got.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1)              # (B*Hkv, G, Sq_pad)
    return qt, got, delta


def _flash_backward(q, k, v, o, lse, g, *, causal: bool, scale: float,
                    block_q: int, block_k: int, interpret: bool,
                    offsets=None, window: int | None = None,
                    segment_ids=None, kv_segment_ids=None):
    qt, got, delta = _flash_bwd_prep(q, o, g, block_q, k.shape[2])
    return _flash_backward_folded(
        qt, got, delta, lse, k, v, B=q.shape[0], Sq=q.shape[1],
        q_dtype=q.dtype, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        offsets=offsets, window=window, segment_ids=segment_ids,
        kv_segment_ids=kv_segment_ids)


def _flash_backward_folded(qt, got, delta, lse, k, v, *, B: int, Sq: int,
                           q_dtype, causal: bool, scale: float,
                           block_q: int, block_k: int, interpret: bool,
                           offsets=None, window: int | None = None,
                           segment_ids=None, kv_segment_ids=None):
    """The two backward pallas_calls over pre-folded q/dO/delta (see
    :func:`_flash_bwd_prep`); k/v arrive raw (B, Sk, Hkv, D) and stay
    at Hkv heads throughout — the dK/dV kernel's contractions sum the
    GQA group inside the matmul."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _, Sk, Hkv, D = k.shape
    group = qt.shape[1]
    Sq_pad = qt.shape[2]
    Sk_pad = -(-Sk // block_k) * block_k

    kt = _fold_heads(k, Sk_pad)           # (B*Hkv, Sk_pad, D)
    vt = _fold_heads(v, Sk_pad)
    offs = _offsets_array(offsets)
    has_seg = segment_ids is not None
    if has_seg:
        qseg, kseg = _seg_planes(segment_ids, kv_segment_ids,
                                 Sq_pad, Sk_pad)

    dq_base = functools.partial(
        _flash_bwd_dq_kernel, block_k=block_k, seq_k=Sk_pad,
        seq_k_valid=Sk, causal=causal, scale=scale, block_q=block_q,
        window=window)

    def dq_kernel(offs_ref, *refs):
        if has_seg:
            (q_r, k_r, v_r, do_r, l_r, d_r, qs_r, ks_r, dq_r) = refs
            dq_base(offs_ref, q_r, k_r, v_r, do_r, l_r, d_r, dq_r,
                    qseg_ref=qs_r, kseg_ref=ks_r)
        else:
            (q_r, k_r, v_r, do_r, l_r, d_r, dq_r) = refs
            dq_base(offs_ref, q_r, k_r, v_r, do_r, l_r, d_r, dq_r)

    dq_in_specs = [
        pl.BlockSpec((1, group, block_q, D),
                     lambda bh, qb, offs: (bh, 0, qb, 0)),  # q
        pl.BlockSpec((1, Sk_pad, D),
                     lambda bh, qb, offs: (bh, 0, 0)),      # k
        pl.BlockSpec((1, Sk_pad, D),
                     lambda bh, qb, offs: (bh, 0, 0)),      # v
        pl.BlockSpec((1, group, block_q, D),
                     lambda bh, qb, offs: (bh, 0, qb, 0)),  # dO
        pl.BlockSpec((1, group, block_q),
                     lambda bh, qb, offs: (bh, 0, qb)),     # lse
        pl.BlockSpec((1, group, block_q),
                     lambda bh, qb, offs: (bh, 0, qb)),     # dta
    ]
    dq_args = [qt, kt, vt, got, lse, delta]
    if has_seg:
        dq_in_specs += [
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, qb, offs: (bh // Hkv, qb, 0)),
            pl.BlockSpec((1, 1, Sk_pad),
                         lambda bh, qb, offs: (bh // Hkv, 0, 0)),
        ]
        dq_args += [qseg, kseg]
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((B * Hkv, group, Sq_pad, D),
                                       q_dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B * Hkv, Sq_pad // block_q),
            in_specs=dq_in_specs,
            out_specs=pl.BlockSpec((1, group, block_q, D),
                                   lambda bh, qb, offs: (bh, 0, qb, 0)),
        ),
        interpret=interpret,
    )(offs, *dq_args)

    dkv_base = functools.partial(
        _flash_bwd_dkv_kernel, block_q=block_q, seq_q=Sq_pad,
        seq_q_valid=Sq, seq_k_valid=Sk, causal=causal, scale=scale,
        block_k=block_k, group=group, window=window)

    def dkv_kernel(offs_ref, *refs):
        if has_seg:
            (k_r, v_r, q_r, do_r, l_r, d_r, qs_r, ks_r,
             dk_r, dv_r, dk_s, dv_s) = refs
            dkv_base(offs_ref, k_r, v_r, q_r, do_r, l_r, d_r,
                     dk_r, dv_r, dk_s, dv_s,
                     qseg_ref=qs_r, kseg_ref=ks_r)
        else:
            (k_r, v_r, q_r, do_r, l_r, d_r,
             dk_r, dv_r, dk_s, dv_s) = refs
            dkv_base(offs_ref, k_r, v_r, q_r, do_r, l_r, d_r,
                     dk_r, dv_r, dk_s, dv_s)

    dkv_in_specs = [
        pl.BlockSpec((1, block_k, D),
                     lambda bh, kb, g, offs: (bh, kb, 0)),   # k
        pl.BlockSpec((1, block_k, D),
                     lambda bh, kb, g, offs: (bh, kb, 0)),   # v
        pl.BlockSpec((1, 1, Sq_pad, D),
                     lambda bh, kb, g, offs: (bh, g, 0, 0)),  # q
        pl.BlockSpec((1, 1, Sq_pad, D),
                     lambda bh, kb, g, offs: (bh, g, 0, 0)),  # dO
        # lse/delta get a trailing unit dim so the last two
        # block dims (Sq_pad, 1) equal the array dims — the
        # (1, 1, Sq_pad) layout fails Mosaic's block-shape
        # rule whenever group is not 1 or a multiple of 8.
        pl.BlockSpec((1, 1, Sq_pad, 1),
                     lambda bh, kb, g, offs: (bh, g, 0, 0)),  # lse
        pl.BlockSpec((1, 1, Sq_pad, 1),
                     lambda bh, kb, g, offs: (bh, g, 0, 0)),  # dta
    ]
    dkv_args = [kt, vt, qt, got, lse[..., None], delta[..., None]]
    if has_seg:
        dkv_in_specs += [
            pl.BlockSpec((1, Sq_pad, 1),
                         lambda bh, kb, g, offs: (bh // Hkv, 0, 0)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bh, kb, g, offs: (bh // Hkv, 0, kb)),
        ]
        dkv_args += [qseg, kseg]
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((B * Hkv, Sk_pad, D), k.dtype),
            jax.ShapeDtypeStruct((B * Hkv, Sk_pad, D), v.dtype),
        ],
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            # Group innermost: sequential on-core, so the fp32 scratch
            # accumulators carry this k-block's dk/dv across the
            # group's heads; q/dO stage one (Sq_pad, D) plane at a time.
            grid=(B * Hkv, Sk_pad // block_k, group),
            in_specs=dkv_in_specs,
            out_specs=[
                pl.BlockSpec((1, block_k, D),
                             lambda bh, kb, g, offs: (bh, kb, 0)),
                pl.BlockSpec((1, block_k, D),
                             lambda bh, kb, g, offs: (bh, kb, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),   # dk
                pltpu.VMEM((block_k, D), jnp.float32),   # dv
            ],
        ),
        interpret=interpret,
    )(offs, *dkv_args)

    dq = _unfold_q_gqa(dq, B, Hkv, Sq)
    dk = _unfold_heads(dk, B, Hkv, Sk)
    dv = _unfold_heads(dv, B, Hkv, Sk)
    return dq, dk, dv


_use_interpret = _shared_use_interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True,
                    scale: float | None = None,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    window: int | None = None,
                    segment_ids=None):
    """Flash attention: fused, O(S) memory forward.

    q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D).  ``window``: sliding-window
    size (Mistral-style, causal only) — both passes prune k/q blocks
    outside the band, so compute is O(S * window) instead of O(S^2/2).
    ``segment_ids`` (B, S) int: packed-document masking — queries
    attend only keys in the same segment (requires Sq == Sk; compose
    with causal for the standard packed-pretraining mask).  Both
    backward kernels apply the identical mask.
    ``block_q``/``block_k`` default to the per-shape tuned table
    (:data:`TUNED_BLOCKS`, measured by ``tune_flash.py`` on a live
    chip) falling back to 128.  On non-TPU backends the Pallas kernel
    runs in interpreter mode (slow but exact), so tests exercise the
    same code path everywhere.
    """
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                      window, segment_ids)[0]


def _resolved_scale(scale, D):
    return scale if scale is not None else 1.0 / np.sqrt(D)


# (Sq, Sk, head_dim, gqa_group) -> (block_q, block_k), measured on a
# live v5e by tune_flash.py's chained-timing sweep (see BASELINE.md for
# the sweep protocol and numbers).  The group (H // Hkv) is part of the
# key because it sets the q-block's batch extent inside the kernel —
# MHA (group 1) and GQA (group > 1) tune differently at the same S/D.
# Consulted only when the caller passes no explicit block sizes; empty
# entries fall back to 128x128.  Seeded from ops/tuned_blocks.json
# (written by tune_flash.py on a live chip — see ops/_tuned.py).
from ._tuned import load as _load_tuned

TUNED_BLOCKS: dict = _load_tuned()[0]
_DEFAULT_BLOCK = 128


def _block_sizes(block_q, block_k, Sq, Sk, D=None, group=None):
    """Resolve block sizes: explicit args win; None consults the tuned
    per-shape table, then the 128 default; both clamp to the array."""
    if block_q is None or block_k is None:
        tq, tk = TUNED_BLOCKS.get((Sq, Sk, D, group),
                                  (_DEFAULT_BLOCK, _DEFAULT_BLOCK))
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    return min(block_q, Sq), min(block_k, Sk)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, window=None,
               segment_ids=None):
    check_window(window, causal)
    if segment_ids is not None and q.shape[1] != k.shape[1]:
        raise ValueError("segment_ids requires Sq == Sk (packed "
                         "self-attention)")
    D = q.shape[-1]
    bq, bk = _block_sizes(block_q, block_k, q.shape[1], k.shape[1], D,
                          q.shape[2] // k.shape[2])
    out, lse = _flash_forward(q, k, v, causal=causal,
                              scale=_resolved_scale(scale, D),
                              block_q=bq, block_k=bk,
                              interpret=_use_interpret(),
                              window=window, segment_ids=segment_ids,
                              kv_segment_ids=segment_ids)
    return out, (q, k, v, out, lse, segment_ids)


def _flash_bwd(causal, scale, block_q, block_k, window, residuals, g):
    """Blockwise Pallas backward: reconstructs each score block from
    the saved logsumexp, so no O(S^2) tensor exists in the backward
    either."""
    q, k, v, out, lse, segment_ids = residuals
    bq, bk = _block_sizes(block_q, block_k, q.shape[1], k.shape[1],
                          q.shape[-1], q.shape[2] // k.shape[2])
    dq, dk, dv = _flash_backward(
        q, k, v, out, lse, g, causal=causal,
        scale=_resolved_scale(scale, q.shape[-1]),
        block_q=bq, block_k=bk,
        interpret=_use_interpret(), window=window,
        segment_ids=segment_ids, kv_segment_ids=segment_ids)
    if segment_ids is None:
        return dq, dk, dv, None
    # Integer primal: its cotangent is the symbolic-zero float0.
    dseg = np.zeros(segment_ids.shape, jax.dtypes.float0)
    return dq, dk, dv, dseg


flash_attention.defvjp(_flash_fwd, _flash_bwd)
