"""Cell effect inference: the read/write/collective footprint that
makes concurrent scheduling provably safe (the ISSUE 9 tentpole).

PR 8's gateway shipped ``NBD_POOL_MESH_SLOTS`` with a stated hazard:
more than one concurrent cell is only safe when the overlapping cells
are collective-free, because concurrent broadcasts carry no cross-rank
ordering — two tenants' collectives can pair up mismatched and hang
the shared mesh.  Nothing *proved* a cell collective-free, so the knob
was effectively unusable.  This module is that proof, plus the name
footprint ROADMAP item 3 (async pipelined dispatch) needs to know cell
N+1 is independent of cell N.

For one cell, :func:`infer_effects` returns an :class:`EffectReport`:

- **name footprint** — free names the cell *reads*, names it *binds*
  at module scope (``writes``), object-*mutation* targets
  (``x.attr = …``, ``x[k] = …``, known mutator methods like
  ``x.append(...)``), and ``del``-ed names — including ``global``
  escapes out of function bodies and augmented assigns (read+write).
  Dynamic namespace escapes (``exec``/``eval``, star-imports,
  ``globals()``/``vars()``/``locals()`` writes, unparseable source)
  yield an explicit ``opaque`` verdict that conservatively poisons the
  whole namespace: an opaque cell depends on everything and everything
  after it depends on it.

- **collective footprint** — the *ordered* sequence of collective call
  sites the cell can reach from module level, with a three-way
  verdict: ``"none"`` (proven collective-free), ``"exact"`` (the
  sites are statically enumerable, in order), or ``"unknown"``
  (collectives may hide behind calls the analyzer cannot see
  through).  Calls into same-cell ``def``\\ s are resolved **one level
  deep**; anything deeper, any call into a user/framework function the
  analyzer cannot vet, and any host-sync call on a possibly-sharded
  array (``.item()`` on a cross-host array gathers) records a *taint*
  and degrades the verdict to ``unknown`` — never to a false "free".
  Calls whose root is provably inert (builtins, pure stdlib modules,
  ``numpy``/``jnp``) stay safe, so ordinary compute cells can be
  *proven* free rather than merely assumed.

- **host-sync / purity flags** — folds in the cellcheck
  host-sync-in-loop detection (`.item()`/`.tolist()`/
  ``block_until_ready``/``device_get``/printing computed values inside
  a loop) plus a cell-wide ``host_sync`` flag and a ``pure`` property
  (touches no names, no collectives, no host syncs, not opaque).

Consumers: the gateway scheduler's effects-aware admission mode
(``NBD_POOL_SCHED_EFFECTS=1`` — only *proven*-free cells may overlap a
collective-bearing cell; unknown/opaque cells serialize with a verdict
naming the reason) and the preflight store's per-session cell
dependency DAG (``%dist_lint deps``), the declared substrate for
ROADMAP item 3's in-flight window.

Stdlib-only (ast + builtins), shares the collective vocabulary and the
IPython stripping with :mod:`cellcheck` / :mod:`ipycompat`, and never
raises: source the analyzer cannot read comes back opaque.
"""

from __future__ import annotations

import ast
import builtins
from dataclasses import dataclass, field

from .cellcheck import COLLECTIVE_NAMES, HOST_SYNC_ATTRS
from .ipycompat import non_python_cell_magic, strip_ipython

_BUILTIN_NAMES = frozenset(dir(builtins))

# Modules that can never reach a mesh collective: pure stdlib plus
# numpy (host-only) and jax.numpy (device compute; the collectives
# live in lax/dist/multihost_utils, reached via names the classifier
# already treats as collective or unvetted).  `jax` itself is NOT
# safe: jax.jit/shard_map/pmap products can run psums when called.
SAFE_MODULES = frozenset({
    "time", "math", "os", "sys", "json", "re", "random", "itertools",
    "functools", "collections", "statistics", "string", "textwrap",
    "pathlib", "dataclasses", "typing", "heapq", "bisect", "copy",
    "pprint", "numpy", "jax.numpy",
})

# Ambient names assumed to denote those modules when the cell does not
# bind them itself (the worker seeds np/jnp; time/math/os/... are the
# idiomatic stdlib spellings).  A cell that REBINDS one of these to
# anything that is not a safe import loses the assumption.
SAFE_CALL_ROOTS = frozenset(
    {m for m in SAFE_MODULES if "." not in m} | {"np", "jnp"})

# Reading globals()/vars()/locals() is fine; WRITING through them is a
# dynamic namespace escape the static footprint cannot see.
_DYNAMIC_NS = frozenset({"globals", "vars", "locals"})
_NS_WRITE_METHODS = frozenset({"update", "setdefault", "pop",
                               "popitem", "clear"})

# Method names that mutate their receiver in place — conservative
# extras for the mutation footprint (same family the self-lint's
# thread pass recognizes).
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "update", "pop", "popleft",
    "popitem", "remove", "discard", "clear", "setdefault", "extend",
    "insert", "sort", "reverse",
})

# Builtin decorators that provably never INVOKE the function they
# wrap at application time (they build descriptors around it).
_NON_INVOKING_DECORATORS = frozenset({
    "staticmethod", "classmethod", "property",
})

_MAX_TAINTS = 8

VERDICT_NONE = "none"
VERDICT_EXACT = "exact"
VERDICT_UNKNOWN = "unknown"


@dataclass
class CollectiveSite:
    """One statically-visible collective call site."""

    op: str
    line: int
    in_loop: bool = False
    conditional: bool = False
    via: str | None = None   # reached through this same-cell def

    def as_dict(self) -> dict:
        d = {"op": self.op, "line": self.line}
        if self.in_loop:
            d["in_loop"] = True
        if self.conditional:
            d["conditional"] = True
        if self.via:
            d["via"] = self.via
        return d

    def render(self) -> str:
        out = f"{self.op}@L{self.line}"
        if self.via:
            out += f" (via {self.via})"
        flags = [f for f, on in (("loop", self.in_loop),
                                 ("cond", self.conditional)) if on]
        if flags:
            out += f" [{','.join(flags)}]"
        return out


@dataclass
class EffectReport:
    """Everything the scheduler and the dependency DAG need to know
    about one cell without running it."""

    parsed: bool = True
    opaque: bool = False
    opaque_reasons: tuple = ()
    reads: frozenset = frozenset()      # free names read
    writes: frozenset = frozenset()     # names bound at module scope
    mutates: frozenset = frozenset()    # objects mutated in place
    deletes: frozenset = frozenset()    # names del-ed at module scope
    collectives: tuple = ()             # ordered CollectiveSites
    collective_verdict: str = VERDICT_UNKNOWN
    taints: tuple = ()                  # why the verdict is unknown
    host_sync: bool = False
    host_sync_in_loop: bool = False
    # Ambient names this cell RE-ARMED by importing the real module
    # (`import numpy as np`): excluded from ambient_poison().
    safe_rearms: frozenset = frozenset()

    @property
    def touched(self) -> frozenset:
        """Names a later cell could observe a change to — the write
        side of the dependency DAG's write-read edges."""
        return self.writes | self.mutates | self.deletes

    @property
    def collective_free(self) -> bool:
        """PROVEN free — the only verdict that may overlap a running
        collective-bearing cell under effects admission."""
        return (self.parsed and not self.opaque
                and self.collective_verdict == VERDICT_NONE)

    @property
    def pure(self) -> bool:
        """Namespace-pure and mesh-silent: safe to reorder freely."""
        return (self.parsed and not self.opaque and not self.touched
                and self.collective_verdict == VERDICT_NONE
                and not self.host_sync)

    def as_dict(self) -> dict:
        """JSON-safe summary (the preflight store's entry shape)."""
        return {
            "parsed": self.parsed,
            "opaque": self.opaque,
            "opaque_reasons": list(self.opaque_reasons),
            "reads": sorted(self.reads),
            "writes": sorted(self.writes),
            "mutates": sorted(self.mutates),
            "deletes": sorted(self.deletes),
            "collectives": [s.as_dict() for s in self.collectives],
            "collective_verdict": self.collective_verdict,
            "taints": list(self.taints),
            "host_sync": self.host_sync,
            "host_sync_in_loop": self.host_sync_in_loop,
            "pure": self.pure,
        }


def collective_class(report: EffectReport | None) -> str:
    """The scheduler's three-way admission class for one cell:
    ``"free"`` (proven collective-free — may overlap anything),
    ``"bearing"`` (proven collective sites — must run alone among
    non-free cells), ``"unknown"`` (opaque/tainted — treated like
    bearing, with the verdict naming the uncertainty)."""
    if report is None or not report.parsed or report.opaque:
        return "unknown"
    if report.collective_verdict == VERDICT_NONE:
        return "free"
    if report.collective_verdict == VERDICT_EXACT:
        return "bearing"
    return "unknown"


# ----------------------------------------------------------------------


def _base_name(node: ast.AST) -> str | None:
    """The root Name of an attribute/call chain:
    ``jnp.ones(2).sum`` → ``jnp``; non-name bases → None."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def _param_names(args: ast.arguments) -> set[str]:
    """Every parameter name an ast.arguments node binds."""
    names = {a.arg for a in (args.args + args.posonlyargs
                             + args.kwonlyargs)}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def _pattern_names(pattern: ast.AST) -> list[str]:
    """Capture names bound by a match-case pattern."""
    out = []
    for sub in ast.walk(pattern):
        if isinstance(sub, (ast.MatchAs, ast.MatchStar)) \
                and sub.name is not None:
            out.append(sub.name)
        elif isinstance(sub, ast.MatchMapping) \
                and sub.rest is not None:
            out.append(sub.rest)
    return out


def _binding_targets(node: ast.AST):
    """(target names, value) for the single-value binding forms —
    Assign, AnnAssign, walrus — or ([], None)."""
    if isinstance(node, ast.Assign):
        return [t.id for t in node.targets
                if isinstance(t, ast.Name)], node.value
    if isinstance(node, ast.AnnAssign) and node.value is not None \
            and isinstance(node.target, ast.Name):
        return [node.target.id], node.value
    if isinstance(node, ast.NamedExpr) \
            and isinstance(node.target, ast.Name):
        return [node.target.id], node.value
    return [], None


def _collect_def_names(tree: ast.AST) -> frozenset:
    """Every function-object name the cell could create — def names
    (anywhere but class bodies, whose methods are not module names),
    lambda bindings (assign / annotated assign / walrus), and plain
    ALIASES of any of those (`g = step`), to a fixpoint.  This is the
    conservative net for the argument-escape scan: a name in this set
    passed as a call argument is a function the callee may invoke."""
    names: set[str] = set()

    def scan(node: ast.AST, aliases: bool) -> bool:
        changed = False
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                continue
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                if child.name not in names:
                    names.add(child.name)
                    changed = True
            else:
                tgts, value = _binding_targets(child)
                if tgts and (isinstance(value, ast.Lambda)
                             or (aliases and isinstance(value,
                                                        ast.Name)
                                 and value.id in names)):
                    for t in tgts:
                        if t not in names:
                            names.add(t)
                            changed = True
            if scan(child, aliases):
                changed = True
        return changed

    scan(tree, aliases=False)
    while scan(tree, aliases=True):
        pass
    return frozenset(names)


class _Walker:
    """One ordered pass over the module: name footprint, collective
    footprint, host-sync flags, opacity — all in source order."""

    def __init__(self, assume_unsafe: frozenset = frozenset()):
        self.reads: set[str] = set()
        self.writes: set[str] = set()
        self.mutates: set[str] = set()
        self.deletes: set[str] = set()
        self.bound: set[str] = set()      # bound so far at module scope
        self.sites: list[CollectiveSite] = []
        self.taints: list[str] = []
        self.opaque_reasons: list[str] = []
        self.host_sync = False
        self.host_sync_in_loop = False
        # Defs (and lambda-assigns) whose statement has EXECUTED in
        # the source-order walk: only these are resolvable — a call
        # before its `def` invokes whatever the name is bound to at
        # that point, not the later body.
        self.defs: dict[str, ast.AST] = {}
        # Every def name appearing ANYWHERE in the cell (conditional
        # branches, later lines, nested) — the conservative net for
        # the argument-escape scan.
        self._def_names: frozenset = frozenset()
        # Def names whose escape-check is in progress (bounds the
        # recursion of mutually-passing defs).
        self._escape_stack: set[str] = set()
        # False inside class bodies and resolved function bodies:
        # defs there do not bind resolvable module names.
        self._module_scope = True
        # Ambient names an EARLIER cell in this session rebound/
        # mutated/deleted: the per-cell assumption that `np`/`time`/
        # builtins denote their modules no longer holds for them.
        self._assume_unsafe = frozenset(assume_unsafe)
        # Names currently assumed to denote collective-free modules;
        # a safe import adds, any other rebind removes.
        self._safe_names: set[str] = (set(SAFE_CALL_ROOTS)
                                      - self._assume_unsafe)
        # from-imports of a safe module's attribute (`from math import
        # sqrt`): safe as bare Name calls.
        self._safe_callables: set[str] = set()
        # Def names later rebound to something else: calling them is
        # no longer provably the same-cell def.
        self._rebound_defs: set[str] = set()
        # Ambient names this cell re-bound to their REAL modules —
        # a rebind that restores the assumption instead of breaking it.
        self._rearmed: set[str] = set()
        # One-level def resolution depth (recursion guard: a def that
        # calls itself — or another def — must taint, not recurse).
        self._depth = 0

    # -- small helpers --------------------------------------------------

    def _read(self, name: str) -> None:
        if name not in self.bound:
            self.reads.add(name)

    def _bind(self, name: str) -> None:
        if name in self.defs and name in self.bound:
            self._rebound_defs.add(name)
        self._safe_names.discard(name)
        self._safe_callables.discard(name)
        self._rearmed.discard(name)
        self.writes.add(name)
        self.bound.add(name)

    def _taint(self, why: str) -> None:
        # Deduped: a nested call's argument subtree is re-walked by
        # the enclosing call's escape scan.
        if why not in self.taints and len(self.taints) < _MAX_TAINTS:
            self.taints.append(why)

    def _register_fn_binding(self, node: ast.AST, *, loop: int,
                             cond: int) -> None:
        """`g = lambda x: …` (assign / annotated / walrus) and plain
        ALIASES of a resolvable function (`g = step`) are same-cell
        function definitions: resolvable at later calls and
        escape-checkable as arguments, under the same scope/order
        rules as a def."""
        if not (self._module_scope and loop == 0 and cond == 0):
            return
        tgts, value = _binding_targets(node)
        if isinstance(value, ast.Lambda):
            fn: ast.AST | None = value
        elif isinstance(value, ast.Name) and value.id in self.defs \
                and value.id not in self._rebound_defs:
            fn = self.defs[value.id]
        else:
            fn = None
        if fn is None:
            return
        for t in tgts:
            self.defs[t] = fn
            self._rebound_defs.discard(t)

    def _opaque(self, why: str) -> None:
        if why not in self.opaque_reasons:
            self.opaque_reasons.append(why)

    def _collective_op(self, fn: ast.AST) -> str | None:
        if isinstance(fn, ast.Name) and fn.id in COLLECTIVE_NAMES:
            return fn.id
        if isinstance(fn, ast.Attribute) and fn.attr in COLLECTIVE_NAMES:
            return fn.attr
        return None

    # -- module entry ---------------------------------------------------

    def run(self, tree: ast.Module) -> None:
        self._def_names = _collect_def_names(tree)
        self._scan_opacity(tree)
        self._block(tree.body, loop=0, cond=0)

    def _scan_opacity(self, tree: ast.Module) -> None:
        """Whole-tree sweep for dynamic namespace escapes — anywhere
        in the cell, including def bodies (a def is one call away)."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in ("exec", "eval"):
                self._opaque(f"{node.func.id}() at L{node.lineno} — "
                             "dynamic code can touch any name")
            elif isinstance(node, ast.ImportFrom) \
                    and any(a.name == "*" for a in node.names):
                self._opaque(f"star-import at L{node.lineno} binds an "
                             "unknowable set of names")
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name) \
                    and node.value.func.id in _DYNAMIC_NS:
                self._opaque(
                    f"{node.value.func.id}()[...] write at "
                    f"L{node.lineno} escapes the static footprint")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _NS_WRITE_METHODS \
                    and isinstance(node.func.value, ast.Call) \
                    and isinstance(node.func.value.func, ast.Name) \
                    and node.func.value.func.id in _DYNAMIC_NS:
                self._opaque(
                    f"{node.func.value.func.id}()."
                    f"{node.func.attr}(...) at L{node.lineno} escapes "
                    "the static footprint")

    # -- statements (source order) --------------------------------------

    def _block(self, stmts, *, loop: int, cond: int) -> None:
        for st in stmts:
            self._stmt(st, loop=loop, cond=cond)

    def _stmt(self, st: ast.stmt, *, loop: int, cond: int) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in (list(st.args.defaults)
                      + [d for d in st.args.kw_defaults
                         if d is not None]):
                self._expr(d, loop=loop, cond=cond)
            self._bind(st.name)
            # Resolvable only from here on, and only when the def
            # statement EXECUTES unconditionally at module scope — a
            # def inside an if/for arm leaves the name's binding
            # statically ambiguous, so calls to it must not resolve
            # this body.
            if self._module_scope and loop == 0 and cond == 0:
                self.defs[st.name] = st
                self._rebound_defs.discard(st.name)
            self._def_name_footprint(st)
            # Decorator application CALLS the decorator with the
            # just-created function at definition time.
            for dec in st.decorator_list:
                self._decorator(dec, st, loop=loop, cond=cond)
            return
        if isinstance(st, ast.ClassDef):
            for dec in st.decorator_list:
                self._class_decorator(dec, st, loop=loop, cond=cond)
            for b in st.bases:
                self._expr(b, loop=loop, cond=cond)
            # The class body EXECUTES at definition time (its calls are
            # reachable) but binds class attributes, not module names:
            # route the walk through a bind-sink.
            saved_bind, self._bind = self._bind, lambda name: None
            saved_scope, self._module_scope = self._module_scope, False
            try:
                self._block(st.body, loop=loop, cond=cond)
            finally:
                self._bind = saved_bind
                self._module_scope = saved_scope
            self._bind(st.name)
            return
        if isinstance(st, ast.If):
            self._expr(st.test, loop=loop, cond=cond)
            self._block(st.body, loop=loop, cond=cond + 1)
            self._block(st.orelse, loop=loop, cond=cond + 1)
            return
        if isinstance(st, ast.While):
            self._expr(st.test, loop=loop, cond=cond)
            self._block(st.body, loop=loop + 1, cond=cond + 1)
            self._block(st.orelse, loop=loop, cond=cond + 1)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter, loop=loop, cond=cond)
            self._target(st.target)
            self._block(st.body, loop=loop + 1, cond=cond)
            self._block(st.orelse, loop=loop, cond=cond)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr, loop=loop, cond=cond)
                if item.optional_vars is not None:
                    self._target(item.optional_vars)
            self._block(st.body, loop=loop, cond=cond)
            return
        if isinstance(st, ast.Try):
            self._block(st.body, loop=loop, cond=cond)
            for h in st.handlers:
                if h.type is not None:
                    self._expr(h.type, loop=loop, cond=cond)
                if h.name:
                    self._bind(h.name)
                self._block(h.body, loop=loop, cond=cond + 1)
            self._block(st.orelse, loop=loop, cond=cond)
            self._block(st.finalbody, loop=loop, cond=cond)
            return
        if isinstance(st, ast.Match):
            self._expr(st.subject, loop=loop, cond=cond)
            for case in st.cases:
                for name in _pattern_names(case.pattern):
                    self._bind(name)
                if case.guard is not None:
                    self._expr(case.guard, loop=loop, cond=cond)
                self._block(case.body, loop=loop, cond=cond + 1)
            return
        if isinstance(st, ast.Assign):
            self._expr(st.value, loop=loop, cond=cond)
            for tgt in st.targets:
                self._target(tgt)
            self._register_fn_binding(st, loop=loop, cond=cond)
            return
        if isinstance(st, ast.AugAssign):
            self._expr(st.value, loop=loop, cond=cond)
            if isinstance(st.target, ast.Name):
                self._read(st.target.id)   # read-modify-write
                self._bind(st.target.id)
            else:
                self._target(st.target)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._expr(st.value, loop=loop, cond=cond)
                self._target(st.target)
                self._register_fn_binding(st, loop=loop, cond=cond)
            return
        if isinstance(st, ast.Delete):
            for tgt in st.targets:
                if isinstance(tgt, ast.Name):
                    self.deletes.add(tgt.id)
                    # A deleted name is free again for later reads.
                    self.bound.discard(tgt.id)
                elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    base = _base_name(tgt)
                    if base is not None:
                        self._read(base)
                        self.mutates.add(base)
                    self._expr(tgt, loop=loop, cond=cond)
            return
        if isinstance(st, ast.Import):
            for alias in st.names:
                bound = alias.asname or alias.name.split(".")[0]
                self._bind(bound)
                # `import numpy as np` re-arms np as a safe root;
                # `import jax as np` disarms it (handled by _bind).
                if alias.name in SAFE_MODULES or (
                        alias.asname is None
                        and alias.name.split(".")[0] in SAFE_MODULES):
                    self._safe_names.add(bound)
                    self._rearmed.add(bound)
            return
        if isinstance(st, ast.ImportFrom):
            for alias in st.names:
                if alias.name == "*":
                    continue      # opacity pass already flagged it
                bound = alias.asname or alias.name
                self._bind(bound)
                mod = st.module or ""
                if mod in SAFE_MODULES:
                    # `from math import sqrt`: sqrt() is as inert as
                    # math.sqrt().  `from jax import numpy as jnp`:
                    # the ATTR itself is a safe module.
                    if f"{mod}.{alias.name}" in SAFE_MODULES:
                        self._safe_names.add(bound)
                    else:
                        self._safe_callables.add(bound)
                    self._rearmed.add(bound)
                elif f"{mod}.{alias.name}" in SAFE_MODULES:
                    self._safe_names.add(bound)
                    self._rearmed.add(bound)
            return
        if isinstance(st, ast.Global):
            # Module-level `global` is a no-op; the def walker handles
            # the in-function case.
            return
        if isinstance(st, (ast.Return, ast.Raise)):
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._expr(child, loop=loop, cond=cond)
            return
        if isinstance(st, ast.Expr):
            self._expr(st.value, loop=loop, cond=cond)
            return
        # Pass/Break/Continue/Nonlocal/etc.: walk any expressions.
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child, loop=loop, cond=cond)

    def _target(self, tgt: ast.AST) -> None:
        """An assignment/for/with target: Names bind the module
        namespace; attribute/subscript targets mutate the base
        object (and read its name)."""
        if isinstance(tgt, ast.Name):
            self._bind(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._target(el)
        elif isinstance(tgt, ast.Starred):
            self._target(tgt.value)
        elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
            base = _base_name(tgt)
            if base is not None:
                self._read(base)
                self.mutates.add(base)
            # Subscript index / attribute chain still reads names.
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Load):
                    self._read(sub.id)

    # -- expressions ----------------------------------------------------

    def _expr(self, expr: ast.expr, *, loop: int, cond: int,
              via: str | None = None, depth: int = 0) -> None:
        """In-order expression walk: reads, walrus binds, nested defs
        (lambda/comprehension scopes), and call classification."""
        if expr is None:
            return
        if isinstance(expr, ast.Name):
            if isinstance(expr.ctx, ast.Load):
                self._read(expr.id)
            return
        if isinstance(expr, ast.NamedExpr):
            self._expr(expr.value, loop=loop, cond=cond, via=via,
                       depth=depth)
            if isinstance(expr.target, ast.Name):
                self._bind(expr.target.id)
                self._register_fn_binding(expr, loop=loop, cond=cond)
            return
        if isinstance(expr, ast.Lambda):
            # Body runs at call time; free names still count as reads
            # (conservative), but its calls are classified only when
            # the lambda is called — which the classifier taints.
            self._lambda_reads(expr)
            return
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            # Comprehensions are their own scope (py3): iteration
            # targets are not module binds; a host-sync inside one IS
            # a loop-shaped host sync.
            self._comp(expr, loop=loop, cond=cond, via=via,
                       depth=depth)
            return
        if isinstance(expr, ast.Call):
            self._call(expr, loop=loop, cond=cond, via=via,
                       depth=depth)
            return
        if isinstance(expr, ast.IfExp):
            self._expr(expr.test, loop=loop, cond=cond, via=via,
                       depth=depth)
            self._expr(expr.body, loop=loop, cond=cond + 1, via=via,
                       depth=depth)
            self._expr(expr.orelse, loop=loop, cond=cond + 1, via=via,
                       depth=depth)
            return
        if isinstance(expr, ast.Await):
            self._expr(expr.value, loop=loop, cond=cond, via=via,
                       depth=depth)
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._expr(child, loop=loop, cond=cond, via=via,
                           depth=depth)

    def _comp(self, comp, *, loop: int, cond: int, via, depth) -> None:
        local = set()
        for gen in comp.generators:
            for sub in ast.walk(gen.target):
                if isinstance(sub, ast.Name):
                    local.add(sub.id)
        saved = self.bound
        self.bound = saved | local
        try:
            for gen in comp.generators:
                self._expr(gen.iter, loop=loop, cond=cond, via=via,
                           depth=depth)
                for cnd in gen.ifs:
                    self._expr(cnd, loop=loop + 1, cond=cond + 1,
                               via=via, depth=depth)
            if isinstance(comp, ast.DictComp):
                self._expr(comp.key, loop=loop + 1, cond=cond,
                           via=via, depth=depth)
                self._expr(comp.value, loop=loop + 1, cond=cond,
                           via=via, depth=depth)
            else:
                self._expr(comp.elt, loop=loop + 1, cond=cond,
                           via=via, depth=depth)
        finally:
            self.bound = saved

    def _lambda_reads(self, lam: ast.Lambda) -> None:
        params = _param_names(lam.args)
        for sub in ast.walk(lam.body):
            if isinstance(sub, ast.Name) \
                    and isinstance(sub.ctx, ast.Load) \
                    and sub.id not in params:
                self._read(sub.id)

    # -- call classification --------------------------------------------

    def _call(self, call: ast.Call, *, loop: int, cond: int,
              via: str | None, depth: int) -> None:
        # Arguments first (they evaluate before the call).
        for a in call.args:
            self._expr(a, loop=loop, cond=cond, via=via, depth=depth)
        for kw in call.keywords:
            self._expr(kw.value, loop=loop, cond=cond, via=via,
                       depth=depth)
        # A function object among the arguments ESCAPES into the
        # callee, which may invoke it any number of times — its
        # collectives would run without a visible site here
        # (`list(map(step, data))`, `sorted(xs, key=fn)`).
        self._escape_args(call)
        fn = call.func
        op = self._collective_op(fn)
        if op is not None:
            # The shadow check: `all_reduce = my_fn` earlier makes the
            # name a user function, not the framework collective — but
            # the conservative direction is to still record the SITE
            # (a shadowed collective is at best unknown).
            self.sites.append(CollectiveSite(
                op=op, line=call.lineno, in_loop=loop > 0,
                conditional=cond > 0, via=via))
            if isinstance(fn, ast.Name):
                self._read(fn.id)
            else:
                self._expr(fn, loop=loop, cond=cond, via=via,
                           depth=depth)
            return
        if isinstance(fn, ast.Name):
            self._read(fn.id)
            name = fn.id
            # Only defs whose STATEMENT already executed in the walk
            # resolve (self.defs is populated in source order): in
            # `f = g; f(); def f(): …` the call invokes the earlier
            # binding, so it falls through to the generic rules below
            # instead of borrowing the later body's proof.
            if name in self.defs and name not in self._rebound_defs:
                if self._depth == 0:
                    self._resolve_def(name, loop=loop, cond=cond)
                else:
                    self._taint(
                        f"nested call to `{name}()` (L{call.lineno}) "
                        f"— same-cell defs resolve one level deep "
                        f"only")
                return
            if name in ("exec", "eval"):
                return      # opacity pass owns these
            if name in _DYNAMIC_NS:
                return      # reads are fine; writes flagged already
            if name == "print":
                if loop and any(not isinstance(a, ast.Constant)
                                for a in call.args):
                    self.host_sync = True
                    self.host_sync_in_loop = True
                return
            if name in self._safe_callables:
                return      # from-import of a safe module's attr
            if name in _BUILTIN_NAMES and name not in self.writes \
                    and name not in self._assume_unsafe:
                return      # builtins cannot reach the mesh
            self._taint(f"calls unvetted function `{name}()` "
                        f"(L{call.lineno})")
            return
        if isinstance(fn, ast.Attribute):
            base = _base_name(fn)
            sync = (fn.attr in HOST_SYNC_ATTRS
                    or fn.attr == "device_get")
            if sync:
                self.host_sync = True
                if loop:
                    self.host_sync_in_loop = True
            self._expr(fn.value, loop=loop, cond=cond, via=via,
                       depth=depth)
            if fn.attr in _MUTATOR_METHODS:
                # In-place container mutation: a write to the base
                # name's object — and inert for the collective verdict
                # (a custom `.append` that runs a collective is
                # pathological; `history.append(loss)` cells must stay
                # provable).
                if base is not None:
                    self.mutates.add(base)
                    self._read(base)
                return
            if base is not None and base in self._safe_names:
                return      # provably inert module root
            if sync:
                # .item()/.tolist()/device_get on a possibly-sharded
                # array gathers across hosts — not provably free.
                self._taint(
                    f"host-sync `.{fn.attr}()` (L{call.lineno}) may "
                    f"gather a cross-host array")
                return
            self._taint(f"calls into `.{fn.attr}()` (L{call.lineno}) "
                        f"— could reach a collective")
            return
        # Dynamic callee: subscripted table, lambda result, call chain.
        self._expr(fn, loop=loop, cond=cond, via=via, depth=depth)
        self._taint(f"dynamic callee at L{call.lineno} — cannot prove "
                    f"it collective-free")

    def _resolve_def(self, name: str, *, loop: int, cond: int) -> None:
        """One level deep through a same-cell def (or lambda-assign):
        its body's calls are classified AT THE CALL SITE's position in
        the top-level order (the collectives it runs happen when it is
        called).  Nested user-function calls inside the body taint
        instead of recursing (``self._depth``), so a recursive def
        terminates with an honest ``unknown``."""
        fndef = self.defs[name]
        saved = self.bound
        self.bound = saved | _param_names(fndef.args)
        self._depth += 1
        saved_scope, self._module_scope = self._module_scope, False
        first_new = len(self.sites)
        try:
            if isinstance(fndef, ast.Lambda):
                self._expr(fndef.body, loop=loop, cond=cond)
            else:
                self._block(fndef.body, loop=loop, cond=cond)
        finally:
            self._depth -= 1
            self._module_scope = saved_scope
            self.bound = saved
        # Tag the sites this resolution added with the via name.
        for site in self.sites[first_new:]:
            if site.via is None:
                site.via = name

    # -- function-object escapes (args, decorators) ---------------------

    def _escape_args(self, call: ast.Call) -> None:
        """Taint any function object escaping through this call's
        arguments unless its body is PROVABLY collective-free — never
        a false 'free' for `list(map(step, data))` or a decorator
        factory's operands."""
        roots = list(call.args) + [kw.value for kw in call.keywords]
        for root in roots:
            for sub in ast.walk(root):
                if isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Load):
                    nm = sub.id
                    if nm in self.defs \
                            and nm not in self._rebound_defs:
                        if not self._fn_free(nm):
                            self._taint(
                                f"same-cell function `{nm}` passed to "
                                f"a call (L{call.lineno}) — its body "
                                f"is not provably collective-free")
                    elif nm in self._def_names:
                        # Conditionally-defined, later-defined, or
                        # rebound function name: the body the callee
                        # would invoke is not resolvable here.
                        self._taint(
                            f"function `{nm}` passed to a call "
                            f"(L{call.lineno}) — its binding is not "
                            f"resolvable at this point")
                elif isinstance(sub, ast.Lambda):
                    if not self._shadow_free(
                            _param_names(sub.args),
                            lambda w, s=sub: w._expr(s.body, loop=0,
                                                     cond=0)):
                        self._taint(
                            f"lambda passed to a call "
                            f"(L{call.lineno}) — not provably "
                            f"collective-free")

    def _fn_free(self, name: str, node: ast.AST | None = None) -> bool:
        """True only when the named same-cell def/lambda's body is
        provably collective-free, so escaping it is harmless no matter
        how often the callee invokes it.  Re-entrant escapes
        (mutually-passing defs) come back False, bounding recursion;
        a name with no resolvable body (and no explicit ``node``) is
        never provably free."""
        if name in self._escape_stack:
            return False
        if node is None:
            node = self.defs.get(name)
        if node is None:
            return False
        self._escape_stack.add(name)
        try:
            if isinstance(node, ast.Lambda):
                return self._shadow_free(
                    _param_names(node.args),
                    lambda w: w._expr(node.body, loop=0, cond=0))
            return self._shadow_free(
                _param_names(node.args),
                lambda w: w._block(node.body, loop=0, cond=0))
        finally:
            self._escape_stack.discard(name)

    def _shadow_free(self, params: set, run) -> bool:
        """Classify a function body in a scratch walker and report
        whether it is provably collective-free (no sites, taints, or
        opacity).  Host-sync flags propagate to the real walker — the
        body runs whenever the callee invokes it; its name footprint
        was already recorded at definition time."""
        sub = _Walker(self._assume_unsafe)
        sub.defs = dict(self.defs)
        sub._def_names = self._def_names
        sub._rebound_defs = set(self._rebound_defs)
        sub._safe_names = set(self._safe_names)
        sub._safe_callables = set(self._safe_callables)
        sub._escape_stack = self._escape_stack
        # The builtin-inertness check consults writes: a rebound
        # builtin (`float = bad_fn`) must stay rebound inside the
        # shadow body, or the escape check re-proves on a dead
        # assumption.
        sub.writes = set(self.writes)
        sub.bound = set(self.bound) | set(params)
        sub._depth = self._depth + 1
        sub._module_scope = False
        try:
            run(sub)
        except RecursionError:
            return False
        self.host_sync = self.host_sync or sub.host_sync
        self.host_sync_in_loop = (self.host_sync_in_loop
                                  or sub.host_sync_in_loop)
        return not (sub.sites or sub.taints or sub.opaque_reasons)

    def _decorator(self, dec: ast.expr, fndef, *, loop: int,
                   cond: int) -> None:
        """``@dec`` above ``def f`` CALLS ``dec(f)`` when the def
        executes — a call the expression walk alone would miss, which
        is how ``@my_decorator`` escaped classification.  The rules
        mirror :meth:`_call`, with the decorated def as the escaping
        argument."""
        if isinstance(dec, ast.Name):
            self._read(dec.id)
            name = dec.id
            if name in self.defs and name not in self._rebound_defs:
                # Same-cell decorator: its body runs here…
                if self._depth == 0:
                    self._resolve_def(name, loop=loop, cond=cond)
                else:
                    self._taint(
                        f"nested decorator `@{name}` (L{dec.lineno}) "
                        f"— same-cell defs resolve one level deep "
                        f"only")
                # …the decorated def escapes into it, and the name is
                # rebound to whatever the decorator returned.
                if not self._fn_free(fndef.name, fndef):
                    self._taint(
                        f"def `{fndef.name}` passed to decorator "
                        f"`@{name}` (L{dec.lineno}) — its body is not "
                        f"provably collective-free")
                self._rebound_defs.add(fndef.name)
                return
            if name in _NON_INVOKING_DECORATORS \
                    and name not in self.writes \
                    and name not in self._assume_unsafe:
                return   # descriptor wrapper: never calls fndef
            if name in self._safe_callables or (
                    name in _BUILTIN_NAMES
                    and name not in self.writes
                    and name not in self._assume_unsafe):
                # Application itself is inert, but the product may
                # invoke the def — require a provably free body.
                if not self._fn_free(fndef.name, fndef):
                    self._taint(
                        f"def `{fndef.name}` passed to decorator "
                        f"`@{name}` (L{dec.lineno}) — its body is not "
                        f"provably collective-free")
                return
            self._taint(f"decorator `@{name}` (L{dec.lineno}) applies "
                        f"an unvetted function at definition time")
            return
        if isinstance(dec, ast.Attribute):
            base = _base_name(dec)
            self._expr(dec, loop=loop, cond=cond)
            if base is not None and base in self._safe_names:
                # e.g. @functools.cache: the safe-module contract says
                # its product only composes the wrapped body with
                # inert code — so the body itself must be provable.
                if not self._fn_free(fndef.name, fndef):
                    self._taint(
                        f"def `{fndef.name}` passed to decorator "
                        f"`@{base}.{dec.attr}` (L{dec.lineno}) — its "
                        f"body is not provably collective-free")
                return
            self._taint(f"decorator `@….{dec.attr}` (L{dec.lineno}) "
                        f"— could reach a collective at definition "
                        f"time")
            return
        if isinstance(dec, ast.Call):
            # Factory form: the inner call classifies normally (and
            # fndef is not among its args), but the factory's PRODUCT
            # is then invoked with fndef — a dynamic callee.
            self._expr(dec, loop=loop, cond=cond)
            self._taint(f"decorator factory at L{dec.lineno} — cannot "
                        f"prove its product collective-free")
            self._rebound_defs.add(fndef.name)
            return
        self._expr(dec, loop=loop, cond=cond)
        self._taint(f"dynamic decorator at L{dec.lineno} — cannot "
                    f"prove it collective-free")
        self._rebound_defs.add(fndef.name)

    def _safe_callee(self, fn: ast.AST) -> bool:
        """A callee expression that provably cannot reach the mesh on
        its own: a safe from-import / unshadowed builtin Name, or an
        attribute chain rooted in a safe module."""
        if isinstance(fn, ast.Name):
            return fn.id not in self.defs and (
                fn.id in self._safe_callables
                or (fn.id in _BUILTIN_NAMES
                    and fn.id not in self.writes
                    and fn.id not in self._assume_unsafe))
        if isinstance(fn, ast.Attribute):
            base = _base_name(fn)
            return base is not None and base in self._safe_names
        return False

    def _class_decorator(self, dec: ast.expr, cdef: ast.ClassDef, *,
                         loop: int, cond: int) -> None:
        """``@dec`` above ``class C`` CALLS ``dec(C)`` when the class
        statement executes.  Safe-module decorators (``@dataclass``,
        ``@functools.total_ordering``) introspect the class without
        invoking user code, so they stay provable; anything else could
        instantiate C or call its methods at definition time —
        unprovable, taint."""
        if isinstance(dec, ast.Name):
            self._read(dec.id)
        if self._safe_callee(dec):
            return
        if isinstance(dec, ast.Call):
            # Factory form (`@dataclass(frozen=True)`): the inner call
            # classifies normally; a safe factory's product keeps the
            # introspect-only contract.
            before = len(self.taints)
            safe = self._safe_callee(dec.func)
            self._expr(dec, loop=loop, cond=cond)
            if safe and len(self.taints) == before:
                return
            self._taint(f"class decorator factory at L{dec.lineno} — "
                        f"cannot prove its product collective-free")
            return
        if not isinstance(dec, ast.Name):
            self._expr(dec, loop=loop, cond=cond)
        self._taint(f"class decorator at L{dec.lineno} on "
                    f"`{cdef.name}` — could run the class's code at "
                    f"definition time")

    # -- def name footprint ---------------------------------------------

    def _def_name_footprint(self, fndef) -> None:
        """A def's body runs at call time: free names it loads count
        as reads (conservative), and names it declares ``global`` and
        assigns escape into the module footprint as writes."""
        local: set[str] = set(_param_names(fndef.args))
        glb: set[str] = set()
        for node in ast.walk(fndef):
            if isinstance(node, ast.Global):
                glb.update(node.names)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)):
                local.add(node.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)):
                if node is not fndef and getattr(node, "name", None):
                    local.add(node.name)
        for g in glb & local:
            self.writes.add(g)
        local -= glb
        for node in ast.walk(fndef):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id not in local \
                    and node.id not in self.bound:
                self.reads.add(node.id)


# ----------------------------------------------------------------------


def ambient_poison(report: EffectReport) -> frozenset:
    """The ambient names this cell invalidates for LATER cells in the
    same session: safe roots / builtins it rebinds, mutates, or
    deletes — feed the union of these into the next cell's
    ``assume_unsafe``.  A rebind that re-imports the real module
    (``import numpy as np``) restores the assumption instead of
    breaking it.  An opaque cell could have rebound anything, so it
    poisons every ambient assumption at once."""
    ambient = SAFE_CALL_ROOTS | _BUILTIN_NAMES
    if not report.parsed or report.opaque:
        return frozenset(ambient)
    return frozenset((report.touched & ambient) - report.safe_rearms)


def infer_effects(code: str, *,
                  assume_unsafe: frozenset = frozenset()
                  ) -> EffectReport:
    """Infer one cell's :class:`EffectReport`.  Never raises:
    unreadable source comes back ``parsed=False`` AND ``opaque=True``
    — the conservative verdict that serializes it under effects
    admission and poisons the dependency DAG.

    ``assume_unsafe``: ambient names (safe module roots, builtins) an
    earlier cell in the session rebound — accumulated via
    :func:`ambient_poison` — whose per-cell safety assumption must
    not be trusted here.  A cell can re-arm a root by importing the
    real module itself (``import numpy as np``)."""
    if non_python_cell_magic(code) is not None:
        # %%bash / %%writefile / …: the payload is data for the magic,
        # not Python — no namespace footprint and no mesh collectives,
        # but REAL host side effects (filesystem, subprocesses, pip),
        # so the cell must never read as pure/reorderable.  host_sync
        # is the honest flag: the magic synchronously runs host work.
        return EffectReport(
            parsed=True, opaque=False,
            collective_verdict=VERDICT_NONE,
            host_sync=True)
    try:
        cleaned = strip_ipython(code)
        tree = ast.parse(cleaned)
    except (SyntaxError, ValueError, RecursionError):
        return EffectReport(
            parsed=False, opaque=True,
            opaque_reasons=("unparseable source",),
            collective_verdict=VERDICT_UNKNOWN)
    w = _Walker(assume_unsafe)
    try:
        w.run(tree)
    except RecursionError:
        return EffectReport(
            parsed=False, opaque=True,
            opaque_reasons=("analysis recursion limit",),
            collective_verdict=VERDICT_UNKNOWN)
    opaque = bool(w.opaque_reasons)
    if opaque or w.taints:
        verdict = VERDICT_UNKNOWN
    elif w.sites:
        verdict = VERDICT_EXACT
    else:
        verdict = VERDICT_NONE
    return EffectReport(
        parsed=True,
        opaque=opaque,
        opaque_reasons=tuple(w.opaque_reasons),
        reads=frozenset(w.reads),
        writes=frozenset(w.writes),
        mutates=frozenset(w.mutates),
        deletes=frozenset(w.deletes),
        collectives=tuple(w.sites),
        collective_verdict=verdict,
        taints=tuple(w.taints),
        host_sync=w.host_sync,
        host_sync_in_loop=w.host_sync_in_loop,
        safe_rearms=frozenset(w._rearmed))
