"""Lifecycle self-analysis: resource-leak, bracket-discipline, and
shutdown-completeness passes over the framework's own source (the
ISSUE 15 tentpole — self-lint passes 8–10).

The review history after the gateway arc shows the dominant bug class
is no longer data races (PR 10's lockset passes own those) but
*lifecycle* bugs: fds and reader threads leaked on failed hellos,
unreaped children on signal paths, and paired counters released on
only some exception edges.  This module mechanizes that class with
the same interprocedural machinery as :mod:`concur` — one-level call
resolution, constructor-typed attributes, the ``*_locked``-style
conventions, per-site exemption tables — aimed at acquire/release
pairs instead of locksets:

1. **resource-leak** (:func:`check_resource_leaks`): a declared
   acquire vocabulary (``socket.socket`` / ``create_connection`` /
   ``socketpair``, write-mode ``open``, non-daemon
   ``threading.Thread``, ``subprocess.Popen``,
   ``tempfile.TemporaryDirectory``, ``mmap.mmap``,
   ``ThreadingHTTPServer``) bound to a FUNCTION-LOCAL name must reach
   its release (``close``/``join``/``wait``/``cleanup``/
   ``shutdown``…) on **all** paths including exception edges.  A
   ``with`` block or a release inside a ``finally`` satisfies it;
   ownership transfer is modeled — assigned to ``self.X`` (or a
   ``self`` container) the resource moves to the class ledger
   (pass 3's domain), ``return``/``yield`` hands it to the caller,
   and passing it as an argument to any call consumes it (the
   registering-call pattern: ``self._io[r] = _ChildIO(proc, r)``).
   A release reached only on the fall-through path (no ``finally``,
   not adjacent to the acquire) is still a finding: the raise edge
   leaks.

2. **bracket-discipline** (:func:`check_brackets`): paired
   mutate/unmutate operations declared in :data:`BRACKETS` (the
   gateway serve counter / ``_serve_done``, the async-window
   in-flight list, the mailbox ``claim_all``/``park`` exactly-once
   pair, metrics gauge ``inc``/``dec``) must be exception-safe — the
   release must postdominate the acquire via ``finally``, be
   reachable on every raise edge (a broad ``except`` that reparks),
   or the acquire must hand off *immediately* (next statement,
   climbing out of ``with``/``if``) to a function that releases in
   ITS ``finally`` (``Thread(target=self._serve_execute)`` where
   ``_serve_execute``'s whole body is try/finally → ``_serve_done``).
   Anything else can strand a slot when the serve thread throws.

3. **shutdown-completeness** (:func:`check_shutdown_completeness`):
   a class-level ledger — every resource a class acquires in
   ``__init__``/``start`` (one level deep: helpers they call count)
   must be released in its ``close``/``stop``/``shutdown``/
   ``__exit__`` (one level deep again); every non-daemon ``Thread``
   joined by its owner; every ``Popen`` waited; listener sockets
   closed; attributes typed as *other resource-owning product
   classes* (``self._ch = WorkerChannel(...)``) released through
   their own close/stop.  Daemon threads whose target touches a
   ``threading`` lock are flagged as interpreter-teardown hazards
   unless their owner joins them on close (daemon threads die
   mid-critical-section at interpreter exit; a lock held then
   deadlocks other atexit work).

Deliberate leaks live in the module-local ``_LINT_LIFECYCLE_OK``
exemption table — ``{"Class.method:resource": "why"}`` for passes
1–2 (``resource`` is the vocabulary kind or the bracket name) and
``{"Class:attr": "why"}`` for pass 3 — mirroring
``_LINT_BLOCKING_OK``.  Stdlib-only (ast), shares the finding shape
with :mod:`selfcheck`, and is wired into ``run_self_lint`` /
``nbd-lint --self`` / the CI ``static-analysis`` job; the per-class
ledger is exportable (``nbd-lint --shutdown-ledger``) as a CI
artifact.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from .concur import _FnWalker, _dotted, _str_table
from .selfcheck import SelfFinding, _iter_product_files, _parse, _rel

# ----------------------------------------------------------------------
# vocabulary

# Dotted (and bare, for `from x import Y` style) constructor paths →
# resource kind.
_ACQUIRE_CTORS = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "socket.socketpair": "socket",
    "subprocess.Popen": "process",
    "Popen": "process",
    "threading.Thread": "thread",
    "Thread": "thread",
    "mmap.mmap": "mmap",
    "tempfile.TemporaryDirectory": "tempdir",
    "TemporaryDirectory": "tempdir",
    "ThreadingHTTPServer": "server",
    "HTTPServer": "server",
}

# Per-kind release method names (called ON the resource).
_RELEASES = {
    "socket": frozenset({"close", "detach"}),
    "process": frozenset({"wait", "communicate"}),
    "thread": frozenset({"join"}),
    "mmap": frozenset({"close"}),
    "tempdir": frozenset({"cleanup"}),
    "server": frozenset({"server_close"}),
    "file": frozenset({"close"}),
}

# Release methods accepted for attributes typed as resource-owning
# product classes (tier B of the class ledger).
_OWNER_RELEASES = frozenset({"close", "stop", "shutdown",
                             "shutdown_all", "stop_all", "detach"})

# Methods that count as a class's shutdown surface.
_CLOSE_METHODS = ("close", "stop", "shutdown", "__exit__", "__del__",
                  "cleanup", "stop_all", "shutdown_all")

# Declared bracket pairs (pass 2).  ``acquire``/``release`` are
# matcher specs; see _match_bracket_*.  Declaring a bracket that the
# current tree never performs is fine — it simply never fires.
BRACKETS = (
    # The gateway serve counter: incremented on the listener thread
    # (`self._serving[name] = self._serving.get(name, 0) + 1`),
    # released by `_serve_done` in the serve thread's finally.
    {"name": "serve-slot",
     "acquire": {"kind": "subscript-incr", "attr": "_serving"},
     "release": {"kind": "call", "name": "_serve_done"}},
    # The async executor's in-flight window entry/exit.
    {"name": "async-window",
     "acquire": {"kind": "attr-method", "attr": "_inflight",
                 "name": "append"},
     "release": {"kind": "attr-method", "attr": "_inflight",
                 "name": "remove"}},
    # The mailbox exactly-once pair: a destructive claim must be
    # reparked on every raise edge or the results are lost on both
    # sides.
    {"name": "mailbox-claim",
     "acquire": {"kind": "call", "name": "claim_all"},
     "release": {"kind": "call", "name": "park"}},
    # Metrics gauge up/down pairs (occupancy-style gauges): an `inc`
    # with a matching `dec` in the same function's module must not
    # strand the gauge high on a raise edge.
    {"name": "gauge-updown",
     "acquire": {"kind": "attr-method", "attr": None, "name": "inc_gauge"},
     "release": {"kind": "attr-method", "attr": None, "name": "dec_gauge"}},
)


def _exempt(table: dict, key: str) -> bool:
    return key in table


# ----------------------------------------------------------------------
# shared AST plumbing


def _ctor_kind(call: ast.AST) -> str | None:
    """Resource kind of an acquire-vocabulary constructor call, or
    None.  Write-mode ``open`` is kind "file"."""
    if not isinstance(call, ast.Call):
        return None
    dotted = _dotted(call.func)
    if dotted in _ACQUIRE_CTORS:
        return _ACQUIRE_CTORS[dotted]
    if dotted is not None and "." in dotted:
        # `http.server.ThreadingHTTPServer` etc.: match the last
        # attribute too so alias imports don't hide a server.
        tail = dotted.rsplit(".", 1)[1]
        if tail in ("ThreadingHTTPServer",):
            return "server"
    if isinstance(call.func, ast.Name) and call.func.id == "open" \
            and _FnWalker._open_writes(call):
        return "file"
    return None


def _thread_is_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _self_attr_of(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _Blocks:
    """Statement-position index for one function: parent links, the
    (block-list, index) of every statement, and finally/handler
    membership — the postdomination approximations both passes
    share."""

    def __init__(self, fn: ast.AST):
        self.parent: dict = {}
        self.stmt_pos: dict = {}       # stmt -> (block list, index)
        self.in_finally: set = set()   # stmts under any finalbody
        for node in ast.walk(fn):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        for node in ast.walk(fn):
            for name in ("body", "orelse", "finalbody"):
                block = getattr(node, name, None)
                if isinstance(block, list):
                    for i, stmt in enumerate(block):
                        if isinstance(stmt, ast.stmt):
                            self.stmt_pos[stmt] = (block, i)
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        self.in_finally.add(sub)

    def stmt_of(self, node: ast.AST) -> ast.stmt | None:
        while node is not None and node not in self.stmt_pos:
            node = self.parent.get(node)
        return node

    def next_stmt(self, stmt: ast.stmt) -> ast.stmt | None:
        """The statement that executes immediately after ``stmt`` on
        the fall-through path, climbing out of with/if bodies when
        ``stmt`` closes them (a `with lock:` whose last statement is
        the acquire falls through to the with's sibling).  Stops at
        try/loop bodies — an exception or another iteration breaks
        the adjacency."""
        while stmt is not None:
            block, i = self.stmt_pos.get(stmt, (None, None))
            if block is None:
                return None
            if i + 1 < len(block):
                return block[i + 1]
            parent = self.parent.get(stmt)
            # climb only through containers whose fall-through leads
            # to their own next sibling
            if isinstance(parent, (ast.With, ast.If)):
                stmt = parent
                continue
            return None
        return None

def _tries_covering(fn: ast.AST, node: ast.AST) -> list:
    """Try statements whose try-BODY contains ``node`` (so the
    finalbody / handlers run if anything after it raises)."""
    out = []
    for t in ast.walk(fn):
        if not isinstance(t, ast.Try):
            continue
        for stmt in t.body:
            found = any(sub is node for sub in ast.walk(stmt))
            if found:
                out.append(t)
                break
    return out


# ----------------------------------------------------------------------
# pass 1: resource-leak (function-local)


@dataclass
class _Local:
    names: tuple          # bound local name(s)
    kind: str
    line: int
    stmt: ast.stmt        # the binding statement


def _acquires_in(fn) -> tuple[list[_Local], set]:
    """Function-local acquire bindings, plus the set of acquire Call
    nodes that are already satisfied/consumed at the acquire site
    (with-blocks, direct-argument use, self-assignment)."""
    satisfied: set = set()
    locals_: list[_Local] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                if _ctor_kind(item.context_expr):
                    satisfied.add(item.context_expr)
        elif isinstance(node, ast.Call):
            # an acquire constructed directly inside another call is
            # consumed by that call (registering-call pattern), and a
            # method chained on the constructor (`Thread(...).start()`)
            # keeps no reference to release — only daemon threads may
            # do that (handled below).
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                if _ctor_kind(arg):
                    satisfied.add(arg)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        kind = _ctor_kind(node.value)
        if kind is None:
            continue
        if kind == "thread" and _thread_is_daemon(node.value):
            # Daemon threads die with the process by design; their
            # hazards are pass 3's (teardown) domain.
            satisfied.add(node.value)
            continue
        tgt = node.targets[0]
        if isinstance(tgt, ast.Name):
            locals_.append(_Local((tgt.id,), kind, node.lineno, node))
        elif isinstance(tgt, ast.Tuple) and kind == "socket" \
                and all(isinstance(e, ast.Name) for e in tgt.elts):
            # `r, w = socket.socketpair()` — each end is its own
            # socket and needs its own release (closing one end must
            # not satisfy the check for the other).
            for e in tgt.elts:
                locals_.append(_Local((e.id,), kind, node.lineno,
                                      node))
        else:
            # self.X = acquire → the class ledger (pass 3) owns it.
            satisfied.add(node.value)
    return locals_, satisfied


def _disposes(fn, res: _Local, blocks: _Blocks) -> tuple[str, bool]:
    """How the function disposes of a local resource:
    ``("transferred"|"released"|"leaked", exception_safe)``."""
    names = set(res.names)
    release_names = _RELEASES[res.kind]
    released_nodes = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id in names:
                    return "transferred", True      # caller owns
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in names:
            # v assigned into self state (attr or container item)
            for t in node.targets:
                attr_t = t.value if isinstance(t, ast.Subscript) else t
                if _self_attr_of(attr_t) is not None:
                    return "transferred", True      # class ledger
        if isinstance(node, ast.Call):
            fn_attr = node.func if isinstance(node.func, ast.Attribute)\
                else None
            if fn_attr is not None \
                    and isinstance(fn_attr.value, ast.Name) \
                    and fn_attr.value.id in names:
                if fn_attr.attr in release_names:
                    released_nodes.append(node)
                continue
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in names:
                        return "transferred", True  # consumed by call
    if not released_nodes:
        return "leaked", False
    # Exception-safety of the release: a finally covers every edge;
    # so does being the very next statement after the acquire (no
    # raise window).
    for rel in released_nodes:
        if rel in blocks.in_finally:
            return "released", True
        rel_stmt = blocks.stmt_of(rel)
        if rel_stmt is not None \
                and blocks.next_stmt(res.stmt) is rel_stmt:
            return "released", True
    return "released", False


def check_resource_leaks(root: str) -> list[SelfFinding]:
    findings: list[SelfFinding] = []
    for path in _iter_product_files(root):
        tree = _parse(path)
        if tree is None:
            continue
        rel = _rel(root, path).replace(os.sep, "/")
        exempt = _str_table(tree, "_LINT_LIFECYCLE_OK")

        def scan(fn, qname):
            locals_, satisfied = _acquires_in(fn)
            blocks = _Blocks(fn)
            for res in locals_:
                if res.stmt.value in satisfied:
                    continue
                if _exempt(exempt, f"{qname}:{res.kind}"):
                    continue
                verdict, safe = _disposes(fn, res, blocks)
                if verdict == "leaked":
                    findings.append(SelfFinding(
                        rel, res.line, "resource-leak",
                        f"{qname}: {res.kind} "
                        f"{'/'.join(res.names)!r} is acquired here "
                        f"but never released, returned, stored on "
                        f"self, or passed on — use a with-block or "
                        f"try/finally, or exempt "
                        f"'{qname}:{res.kind}' in _LINT_LIFECYCLE_OK "
                        f"with a reason"))
                elif verdict == "released" and not safe:
                    findings.append(SelfFinding(
                        rel, res.line, "resource-leak",
                        f"{qname}: {res.kind} "
                        f"{'/'.join(res.names)!r} is released only "
                        f"on the fall-through path — an exception "
                        f"between acquire and release leaks it; "
                        f"move the release into a finally (or a "
                        f"with-block), or exempt "
                        f"'{qname}:{res.kind}' in _LINT_LIFECYCLE_OK"))

        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        scan(sub, f"{node.name}.{sub.name}")
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                scan(node, node.name)
    return sorted(findings, key=lambda f: (f.file, f.line))


# ----------------------------------------------------------------------
# pass 2: bracket-discipline


def _match_bracket_acquire(node: ast.AST, spec: dict) -> bool:
    kind = spec["kind"]
    if kind == "subscript-incr":
        if isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Subscript) \
                and _self_attr_of(node.target.value) == spec["attr"]:
            return True
        return (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and _self_attr_of(node.targets[0].value)
                == spec["attr"]
                and isinstance(node.value, ast.BinOp)
                and isinstance(node.value.op, ast.Add))
    if kind == "attr-method":
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == spec["name"]):
            return False
        if spec.get("attr") is not None:
            return _self_attr_of(node.func.value) == spec["attr"]
        if spec.get("recv_in") is not None:
            # Pair by receiver: `self.g.inc()` only brackets with a
            # `.dec()` on the SAME dotted receiver — a monotonic
            # counter's inc in a module that decs some other gauge
            # must not arm.
            return _dotted(node.func.value) in spec["recv_in"]
        return True
    if kind == "call":
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == spec["name"])
    return False


def _match_bracket_release(node: ast.AST, spec: dict) -> bool:
    return _match_bracket_acquire(node, spec)


def _fn_releases_in_finally(fn, spec: dict) -> bool:
    """True when every path through ``fn`` runs the release: its body
    (past a docstring) is one try whose finalbody contains the
    release op — the `_serve_execute` shape."""
    body = list(fn.body)
    if body and isinstance(body[0], ast.Expr) \
            and isinstance(body[0].value, ast.Constant):
        body = body[1:]
    if len(body) != 1 or not isinstance(body[0], ast.Try):
        return False
    for stmt in body[0].finalbody:
        for sub in ast.walk(stmt):
            if _match_bracket_release(sub, spec):
                return True
    return False


def _releasing_fns(tree: ast.Module, spec: dict) -> set:
    """Names (bare and Class.method) of functions in this module that
    release the bracket on every path."""
    out: set = set()
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and _fn_releases_in_finally(sub, spec):
                    out.add(sub.name)
                    out.add(f"{node.name}.{sub.name}")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _fn_releases_in_finally(node, spec):
                out.add(node.name)
    return out


def _stmt_hands_off(stmt: ast.stmt, releasing: set, spec: dict) -> bool:
    """Does this statement guarantee the release?  Either it performs
    the release op itself, or it hands off to a releasing function —
    a direct call, or ``Thread(target=<releasing>)`` (the spawned
    thread's whole body releases in its finally)."""
    for sub in ast.walk(stmt):
        if _match_bracket_release(sub, spec):
            return True
        if not isinstance(sub, ast.Call):
            continue
        dotted = _dotted(sub.func)
        if dotted is not None \
                and dotted.split(".")[-1] in releasing:
            return True
        if _ctor_kind(sub) == "thread":
            for kw in sub.keywords:
                if kw.arg != "target":
                    continue
                tgt = _dotted(kw.value)
                if tgt is not None \
                        and tgt.split(".")[-1] in releasing:
                    return True
    return False


def check_brackets(root: str) -> list[SelfFinding]:
    findings: list[SelfFinding] = []
    for path in _iter_product_files(root):
        tree = _parse(path)
        if tree is None:
            continue
        rel = _rel(root, path).replace(os.sep, "/")
        exempt = _str_table(tree, "_LINT_LIFECYCLE_OK")

        # gauge-updown arms only for receivers the module actually
        # calls .dec() on (counters are monotonic; only up/down
        # gauges pair, and only with themselves).
        dec_recvs = {r for r in (
            _dotted(n.func.value) for n in ast.walk(tree)
            if isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "dec") if r is not None}
        armed = []
        for br in BRACKETS:
            spec_a, spec_r = dict(br["acquire"]), dict(br["release"])
            if br["name"] == "gauge-updown":
                if not dec_recvs:
                    continue
                spec_a["name"], spec_r["name"] = "inc", "dec"
                spec_a["recv_in"] = spec_r["recv_in"] = dec_recvs
            armed.append((br["name"], spec_a, spec_r,
                          _releasing_fns(tree, spec_r)))

        def scan(fn, qname):
            blocks = _Blocks(fn)
            for name, spec_a, spec_r, releasing in armed:
                for node in ast.walk(fn):
                    if not _match_bracket_acquire(node, spec_a):
                        continue
                    if _exempt(exempt, f"{qname}:{name}"):
                        continue
                    if _bracket_safe(fn, node, blocks, spec_r,
                                     releasing):
                        continue
                    findings.append(SelfFinding(
                        rel, node.lineno, "bracket-discipline",
                        f"{qname}: bracket {name!r} is acquired "
                        f"here but its release does not postdominate "
                        f"— no enclosing try/finally (or broad "
                        f"except) releases it and the next statement "
                        f"is not a release/hand-off, so a raise "
                        f"strands the bracket; wrap in try/finally, "
                        f"release in an except that re-raises, or "
                        f"exempt '{qname}:{name}' in "
                        f"_LINT_LIFECYCLE_OK with a reason"))

        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        scan(sub, f"{node.name}.{sub.name}")
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                scan(node, node.name)
    return sorted(findings, key=lambda f: (f.file, f.line))


def _bracket_safe(fn, node: ast.AST, blocks: _Blocks, spec_r: dict,
                  releasing: set) -> bool:
    # (a) a try whose body contains the acquire releases in its
    # finalbody or in a broad except handler
    for t in _tries_covering(fn, node):
        for stmt in list(t.finalbody) + [
                s for h in t.handlers
                if h.type is None
                or (isinstance(h.type, ast.Name)
                    and h.type.id in ("Exception", "BaseException"))
                for s in h.body]:
            if _stmt_hands_off(stmt, releasing, spec_r):
                return True
    # (b) the statement immediately after the acquire (climbing out
    # of with/if) releases or hands off — zero raise window
    stmt = blocks.stmt_of(node)
    if stmt is not None:
        nxt = blocks.next_stmt(stmt)
        if nxt is not None and _stmt_hands_off(nxt, releasing, spec_r):
            return True
    # (c) the acquiring function itself releases on every path (the
    # whole body is try/finally → release): self-reported safe
    if _fn_releases_in_finally(fn, spec_r):
        return True
    return False


# ----------------------------------------------------------------------
# pass 3: shutdown-completeness (the class ledger)


@dataclass
class _ClassLedger:
    name: str
    relpath: str
    line: int
    # attr -> {"kind", "line", "daemon", "target", "via"}
    resources: dict = field(default_factory=dict)
    close_methods: list = field(default_factory=list)
    # attr -> set of method names called on self.attr inside the
    # shutdown surface
    released: dict = field(default_factory=dict)
    joined_threads: set = field(default_factory=set)


def _methods_of(cls: ast.ClassDef) -> dict:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _collect_ledger(cls: ast.ClassDef, relpath: str,
                    owner_classes: set, *,
                    resources_only: bool = False) -> _ClassLedger:
    """``resources_only`` skips the shutdown-surface release/alias
    scan — the cheap tier-A probe ``build_ledgers`` uses to decide
    which class NAMES count as resource owners."""
    led = _ClassLedger(cls.name, relpath, cls.lineno)
    methods = _methods_of(cls)

    def init_like(names):
        """The named methods plus self-helpers they call (one level)."""
        seen, out = set(), []
        for name in names:
            fn = methods.get(name)
            if fn is None or name in seen:
                continue
            seen.add(name)
            out.append(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in methods \
                        and node.func.attr not in seen:
                    seen.add(node.func.attr)
                    out.append(methods[node.func.attr])
        return out

    for fn in init_like(["__init__", "start", "open"]):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) \
                    or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            kind = _ctor_kind(node.value)
            if kind is not None:
                attrs = []
                if _self_attr_of(tgt) is not None:
                    attrs = [(_self_attr_of(tgt),)]
                elif isinstance(tgt, ast.Tuple) and kind == "socket":
                    attrs = [tuple(a for a in
                                   (_self_attr_of(e)
                                    for e in tgt.elts)
                                   if a is not None)]
                for group in attrs:
                    for attr in group:
                        target = None
                        if kind == "thread":
                            for kw in node.value.keywords:
                                if kw.arg == "target":
                                    target = _dotted(kw.value)
                        led.resources.setdefault(attr, {
                            "kind": kind, "line": node.lineno,
                            "daemon": (kind == "thread"
                                       and _thread_is_daemon(
                                           node.value)),
                            "target": target, "via": fn.name})
                continue
            # tier B: attr typed as a resource-owning product class
            attr = _self_attr_of(tgt)
            if attr is None or not isinstance(node.value, ast.Call):
                continue
            ctor = node.value.func
            cname = (ctor.id if isinstance(ctor, ast.Name)
                     else ctor.attr
                     if isinstance(ctor, ast.Attribute) else None)
            if cname in owner_classes and cname != cls.name:
                led.resources.setdefault(attr, {
                    "kind": f"owner:{cname}", "line": node.lineno,
                    "daemon": False, "target": None, "via": fn.name})

    led.close_methods = [n for n in _CLOSE_METHODS if n in methods]
    if resources_only:
        return led
    for fn in init_like(list(led.close_methods)):
        # Local aliases of self attributes inside the shutdown
        # surface: `ch, self._ch = self._ch, None` + `ch.close()`,
        # `d = self._driver` + `d.join()`, and the close-loop
        # `for s in (self._a, self._b): s.close()` all release the
        # underlying attribute.
        aliases: dict[str, set] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
                pairs = []
                if isinstance(tgt, ast.Tuple) \
                        and isinstance(val, ast.Tuple) \
                        and len(tgt.elts) == len(val.elts):
                    pairs = list(zip(tgt.elts, val.elts))
                else:
                    pairs = [(tgt, val)]
                for t, v in pairs:
                    if isinstance(t, ast.Name):
                        a = _self_attr_of(v)
                        if a is not None:
                            aliases.setdefault(t.id, set()).add(a)
            elif isinstance(node, ast.For) \
                    and isinstance(node.target, ast.Name) \
                    and isinstance(node.iter, (ast.Tuple, ast.List)):
                attrs = {a for a in (_self_attr_of(e)
                                     for e in node.iter.elts)
                         if a is not None}
                if attrs:
                    aliases.setdefault(node.target.id, set()) \
                        .update(attrs)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                recv = node.func.value
                attrs = set()
                a = _self_attr_of(recv)
                if a is not None:
                    attrs = {a}
                elif isinstance(recv, ast.Name):
                    attrs = aliases.get(recv.id, set())
                for attr in attrs:
                    led.released.setdefault(attr, set()).add(
                        node.func.attr)
                    if node.func.attr == "join":
                        led.joined_threads.add(attr)
    return led


def build_ledgers(root: str) -> tuple[list[_ClassLedger], dict]:
    """All class ledgers plus ``{relpath: exemption_table}``."""
    trees: list[tuple[str, ast.Module, dict]] = []
    for path in _iter_product_files(root):
        tree = _parse(path)
        if tree is None:
            continue
        rel = _rel(root, path).replace(os.sep, "/")
        trees.append((rel, tree, _str_table(tree,
                                            "_LINT_LIFECYCLE_OK")))
    # Tier A first: which classes own stdlib resources (their names
    # feed tier B typing — name-based like concur's attr typing, so
    # best-effort across same-named classes).
    owner_classes: set = set()
    for rel, tree, _ex in trees:
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                led = _collect_ledger(node, rel, set(),
                                      resources_only=True)
                if led.resources:
                    owner_classes.add(node.name)
    ledgers: list[_ClassLedger] = []
    exemptions: dict = {}
    for rel, tree, ex in trees:
        exemptions[rel] = ex
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                led = _collect_ledger(node, rel, owner_classes)
                if led.resources:
                    ledgers.append(led)
    return ledgers, exemptions


def _daemon_touches_lock(led: _ClassLedger, attr: str,
                         lock_fns: set, concur) -> bool:
    target = led.resources[attr].get("target") or ""
    if not target.startswith("self.") or "." in target[5:]:
        return False
    qname = f"{led.name}.{target[5:]}"
    if qname in lock_fns:
        return True
    # one level: the target's direct self-method callees
    summary = concur._fn(qname)
    if summary is None:
        return False
    return any(s.name in lock_fns for s in summary.direct("call"))


def check_shutdown_completeness(root: str, *,
                                concur=None) -> list[SelfFinding]:
    ledgers, exemptions = build_ledgers(root)
    # Functions that acquire a known threading lock (directly, or via
    # a `*_locked` entry lockset) — the concur collector already knows.
    if concur is None:
        from .concur import ConcurAnalysis
        concur = ConcurAnalysis(root)
    lock_fns: set = set()
    for mod in concur.col.modules.values():
        for qname, summary in mod.fns.items():
            if any(s.kind == "acquire" for s in summary.sites) \
                    or any(s.held for s in summary.sites):
                lock_fns.add(qname)

    findings: list[SelfFinding] = []
    for led in ledgers:
        exempt = exemptions.get(led.relpath, {})
        if not led.close_methods:
            # Only resources that actually need a release demand a
            # shutdown surface — a daemon thread that touches no lock
            # dies harmlessly with the process.
            unexempt = [
                a for a, info in led.resources.items()
                if not _exempt(exempt, f"{led.name}:{a}")
                and not (info["kind"] == "thread" and info["daemon"]
                         and not _daemon_touches_lock(
                             led, a, lock_fns, concur))]
            if unexempt:
                findings.append(SelfFinding(
                    led.relpath, led.line, "shutdown-completeness",
                    f"{led.name} acquires "
                    f"{', '.join(sorted(unexempt))} but defines no "
                    f"close/stop/shutdown/__exit__ — add a shutdown "
                    f"surface or exempt '{led.name}:<attr>' in "
                    f"_LINT_LIFECYCLE_OK with a reason"))
            continue
        surface = "/".join(led.close_methods)
        for attr, info in sorted(led.resources.items()):
            if _exempt(exempt, f"{led.name}:{attr}"):
                continue
            kind = info["kind"]
            released = led.released.get(attr, set())
            if kind == "thread":
                if info["daemon"]:
                    if attr in led.joined_threads:
                        continue
                    if _daemon_touches_lock(led, attr, lock_fns,
                                            concur):
                        findings.append(SelfFinding(
                            led.relpath, info["line"],
                            "shutdown-completeness",
                            f"{led.name}.{attr}: daemon thread "
                            f"(target {info['target']}) takes "
                            f"threading locks but is never joined in "
                            f"{surface} — at interpreter teardown "
                            f"daemon threads die mid-critical-"
                            f"section and a held lock deadlocks "
                            f"atexit work; join it (bounded) after "
                            f"signalling stop, or exempt "
                            f"'{led.name}:{attr}' with a reason"))
                    continue
                if attr not in led.joined_threads:
                    findings.append(SelfFinding(
                        led.relpath, info["line"],
                        "shutdown-completeness",
                        f"{led.name}.{attr}: non-daemon thread is "
                        f"never joined in {surface} — the process "
                        f"cannot exit while it runs; join it or "
                        f"exempt '{led.name}:{attr}'"))
                continue
            ok_names = (_OWNER_RELEASES if kind.startswith("owner:")
                        else _RELEASES[kind])
            if kind == "server":
                # shutdown() alone stops serve_forever but leaks the
                # listening fd; server_close() (or close) is the
                # release.
                ok_names = _RELEASES["server"] | {"close"}
            if not (released & ok_names):
                what = (f"resource of class {kind[6:]}"
                        if kind.startswith("owner:") else kind)
                need = "/".join(sorted(ok_names))
                findings.append(SelfFinding(
                    led.relpath, info["line"], "shutdown-completeness",
                    f"{led.name}.{attr}: {what} acquired in "
                    f"{info['via']} is never released in {surface} "
                    f"(expected a {need} call on self.{attr}); "
                    f"release it or exempt '{led.name}:{attr}' in "
                    f"_LINT_LIFECYCLE_OK with a reason"))
    return sorted(findings, key=lambda f: (f.file, f.line))


def shutdown_ledger(root: str) -> dict:
    """The per-class resource ledger as a JSON-ready report (the CI
    ``shutdown-ledger`` artifact): every registered class, every
    resource it owns, and how its shutdown surface releases it."""
    ledgers, exemptions = build_ledgers(root)
    out: dict = {}
    for led in sorted(ledgers, key=lambda l: (l.relpath, l.line)):
        exempt = exemptions.get(led.relpath, {})
        # Same-named classes in different modules must not silently
        # overwrite each other's rows — qualify the later one.
        key = led.name if led.name not in out \
            else f"{led.name} ({led.relpath})"
        entry = {"file": led.relpath, "line": led.line,
                 "shutdown_surface": led.close_methods,
                 "resources": []}
        for attr, info in sorted(led.resources.items()):
            released = sorted(led.released.get(attr, ()))
            entry["resources"].append({
                "attr": attr, "kind": info["kind"],
                "line": info["line"], "daemon": info["daemon"],
                "acquired_in": info["via"],
                "released_by": released,
                "exempt": exempt.get(f"{led.name}:{attr}"),
            })
        out[key] = entry
    return out


# ----------------------------------------------------------------------
# entry point


def run_lifecycle_lint(root: str, concur=None
                       ) -> dict[str, list[SelfFinding]]:
    """The three lifecycle passes; ``{pass_name: findings}``.
    ``concur`` (a :class:`~.concur.ConcurAnalysis`) lets
    ``run_self_lint`` share one collection pass with the lock
    passes."""
    return {
        "resource-leak": check_resource_leaks(root),
        "bracket-discipline": check_brackets(root),
        "shutdown-completeness": check_shutdown_completeness(
            root, concur=concur),
    }
