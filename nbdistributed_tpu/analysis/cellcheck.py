"""Pre-dispatch SPMD cell vetting (the ISSUE 7 tentpole).

One notebook cell is broadcast SPMD to every rank, so a whole class of
cluster-wrecking bugs is a *textual* property of the cell — detectable
coordinator-side in milliseconds, before dispatch, instead of minutes
later when the hang watchdog's warn→dump→interrupt ladder fires:

- ``rank-conditional-collective`` (**error**): a world-collective call
  under rank-dependent control flow (``if rank == 0: all_reduce(...)``,
  ``jax.process_index()`` branches).  Only the matching ranks enter the
  collective; the others never join; the mesh deadlocks.  This is the
  exact cell shape of the PR 5 frozen-rank hang scenario.
- ``subset-collective`` (**error**): the cell's ``--ranks`` rankspec
  targets a strict subset of the world, yet the cell calls world-size
  collectives — the textual twin of the runtime guard's
  ``CollectiveHazardError`` (runtime/collective_guard.py), raised
  before a single byte ships.
- ``rank-conditional-exit`` (**error**): a ``return``/``break``/
  ``continue``/``raise`` on a rank-dependent path with collectives
  still ahead — the exiting rank desyncs the collective sequence the
  guard tracks, and every later collective pairs wrong ranks.
- ``host-sync-in-loop`` (**warning**): blocking host transfers inside
  a loop — ``.item()``/``.tolist()``, ``jax.device_get``, printing
  device values — the submission/completion coupling that kills
  accelerator saturation (Podracer, PAPERS.md) and blocks async
  pipelined dispatch (ROADMAP item 3).
- ``namespace-shadow`` (**warning**): assigning or ``del``-ing a
  seeded framework name (``rank``, ``dist``, ``all_reduce``, …) —
  every later cell in the session inherits the breakage.

Severity contract: **error** findings are reserved for shapes that
deadlock or diverge the mesh; perf/hygiene lints stay warnings.  The
magic layer annotates by default and blocks only under
``%%distributed --strict`` / ``%dist_lint strict`` — and NEVER blocks
on unparseable source (``VetResult.parsed`` is False and the findings
list empty).

Stdlib-only (ast + re); shares the collective vocabulary with the
magic layer's legacy regex and the wire-extension table with the
codec (messaging/codec.py).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .ipycompat import strip_ipython

# The eager world-collectives (parallel/collectives.py), their dist.*
# facade spellings, and the in-jit primitives that stall a multi-host
# mesh just as hard when only some processes' devices participate.
COLLECTIVE_NAMES = frozenset({
    "all_reduce", "all_reduce_quantized", "all_gather", "broadcast",
    "reduce_scatter", "barrier", "scatter", "gather", "reduce",
    "psum", "pmean", "pmax", "pmin", "ppermute", "all_to_all",
    "sync_global_devices",
})

# Expression atoms that make a condition rank-dependent: different
# ranks see different values, so a branch on them diverges SPMD flow.
RANK_ATOMS = frozenset({"rank", "__rank__", "process_index",
                        "process_id"})

# Host-blocking attribute calls: each forces a device→host transfer
# (or a full device sync) at call time.
HOST_SYNC_ATTRS = frozenset({"item", "tolist", "block_until_ready"})

# Seeded framework names whose shadowing/deletion breaks every later
# cell (runtime/worker.py _seed_namespace; the load-bearing subset).
FRAMEWORK_NAMES = frozenset({
    "rank", "world_size", "process_index", "jax", "jnp", "np", "dist",
    "devices", "device", "Mesh", "P", "PartitionSpec", "NamedSharding",
    "shard_map", "all_reduce", "all_gather", "broadcast", "barrier",
    "reduce_scatter", "all_reduce_quantized", "make_mesh",
    "shard_batch",
})

_SEVERITY_ORDER = {"error": 0, "warning": 1, "info": 2}


@dataclass
class Finding:
    rule: str
    severity: str          # "error" | "warning" | "info"
    line: int
    col: int
    message: str
    snippet: str = ""

    def render(self) -> str:
        mark = "⛔" if self.severity == "error" else "⚠️"
        loc = f"L{self.line}"
        out = f"{mark} {loc} [{self.rule}] {self.message}"
        if self.snippet:
            out += f"\n      {loc}: {self.snippet.strip()}"
        return out


@dataclass
class VetResult:
    findings: list[Finding] = field(default_factory=list)
    parsed: bool = True

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "warning"]


def _is_rank_dependent(node: ast.AST) -> bool:
    """Does this expression read a per-rank value?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in RANK_ATOMS:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in RANK_ATOMS:
            return True
    return False


def _collective_called(node: ast.Call) -> str | None:
    """The collective name this call invokes, or None."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in COLLECTIVE_NAMES:
        return fn.id
    if isinstance(fn, ast.Attribute) and fn.attr in COLLECTIVE_NAMES:
        return fn.attr
    return None


def _bound_names(target: ast.AST) -> list[ast.AST]:
    """Name-binding nodes inside an assignment/for/with target
    (attributes and subscripts mutate objects, not the namespace)."""
    out = []
    for sub in ast.walk(target):
        if isinstance(sub, ast.Name):
            out.append(sub)
    return out


class _Analyzer:
    def __init__(self, source: str, *, subset: bool):
        self.lines = source.splitlines()
        self.subset = subset
        self.findings: list[Finding] = []
        # Statements remaining after each node within the enclosing
        # scope — filled during the walk for the desync-exit rule.
        self._collective_mentions = 0

    # ------------------------------------------------------------------

    def _snippet(self, node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        if 1 <= ln <= len(self.lines):
            return self.lines[ln - 1]
        return ""

    def _add(self, rule: str, severity: str, node: ast.AST,
             message: str) -> None:
        self.findings.append(Finding(
            rule=rule, severity=severity,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message, snippet=self._snippet(node)))

    # ------------------------------------------------------------------

    def run(self, tree: ast.Module) -> list[Finding]:
        self._walk(list(tree.body), rank_cond=None, loop=False,
                   in_def=False, collectives_after=None)
        self._scan_subset(tree)
        self._scan_namespace(tree)
        # A node can be reached through more than one context path
        # (e.g. a collective inside a rank-dependent IfExp that also
        # sits under a rank-dependent `if`): one finding per site.
        seen: set = set()
        unique: list[Finding] = []
        for f in self.findings:
            key = (f.rule, f.severity, f.line, f.col)
            if key in seen:
                continue
            seen.add(key)
            unique.append(f)
        unique.sort(key=lambda f: (_SEVERITY_ORDER.get(
            f.severity, 9), f.line, f.col))
        self.findings = unique
        return self.findings

    # ------------------------------------------------------------------
    # core walk: rank-conditional collectives, desync exits, host syncs

    def _stmts_have_collective(self, stmts: list[ast.stmt]) -> bool:
        for s in stmts:
            for sub in ast.walk(s):
                if isinstance(sub, ast.Call) and _collective_called(sub):
                    return True
        return False

    def _walk(self, body: list[ast.stmt], *, rank_cond, loop: bool,
              in_def: bool, collectives_after) -> None:
        """Visit a statement list.  ``rank_cond`` is the innermost
        rank-dependent branch node (or None); ``collectives_after``
        is a callable () -> bool answering "do collectives still lie
        ahead of the current statement in this scope or an enclosing
        loop body" — the desync-exit evidence."""
        for i, stmt in enumerate(body):
            rest = body[i + 1:]

            def later(rest=rest, outer=collectives_after):
                if self._stmts_have_collective(rest):
                    return True
                return outer() if outer is not None else False

            self._visit_stmt(stmt, rank_cond=rank_cond, loop=loop,
                             in_def=in_def, collectives_after=later)

    def _visit_stmt(self, stmt: ast.stmt, *, rank_cond, loop: bool,
                    in_def: bool, collectives_after) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A def body runs when CALLED, not here: analyze it as its
            # own scope.  A rank-conditional around the *definition*
            # does not execute collectives, so the context resets —
            # but a rank-conditional inside the body still counts when
            # every rank later calls the function.
            self._walk(list(stmt.body), rank_cond=None, loop=False,
                       in_def=True, collectives_after=None)
            return
        if isinstance(stmt, ast.ClassDef):
            self._walk(list(stmt.body), rank_cond=None, loop=False,
                       in_def=True, collectives_after=None)
            return

        if isinstance(stmt, (ast.If, ast.While)):
            cond_rank = _is_rank_dependent(stmt.test)
            branch_cond = stmt if cond_rank else rank_cond
            self._scan_expr(stmt.test, rank_cond=rank_cond, loop=loop)
            body = list(stmt.body)
            after = collectives_after
            if isinstance(stmt, ast.While):
                # Like For: a break/continue skips this loop body's
                # remaining ITERATIONS, so collectives anywhere in the
                # body still count as "ahead".
                def after(body=body, outer=collectives_after):
                    if self._stmts_have_collective(body):
                        return True
                    return outer() if outer is not None else False

            self._walk(body, rank_cond=branch_cond,
                       loop=loop or isinstance(stmt, ast.While),
                       in_def=in_def, collectives_after=after)
            self._walk(list(stmt.orelse), rank_cond=branch_cond,
                       loop=loop, in_def=in_def,
                       collectives_after=collectives_after)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            body = list(stmt.body)

            def in_loop(body=body, outer=collectives_after):
                # break/continue desync evidence: collectives anywhere
                # in this loop's body (the skipped iterations), or
                # later in the enclosing scope.
                if self._stmts_have_collective(body):
                    return True
                return outer() if outer is not None else False

            self._scan_expr(stmt.iter, rank_cond=rank_cond, loop=loop)
            self._walk(body, rank_cond=rank_cond, loop=True,
                       in_def=in_def, collectives_after=in_loop)
            self._walk(list(stmt.orelse), rank_cond=rank_cond,
                       loop=loop, in_def=in_def,
                       collectives_after=collectives_after)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr, rank_cond=rank_cond,
                                loop=loop)
            self._walk(list(stmt.body), rank_cond=rank_cond, loop=loop,
                       in_def=in_def,
                       collectives_after=collectives_after)
            return
        if isinstance(stmt, ast.Try):
            for part in (stmt.body, stmt.orelse, stmt.finalbody,
                         *[h.body for h in stmt.handlers]):
                self._walk(list(part), rank_cond=rank_cond, loop=loop,
                           in_def=in_def,
                           collectives_after=collectives_after)
            return
        if isinstance(stmt, ast.Match):
            # ``match rank: case 0: all_reduce(x)`` — a rank-dependent
            # subject (or case guard) routes different ranks into
            # different arms, same divergence as a rank `if`.
            subj_rank = _is_rank_dependent(stmt.subject)
            self._scan_expr(stmt.subject, rank_cond=rank_cond,
                            loop=loop)
            for case in stmt.cases:
                case_rank = subj_rank or (
                    case.guard is not None
                    and _is_rank_dependent(case.guard))
                if case.guard is not None:
                    self._scan_expr(case.guard, rank_cond=rank_cond,
                                    loop=loop)
                self._walk(list(case.body),
                           rank_cond=stmt if case_rank else rank_cond,
                           loop=loop, in_def=in_def,
                           collectives_after=collectives_after)
            return

        # --- leaf statements ------------------------------------------
        if isinstance(stmt, (ast.Return, ast.Break, ast.Continue,
                             ast.Raise)):
            # ``return all_reduce(x)`` under a rank branch: the value
            # expression is itself a rank-conditional collective.
            for sub_expr in ast.iter_child_nodes(stmt):
                if isinstance(sub_expr, ast.expr):
                    self._scan_expr(sub_expr, rank_cond=rank_cond,
                                    loop=loop)
            if rank_cond is not None and collectives_after is not None \
                    and collectives_after():
                kind = type(stmt).__name__.lower()
                self._add(
                    "rank-conditional-exit", "error", stmt,
                    f"`{kind}` on a rank-dependent path (the `if` at "
                    f"L{rank_cond.lineno}) with collectives still "
                    f"ahead — the exiting rank(s) desync the "
                    f"collective sequence and every later collective "
                    f"pairs wrong ranks (the guard tracks this "
                    f"sequence; see runtime/collective_guard.py)")
            return
        # Generic expression scan for everything else.
        for sub_expr in ast.iter_child_nodes(stmt):
            if isinstance(sub_expr, ast.expr):
                self._scan_expr(sub_expr, rank_cond=rank_cond,
                                loop=loop)

    def _scan_expr(self, expr: ast.expr, *, rank_cond, loop: bool
                   ) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.IfExp) and \
                    _is_rank_dependent(node.test):
                for side in (node.body, node.orelse):
                    for sub in ast.walk(side):
                        if isinstance(sub, ast.Call):
                            op = _collective_called(sub)
                            if op:
                                self._flag_rank_conditional(sub, op,
                                                            node)
                continue
            if not isinstance(node, ast.Call):
                continue
            op = _collective_called(node)
            if op and rank_cond is not None:
                self._flag_rank_conditional(node, op, rank_cond)
            if loop:
                self._scan_host_sync(node)

    def _flag_rank_conditional(self, call: ast.Call, op: str,
                               cond: ast.AST) -> None:
        self._add(
            "rank-conditional-collective", "error", call,
            f"`{op}(...)` runs under rank-dependent control flow "
            f"(the branch at L{getattr(cond, 'lineno', '?')}): only "
            f"the matching rank(s) enter the collective, the rest "
            f"never join, and the mesh deadlocks until the hang "
            f"watchdog breaks it — hoist the collective out of the "
            f"branch or make the condition uniform across ranks")

    def _scan_host_sync(self, call: ast.Call) -> None:
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in HOST_SYNC_ATTRS:
            self._add(
                "host-sync-in-loop", "warning", call,
                f"`.{fn.attr}()` inside a loop forces a blocking "
                f"device→host sync every iteration — hoist it out of "
                f"the loop (or log every N steps) to keep the "
                f"accelerator queue full")
            return
        name = (fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None)
        if name == "device_get":
            self._add(
                "host-sync-in-loop", "warning", call,
                "`device_get(...)` inside a loop serializes "
                "submission and completion every iteration — batch "
                "the fetch after the loop")
            return
        if name == "print" and any(
                not isinstance(a, ast.Constant) for a in call.args):
            self._add(
                "host-sync-in-loop", "warning", call,
                "printing computed values inside a loop blocks on "
                "device results every iteration — print every N "
                "steps, or collect and print after the loop")

    # ------------------------------------------------------------------
    # subset-rankspec vs collectives

    def _scan_subset(self, tree: ast.Module) -> None:
        if not self.subset:
            return
        referenced: list[tuple[ast.AST, str]] = []
        called: list[tuple[ast.Call, str, bool]] = []
        # Track which call nodes live inside a def: defining a helper
        # on a subset is fine until it is called — warning, not error.
        def_spans: list[tuple[int, int]] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                end = getattr(node, "end_lineno", node.lineno)
                def_spans.append((node.lineno, end))
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                op = _collective_called(node)
                if op:
                    ln = node.lineno
                    in_def = any(lo <= ln <= hi for lo, hi in def_spans)
                    called.append((node, op, in_def))
            elif isinstance(node, ast.Name) \
                    and node.id in COLLECTIVE_NAMES \
                    and not isinstance(node.ctx, ast.Store):
                referenced.append((node, node.id))
        called_lines = {c.lineno for c, _, _ in called}
        for call, op, in_def in called:
            sev = "warning" if in_def else "error"
            where = (" (inside a function definition — hazardous the "
                     "moment it is called)" if in_def else "")
            self._add(
                "subset-collective", sev, call,
                f"`{op}(...)` in a cell targeted at a strict subset "
                f"of the mesh{where}: a world-collective entered by a "
                f"subset never completes (the absent ranks never "
                f"join) and would deadlock the cluster — run the "
                f"cell on all ranks, or keep subset cells to "
                f"rank-local work")
        for node, name in referenced:
            if node.lineno in called_lines:
                continue
            self._add(
                "subset-collective-ref", "warning", node,
                f"cell names the collective `{name}` but targets a "
                f"subset of the mesh — calling it from these ranks "
                f"would deadlock the cluster")

    # ------------------------------------------------------------------
    # namespace hazards

    def _scan_namespace(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            targets: list[ast.AST] = []
            verb = "assignment shadows"
            if isinstance(node, ast.Assign):
                targets = [t for tgt in node.targets
                           for t in _bound_names(tgt)]
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = _bound_names(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                targets = _bound_names(node.target)
                verb = "loop target shadows"
            elif isinstance(node, ast.Delete):
                targets = [t for tgt in node.targets
                           for t in _bound_names(tgt)]
                verb = "`del` removes"
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                if node.name in FRAMEWORK_NAMES:
                    self._add("namespace-shadow", "warning", node,
                              f"definition shadows the seeded "
                              f"framework name `{node.name}` — every "
                              f"later cell in this session sees the "
                              f"shadow, not the framework object")
                continue
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    # ``import jax`` / ``import numpy as np`` rebind a
                    # framework name to the same (or equivalent)
                    # module — the idiomatic no-op, never a hazard.
                    if bound in ("jax", "jnp", "np"):
                        continue
                    if bound in FRAMEWORK_NAMES:
                        self._add(
                            "namespace-shadow", "warning", node,
                            f"import binds `{bound}` over the seeded "
                            f"framework name — later cells lose the "
                            f"framework object")
                continue
            else:
                continue
            for t in targets:
                name = getattr(t, "id", None)
                if name in FRAMEWORK_NAMES:
                    self._add(
                        "namespace-shadow", "warning", t,
                        f"{verb} the seeded framework name `{name}` "
                        f"— every later cell in this session sees "
                        f"the shadow; pick another name (the rank-"
                        f"dependence and collective checks also key "
                        f"on it)")


def vet_cell(code: str, *, ranks=None, world: int | None = None
             ) -> VetResult:
    """Statically vet one cell before dispatch.

    ``ranks``/``world`` give the dispatch context: when ``ranks`` is a
    strict subset of ``world`` the subset-collective rule arms.
    Never raises; unparseable source (after IPython stripping) comes
    back as ``VetResult(parsed=False)`` with no findings — vetting
    must never block dispatch on source it cannot read.
    """
    subset = bool(ranks is not None and world
                  and len(set(ranks)) < int(world))
    try:
        cleaned = strip_ipython(code)
        tree = ast.parse(cleaned)
    except (SyntaxError, ValueError, RecursionError):
        return VetResult(findings=[], parsed=False)
    try:
        findings = _Analyzer(cleaned, subset=subset).run(tree)
    except RecursionError:
        return VetResult(findings=[], parsed=False)
    return VetResult(findings=findings, parsed=True)
