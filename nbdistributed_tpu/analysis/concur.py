"""Concurrency self-analysis: the lockset passes over the framework's
own source (the ISSUE 10 tentpole).

The framework is a genuinely multithreaded system — gateway serve
threads, the tenant-plane listener, the supervisor, the hang watchdog,
the manifest writer thread, and reader-thread callbacks all share
locks — and PR 8 found, by hand, exactly three expensive bug shapes:
a lock held across blocking IO (the manifest ``json.dump`` under the
daemon ``_lock`` stalling every tenant frame), lock-order inversions,
and user/reader callbacks invoked while a lock is held.  This module
mechanizes all three so they can never regress silently.

For every class (and module) in the product tree it computes, per
function, the set of locks held at each call site — tracking
``with self._lock:`` blocks, explicit ``acquire()``/``release()``
pairs, and the ``*_locked`` helper convention (a method named
``foo_locked`` ASSERTS its callers hold the class's primary lock, so
its body is analyzed with that lock held).  Lock identity is the
qualified attribute (``GatewayDaemon._lock``,
``ResultMailbox._mlock``, ``preflight::_lock`` for module-level
locks); only attributes *proven* to be locks — assigned from
``threading.Lock()`` / ``RLock()`` / ``Condition()`` — participate,
so ``block_until_ready`` never false-positives.

Three passes run over the locksets:

1. **lock-order graph** (:func:`check_lock_order`): a directed edge
   ``A → B`` for every site that acquires ``B`` while holding ``A``.
   Any cycle — including the one-node cycle of re-acquiring a
   non-reentrant ``Lock`` already held — is a potential deadlock and
   a finding.  The graph itself is reviewable documentation:
   ``nbd-lint --lock-graph`` emits it as Graphviz dot (CI uploads it
   as an artifact), with reentrant (RLock) self-edges drawn dashed.

2. **blocking-call-under-lock** (:func:`check_blocking_under_lock`):
   a declared vocabulary of blocking operations (socket
   ``send*``/``recv*``/``sendall``, ``json.dump`` + ``os.replace``,
   ``time.sleep``, ``subprocess.*``, ``send_to_ranks``/``request``,
   write-mode ``open``, ``Event.wait``/``Thread.join``) may not be
   reached while any lock is held.  Per-site exemptions live in the
   module's ``_LINT_BLOCKING_OK = {"Class.method:op": "why"}`` table
   (mirroring ``_LINT_SINGLE_WRITER``) — e.g. the transport's
   ``wlock`` exists precisely to serialize frame writes, and the
   gateway's ``_manifest_lock`` exists precisely to serialize the
   manifest's ``json.dump`` + ``os.replace``.

3. **callback-reentrancy** (:func:`check_callback_under_lock`):
   invoking a *stored callback* (``on_*`` attributes, ``*_cb`` /
   ``*_callback`` / ``*_fn`` / ``*_hook`` names, or a local bound
   from one — including ``for cb in self._notify_callbacks:``) while
   holding a lock is a finding: the callback may re-enter the locking
   object, the exact PR 8 round-9/10 deadlock shape.  Exemptions:
   ``_LINT_CALLBACK_OK = {"Class.method:name": "why"}``.

Calls are resolved **one level deep**, like :mod:`effects`:
``self.helper()`` under a lock pulls in ``helper``'s direct blocking
ops, callback invocations, and lock acquisitions; calls through a
constructor-typed attribute (``self.registry = TenantRegistry(...)``
in ``__init__`` types every ``*.registry.hello()`` receiver) resolve
cross-class, which is how ``tenant.mailbox.claim_all()`` under the
daemon lock contributes the ``GatewayDaemon._lock →
ResultMailbox._mlock`` edge.  Anything deeper, or any receiver the
analyzer cannot type, is simply not followed — the passes are
deliberately vocabulary-bounded, never exhaustive, so every finding
is cheap to verify by hand.

Stdlib-only (ast + re), shares the finding shape with
:mod:`selfcheck`, and is wired into ``run_self_lint`` /
``nbd-lint --self`` / the CI ``static-analysis`` job.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from .selfcheck import SelfFinding, _iter_product_files, _parse, _rel

# ----------------------------------------------------------------------
# vocabulary

# Constructors that make an attribute a lock.  Condition wraps a lock
# and blocks on acquire exactly the same way.
_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": False}

# Dotted call paths that block (module functions).
_BLOCKING_DOTTED = {
    "time.sleep", "json.dump", "pickle.dump", "os.replace",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
}

# Method names that block regardless of receiver: socket send/recv
# family, the control-plane senders, request/response round trips,
# process interaction, and the wait/join family (an Event.wait or
# Thread.join under a lock is a classic deadlock shape).
_BLOCKING_METHODS = frozenset({
    "sendall", "sendto", "sendmsg", "send",
    "recv", "recvfrom", "recv_into", "recvmsg",
    "send_to_ranks", "send_to_rank", "send_to_all", "post",
    "request", "communicate", "wait", "join",
})

# Stored-callback name shapes.  Broad on purpose: an invocation is
# only a finding when a lock is held, so breadth costs nothing on
# lock-free code (models/, ops/ …).  Registration APIs
# (`add_death_callback`, `set_output_callback`) are verb-prefixed
# method calls, not invocations — excluded.
_CB_NAME = re.compile(
    r"^on_[a-z0-9_]+$|.*_cb$|.*_callback$|.*_fn$|.*_hook$")
_CB_REGISTRATION = re.compile(
    r"^(add|remove|set|register|unregister|clear)_")
_CB_CONTAINER = re.compile(r".*_(callbacks|cbs|hooks)$")

_WRITE_MODE = re.compile(r"[wax+]")


# ----------------------------------------------------------------------
# shared shapes


@dataclass
class _Site:
    """One interesting event inside a function body."""

    kind: str            # "acquire" | "blocking" | "callback" | "call"
    name: str            # lock qname / op name / callback name / callee
    line: int
    held: frozenset = frozenset()
    recv_attr: str | None = None   # for kind="call": typed-attr receiver


@dataclass
class _FnSummary:
    qname: str                     # "Class.method" or "function"
    relpath: str
    cls: str | None
    sites: list = field(default_factory=list)

    def direct(self, kind: str):
        return [s for s in self.sites if s.kind == kind]


@dataclass
class _ModuleInfo:
    relpath: str
    tree: ast.Module
    # lock qname -> reentrant?
    locks: dict = field(default_factory=dict)
    # "Class.method" / "function" -> _FnSummary
    fns: dict = field(default_factory=dict)
    blocking_ok: dict = field(default_factory=dict)
    callback_ok: dict = field(default_factory=dict)
    # class name -> {attr: class-name-it-was-constructed-from}
    attr_types: dict = field(default_factory=dict)
    # class name -> set of method names (to tell methods from
    # stored-callback attributes)
    methods: dict = field(default_factory=dict)


def _dotted(node: ast.AST) -> str | None:
    """``a.b.c`` → "a.b.c" (Names/Attributes only)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _str_table(tree: ast.Module, name: str) -> dict[str, str]:
    """Module-level ``NAME = {"key": "why"}`` exemption table."""
    out: dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Dict)):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(v, ast.Constant):
                    out[str(k.value)] = str(v.value)
    return out


# ----------------------------------------------------------------------
# collection


def _lock_ctor(value: ast.AST) -> bool | None:
    """``threading.Lock()`` / ``Lock()`` → reentrant? (None: not a
    lock constructor)."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    return _LOCK_CTORS.get(name) if name in _LOCK_CTORS else None


class _Collector:
    """Builds a :class:`_ModuleInfo` per file and the global lock /
    attr-type registries."""

    def __init__(self, root: str):
        self.root = root
        self.modules: dict[str, _ModuleInfo] = {}
        # attribute name -> set of class names it was constructed as
        # (from any __init__ `self.X = ClassName(...)`)
        self.attr_classes: dict[str, set] = {}
        # class name -> (module relpath) for summary lookup
        self.class_home: dict[str, str] = {}

    def collect(self) -> None:
        # Phase 1 — registries only (locks, constructor-typed attrs,
        # method sets, exemption tables) over EVERY file, so that the
        # phase-2 body walk can resolve cross-module receivers
        # regardless of file order (daemon.py is walked before
        # tenancy.py declares `mailbox = ResultMailbox()`).
        for path in _iter_product_files(self.root):
            tree = _parse(path)
            if tree is None:
                continue
            rel = _rel(self.root, path).replace(os.sep, "/")
            mod = _ModuleInfo(rel, tree)
            mod.blocking_ok = _str_table(tree, "_LINT_BLOCKING_OK")
            mod.callback_ok = _str_table(tree, "_LINT_CALLBACK_OK")
            self._module_locks(mod)
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    self._register_class(mod, node)
            self.modules[rel] = mod
        # Phase 2 — walk function bodies with the full registries.
        for mod in self.modules.values():
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    for fn in (n for n in node.body
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))):
                        self._collect_fn(mod, fn, cls=node.name)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self._collect_fn(mod, node, cls=None)

    # -- registries ----------------------------------------------------

    def _module_locks(self, mod: _ModuleInfo) -> None:
        stem = os.path.splitext(os.path.basename(mod.relpath))[0]
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                r = _lock_ctor(node.value)
                if r is not None:
                    q = f"{stem}::{node.targets[0].id}"
                    mod.locks[q] = r

    def _register_class(self, mod: _ModuleInfo, cls: ast.ClassDef) -> None:
        methods = {n.name for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        mod.methods[cls.name] = methods
        self.class_home.setdefault(cls.name, mod.relpath)
        attr_types: dict[str, str] = {}
        # class-level lock attrs (`_display_lock = threading.Lock()`)
        for node in cls.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                r = _lock_ctor(node.value)
                if r is not None:
                    mod.locks[f"{cls.name}.{node.targets[0].id}"] = r
        # instance attrs assigned anywhere in the class body's methods:
        # locks, and constructor-typed attributes for cross-class
        # resolution.
        for fn in (n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    r = _lock_ctor(node.value)
                    if r is not None:
                        mod.locks[f"{cls.name}.{tgt.attr}"] = r
                        continue
                    if isinstance(node.value, ast.Call):
                        ctor = node.value.func
                        cname = (ctor.id if isinstance(ctor, ast.Name)
                                 else ctor.attr
                                 if isinstance(ctor, ast.Attribute)
                                 else None)
                        if cname and cname[:1].isupper():
                            attr_types[tgt.attr] = cname
                            self.attr_classes.setdefault(
                                tgt.attr, set()).add(cname)
        mod.attr_types[cls.name] = attr_types

    # -- per-function lockset walk -------------------------------------

    def _collect_fn(self, mod: _ModuleInfo, fn, cls: str | None) -> None:
        qname = f"{cls}.{fn.name}" if cls else fn.name
        summary = _FnSummary(qname, mod.relpath, cls)
        entry: frozenset = frozenset()
        if fn.name.endswith("_locked") and cls:
            primary = self._primary_lock(mod, cls)
            if primary:
                entry = frozenset({primary})
        walker = _FnWalker(self, mod, cls, summary)
        walker.walk_block(fn.body, entry)
        mod.fns[qname] = summary

    def _primary_lock(self, mod: _ModuleInfo, cls: str) -> str | None:
        """The lock a ``*_locked`` helper asserts: ``Class._lock`` when
        declared, else the class's only lock."""
        mine = [q for q in mod.locks if q.startswith(cls + ".")]
        for q in mine:
            if q.endswith("._lock"):
                return q
        return mine[0] if len(mine) == 1 else None

    def _lock_qname(self, mod: _ModuleInfo, cls: str | None,
                    node: ast.AST) -> str | None:
        """Resolve a context/receiver expression to a known lock."""
        stem = os.path.splitext(os.path.basename(mod.relpath))[0]
        if isinstance(node, ast.Name):
            q = f"{stem}::{node.id}"
            return q if q in mod.locks else None
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls:
                    q = f"{cls}.{node.attr}"
                    if q in mod.locks:
                        return q
                # `OtherClass._display_lock` — class-level lock
                q = f"{base.id}.{node.attr}"
                if q in mod.locks:
                    return q
                # lock reached through a typed attribute is not
                # tracked (one level only)
            # `x.y.lockattr` — try typed-attr receiver: self.A.lock
            if isinstance(base, ast.Attribute):
                owner = self._recv_class(mod, cls, base)
                if owner:
                    home = self.modules.get(self.class_home.get(owner, ""))
                    if home and f"{owner}.{node.attr}" in home.locks:
                        return f"{owner}.{node.attr}"
        return None

    def _recv_class(self, mod: _ModuleInfo, cls: str | None,
                    node: ast.AST) -> str | None:
        """Best-effort class of a receiver expression: ``self.attr``
        via this class's constructor-typed attrs, else any
        unambiguous global ``attr`` → class binding."""
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) \
                    and node.value.id == "self" and cls:
                t = mod.attr_types.get(cls, {}).get(node.attr)
                if t:
                    return t
            cands = self.attr_classes.get(node.attr) or set()
            if len(cands) == 1:
                return next(iter(cands))
        return None


class _FnWalker:
    """Walks one function body tracking the held lockset."""

    def __init__(self, col: _Collector, mod: _ModuleInfo,
                 cls: str | None, summary: _FnSummary):
        self.col = col
        self.mod = mod
        self.cls = cls
        self.summary = summary
        self.cb_aliases: set[str] = set()

    # -- statements ----------------------------------------------------

    def walk_block(self, stmts, held: frozenset) -> frozenset:
        for stmt in stmts:
            held = self.walk_stmt(stmt, held)
        return held

    def walk_stmt(self, stmt, held: frozenset) -> frozenset:
        if isinstance(stmt, ast.With):
            inner = held
            for item in stmt.items:
                self.walk_expr(item.context_expr, held)
                q = self.col._lock_qname(self.mod, self.cls,
                                         item.context_expr)
                if q is not None:
                    self.summary.sites.append(_Site(
                        "acquire", q, stmt.lineno, inner))
                    inner = inner | {q}
            self.walk_block(stmt.body, inner)
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # Nested defs execute later, on an unknown thread with an
            # unknown lockset — not followed (one level, like effects).
            return held
        if isinstance(stmt, (ast.If,)):
            self.walk_expr(stmt.test, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.walk_expr(stmt.iter, held)
            self._track_cb_alias_target(stmt.target, stmt.iter)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.While):
            self.walk_expr(stmt.test, held)
            self.walk_block(stmt.body, held)
            self.walk_block(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.Try):
            held = self.walk_block(stmt.body, held)
            for h in stmt.handlers:
                self.walk_block(h.body, held)
            self.walk_block(stmt.orelse, held)
            held = self.walk_block(stmt.finalbody, held)
            return held
        if isinstance(stmt, ast.Expr):
            # acquire()/release() as bare statements move the lockset.
            moved = self._acquire_release(stmt.value, held)
            if moved is not None:
                return moved
            self.walk_expr(stmt.value, held)
            return held
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(stmt, "value", None)
            if value is not None:
                self.walk_expr(value, held)
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    self._track_cb_alias_target(tgt, value)
            return held
        if isinstance(stmt, (ast.Return,)):
            if stmt.value is not None:
                self.walk_expr(stmt.value, held)
            return held
        # Everything else: walk child expressions with the current set.
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.walk_expr(child, held)
            elif isinstance(child, ast.stmt):
                self.walk_stmt(child, held)
        return held

    def _acquire_release(self, expr, held: frozenset
                         ) -> frozenset | None:
        if not (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr in ("acquire", "release")):
            return None
        q = self.col._lock_qname(self.mod, self.cls, expr.func.value)
        if q is None:
            return None
        if expr.func.attr == "acquire":
            self.summary.sites.append(_Site("acquire", q,
                                            expr.lineno, held))
            return held | {q}
        return held - {q}

    def _track_cb_alias_target(self, tgt, value) -> None:
        """``cb = self.on_x`` / ``for cb in self._cbs:`` marks ``cb``
        as a callback alias for the rest of the function."""
        if not isinstance(tgt, ast.Name):
            return
        if isinstance(value, ast.Attribute) and (
                _CB_NAME.match(value.attr)
                or _CB_CONTAINER.match(value.attr)):
            self.cb_aliases.add(tgt.id)

    # -- expressions ---------------------------------------------------

    def walk_expr(self, expr, held: frozenset) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._classify_call(node, held)

    def _classify_call(self, call: ast.Call, held: frozenset) -> None:
        fn = call.func
        dotted = _dotted(fn)
        # blocking: dotted module functions
        if dotted in _BLOCKING_DOTTED:
            self.summary.sites.append(_Site("blocking", dotted,
                                            call.lineno, held))
            return
        # blocking: write-mode open()
        if isinstance(fn, ast.Name) and fn.id == "open" \
                and self._open_writes(call):
            self.summary.sites.append(_Site("blocking", "open-write",
                                            call.lineno, held))
            return
        if isinstance(fn, ast.Attribute):
            name = fn.attr
            recv = fn.value
            is_self = isinstance(recv, ast.Name) and recv.id == "self"
            # callback attribute invocation — but a defined method of
            # this class is a method, not a stored callback, and a
            # verb-prefixed name is a registration API, not an
            # invocation.
            if (_CB_NAME.match(name)
                    and not _CB_REGISTRATION.match(name)
                    and not (is_self and self.cls and name in
                             self.mod.methods.get(self.cls, ()))):
                self.summary.sites.append(_Site("callback", name,
                                                call.lineno, held))
                return
            if name in _BLOCKING_METHODS:
                self.summary.sites.append(_Site("blocking", name,
                                                call.lineno, held))
                return
            if name in ("acquire", "release", "set", "get", "append",
                        "record", "inc", "items", "values", "keys",
                        "pop", "clear", "update", "add", "discard"):
                return  # cheap/bookkeeping: never resolved
            # resolvable call: self.method() or typed-attr method
            if is_self and self.cls:
                self.summary.sites.append(_Site(
                    "call", f"{self.cls}.{name}", call.lineno, held))
            else:
                owner = self.col._recv_class(self.mod, self.cls, recv)
                if owner:
                    self.summary.sites.append(_Site(
                        "call", f"{owner}.{name}", call.lineno, held,
                        recv_attr=_dotted(recv)))
        elif isinstance(fn, ast.Name):
            if fn.id in self.cb_aliases or _CB_NAME.match(fn.id):
                self.summary.sites.append(_Site("callback", fn.id,
                                                call.lineno, held))

    @staticmethod
    def _open_writes(call: ast.Call) -> bool:
        mode = None
        if len(call.args) >= 2 and isinstance(call.args[1],
                                              ast.Constant):
            mode = call.args[1].value
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        return isinstance(mode, str) and bool(_WRITE_MODE.search(mode))


# ----------------------------------------------------------------------
# analysis over the collected summaries


class ConcurAnalysis:
    """One collection pass; the three checks and the graph share it."""

    def __init__(self, root: str):
        self.root = root
        self.col = _Collector(root)
        self.col.collect()

    # -- lookup --------------------------------------------------------

    def _fn(self, qname: str) -> _FnSummary | None:
        cls = qname.split(".", 1)[0] if "." in qname else None
        if cls:
            home = self.col.class_home.get(cls)
            mod = self.col.modules.get(home) if home else None
            if mod:
                return mod.fns.get(qname)
            return None
        for mod in self.col.modules.values():
            if qname in mod.fns:
                return mod.fns[qname]
        return None

    def _lock_reentrant(self, q: str) -> bool:
        for mod in self.col.modules.values():
            if q in mod.locks:
                return mod.locks[q]
        return False

    # -- the lock-order graph ------------------------------------------

    def lock_edges(self) -> dict:
        """``{(src, dst): (relpath, line, via)}`` — first site wins."""
        edges: dict = {}

        def add(src, dst, rel, line, via=None):
            edges.setdefault((src, dst), (rel, line, via))

        for mod in self.col.modules.values():
            for summary in mod.fns.values():
                for s in summary.sites:
                    if s.kind == "acquire":
                        for h in s.held:
                            add(h, s.name, summary.relpath, s.line)
                    elif s.kind == "call" and s.held:
                        callee = self._fn(s.name)
                        if callee is None:
                            continue
                        for c in callee.direct("acquire"):
                            for h in s.held:
                                add(h, c.name, summary.relpath,
                                    s.line, via=s.name)
        return edges

    @staticmethod
    def _sccs(adj: dict) -> list[list[str]]:
        """Tarjan strongly-connected components (iterative) — every
        multi-node SCC contains at least one deadlock cycle, and
        every cycle lives inside exactly one SCC, so enumerating SCCs
        misses nothing (a plain DFS-from-each-start with visited
        pruning does: a b↔c inversion reachable only THROUGH a is
        pruned once a's exploration marks b and c seen)."""
        index: dict = {}
        low: dict = {}
        on_stack: set = set()
        stack: list = []
        sccs: list[list[str]] = []
        counter = [0]
        nodes = sorted(set(adj)
                       | {d for ds in adj.values() for d in ds})
        for root in nodes:
            if root in index:
                continue
            work = [(root, iter(adj.get(root, ())))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(adj.get(nxt, ()))))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        n = stack.pop()
                        on_stack.discard(n)
                        scc.append(n)
                        if n == node:
                            break
                    sccs.append(scc)
        return sccs

    @staticmethod
    def _cycle_in(scc: set, adj: dict) -> list[str]:
        """One concrete cycle inside a multi-node SCC (DFS restricted
        to the SCC; guaranteed to exist by SCC-ness)."""
        start = sorted(scc)[0]
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt not in scc:
                    continue
                if nxt == start:
                    return path + [nxt]
                if nxt not in path:
                    stack.append((nxt, path + [nxt]))
        return [start, start]   # unreachable for a true SCC

    def check_lock_order(self) -> list[SelfFinding]:
        findings: list[SelfFinding] = []
        edges = self.lock_edges()
        # one-node cycles: re-acquiring a non-reentrant lock
        adj: dict = {}
        for (src, dst), (rel, line, via) in sorted(edges.items()):
            if src == dst:
                if not self._lock_reentrant(src):
                    findings.append(SelfFinding(
                        rel, line, "lock-order",
                        f"{src} is acquired while already held"
                        + (f" (via {via})" if via else "")
                        + " — a non-reentrant Lock self-deadlocks "
                          "here; use an RLock or restructure"))
                continue
            adj.setdefault(src, []).append(dst)
        # multi-node cycles: one finding per strongly-connected
        # component, with a concrete representative cycle.
        for scc in self._sccs(adj):
            if len(scc) < 2:
                continue
            cycle = self._cycle_in(set(scc), adj)
            sites = " ; ".join(
                f"{a}→{b} at "
                f"{edges[(a, b)][0]}:{edges[(a, b)][1]}"
                for a, b in zip(cycle, cycle[1:]))
            rel, line, _ = edges[(cycle[0], cycle[1])]
            findings.append(SelfFinding(
                rel, line, "lock-order",
                f"lock-order cycle {' → '.join(cycle)} — two "
                f"threads taking these locks in opposite order "
                f"deadlock ({sites})"
                + (f"; {len(scc)} locks are mutually entangled"
                   if len(scc) > len(cycle) - 1 else "")))
        return sorted(findings, key=lambda f: (f.file, f.line))

    def lock_graph_dot(self) -> str:
        """The acquires-while-holding graph as Graphviz dot —
        reviewable documentation of the framework's lock hierarchy."""
        edges = self.lock_edges()
        nodes = sorted({n for e in edges for n in e})
        out = ["digraph lock_order {",
               '  rankdir=LR;',
               '  node [shape=box, fontsize=10];',
               '  label="acquires-while-holding (nbd-lint '
               '--lock-graph)";']
        for n in nodes:
            style = ', style=rounded' if self._lock_reentrant(n) else ''
            out.append(f'  "{n}" [label="{n}"{style}];')
        for (src, dst), (rel, line, via) in sorted(edges.items()):
            attrs = [f'label="{rel}:{line}"', 'fontsize=8']
            if src == dst and self._lock_reentrant(src):
                attrs.append("style=dashed")  # reentrant self-edge
            if via:
                attrs.append(f'tooltip="via {via}"')
            out.append(f'  "{src}" -> "{dst}" [{", ".join(attrs)}];')
        out.append("}")
        return "\n".join(out)

    # -- blocking under lock -------------------------------------------

    def _exempt(self, table: dict, fn_qname: str, name: str) -> bool:
        return f"{fn_qname}:{name}" in table

    def check_blocking_under_lock(self) -> list[SelfFinding]:
        findings: list[SelfFinding] = []
        for mod in self.col.modules.values():
            for summary in mod.fns.values():
                for s in summary.sites:
                    if s.kind == "blocking" and s.held:
                        self._flag_blocking(findings, mod, summary,
                                            s.name, s.line, s.held)
                    elif s.kind == "call" and s.held:
                        callee = self._fn(s.name)
                        if callee is None:
                            continue
                        for b in callee.direct("blocking"):
                            if b.held:
                                # The callee reports this site itself
                                # (its own lock, or a `_locked` entry
                                # lockset) — re-flagging it at every
                                # caller would count one defect k+1
                                # times.
                                continue
                            self._flag_blocking(
                                findings, mod, summary, b.name,
                                s.line, s.held, via=s.name)
        return sorted(findings, key=lambda f: (f.file, f.line))

    def _flag_blocking(self, findings, mod, summary, op, line, held,
                       via=None) -> None:
        if self._exempt(mod.blocking_ok, summary.qname, op):
            return
        if via is not None:
            # The callee's own module may exempt the op at its site
            # (`Class.method:op`), which covers every caller.
            callee = self._fn(via)
            if callee is not None:
                cmod = self.col.modules.get(callee.relpath)
                if cmod is not None and self._exempt(
                        cmod.blocking_ok, via, op):
                    return
        findings.append(SelfFinding(
            summary.relpath, line, "blocking-under-lock",
            f"{summary.qname}: blocking call {op!r}"
            + (f" (via {via})" if via else "")
            + f" reached while holding {', '.join(sorted(held))} — "
              f"move the IO outside the lock or exempt the site in "
              f"_LINT_BLOCKING_OK with a reason"))

    # -- callbacks under lock ------------------------------------------

    def check_callback_under_lock(self) -> list[SelfFinding]:
        findings: list[SelfFinding] = []
        for mod in self.col.modules.values():
            for summary in mod.fns.values():
                for s in summary.sites:
                    if s.kind == "callback" and s.held:
                        self._flag_callback(findings, mod, summary,
                                            s.name, s.line, s.held)
                    elif s.kind == "call" and s.held:
                        callee = self._fn(s.name)
                        if callee is None:
                            continue
                        for c in callee.direct("callback"):
                            if c.held:
                                continue  # self-reported by the callee
                            self._flag_callback(
                                findings, mod, summary, c.name,
                                s.line, s.held, via=s.name)
        return sorted(findings, key=lambda f: (f.file, f.line))

    def _flag_callback(self, findings, mod, summary, name, line, held,
                       via=None) -> None:
        if self._exempt(mod.callback_ok, summary.qname, name):
            return
        if via is not None:
            callee = self._fn(via)
            if callee is not None:
                cmod = self.col.modules.get(callee.relpath)
                if cmod is not None and self._exempt(
                        cmod.callback_ok, via, name):
                    return
        findings.append(SelfFinding(
            summary.relpath, line, "callback-under-lock",
            f"{summary.qname}: stored callback {name!r}"
            + (f" (via {via})" if via else "")
            + f" invoked while holding {', '.join(sorted(held))} — "
              f"the callback may re-enter this object and deadlock; "
              f"copy the callback under the lock, invoke it outside, "
              f"or exempt the site in _LINT_CALLBACK_OK with a "
              f"reason"))


# ----------------------------------------------------------------------
# entry points


def run_concur_lint(root: str, an: ConcurAnalysis | None = None
                    ) -> dict[str, list[SelfFinding]]:
    """The three concurrency passes; ``{pass_name: findings}``.
    ``an`` lets ``run_self_lint`` share one collection pass with the
    lifecycle passes instead of re-walking the tree."""
    an = an if an is not None else ConcurAnalysis(root)
    return {
        "lock-order": an.check_lock_order(),
        "blocking-under-lock": an.check_blocking_under_lock(),
        "callback-under-lock": an.check_callback_under_lock(),
    }


def lock_graph_dot(root: str) -> str:
    return ConcurAnalysis(root).lock_graph_dot()
