"""Coordinator-side memory of pre-dispatch lint findings.

When the magic layer vets a cell and dispatches it anyway (default
mode annotates, it does not block), the findings are remembered here,
keyed by the cell's source hash — the same ``cell_sha1`` the worker
computes (runtime/collective_guard.cell_hash) and the coordinator now
stamps on each pending execute request.  If a hang verdict later
lands on that cell, the watchdog, the stuck-cell doctor, and the
postmortem bundle all cite the pre-flight finding: "the analyzer told
you so" is the difference between a mystery hang and a closed loop.

Bounded, process-local, stdlib-only.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from threading import Lock

_MAX = 256
_lock = Lock()
_notes: "OrderedDict[str, dict]" = OrderedDict()


def summarize(findings) -> str:
    """One-line human summary of a finding list (errors first)."""
    ordered = sorted(findings,
                     key=lambda f: 0 if f.severity == "error" else 1)
    if not ordered:
        return ""
    head = ordered[0]
    out = f"[{head.rule}] at L{head.line}: {head.message}"
    if len(ordered) > 1:
        rest = len(ordered) - 1
        out += f" (+{rest} more finding{'s' if rest > 1 else ''})"
    return out


def note(cell_sha1: str, findings) -> None:
    """Remember a vetted-and-dispatched cell's findings."""
    if not findings:
        return
    entry = {
        "summary": summarize(findings),
        "rules": sorted({f.rule for f in findings}),
        "errors": sum(1 for f in findings if f.severity == "error"),
        "warnings": sum(1 for f in findings
                        if f.severity == "warning"),
        "ts": time.time(),
    }
    with _lock:
        _notes.pop(cell_sha1, None)
        _notes[cell_sha1] = entry
        while len(_notes) > _MAX:
            _notes.popitem(last=False)


def lookup(cell_sha1: str | None) -> dict | None:
    if not cell_sha1:
        return None
    with _lock:
        entry = _notes.get(cell_sha1)
        return dict(entry) if entry is not None else None


def clear() -> None:
    with _lock:
        _notes.clear()
