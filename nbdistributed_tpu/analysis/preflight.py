"""Coordinator-side memory of pre-dispatch analysis: lint findings
and effect footprints.

**Lint findings** (ISSUE 7): when the magic layer vets a cell and
dispatches it anyway (default mode annotates, it does not block), the
findings are remembered here, keyed by the cell's source hash — the
same ``cell_sha1`` the worker computes
(runtime/collective_guard.cell_hash) and the coordinator stamps on
each pending execute request.  If a hang verdict later lands on that
cell, the watchdog, the stuck-cell doctor, and the postmortem bundle
all cite the pre-flight finding: "the analyzer told you so" is the
difference between a mystery hang and a closed loop.

**Effect footprints** (ISSUE 9): every dispatched cell's
:class:`~.effects.EffectReport` summary is recorded by ``cell_sha1``
too, in *session order* — the substrate for the per-session **cell
dependency DAG** (:func:`deps_dag`, rendered by ``%dist_lint deps``):
an edge from cell *i* to a later cell *j* for every RAW (a name *i*
binds/mutates/deletes is free-read by *j*), WAR (*i* reads a name *j*
writes), or WAW (both write one name) hazard.  An ``opaque`` cell
(exec/star-import/globals-write/unparseable) conservatively depends
on everything before it and gates everything after it (edges named
``*``).  ROADMAP item 3's async in-flight window is declared against
exactly this DAG: cell N+1 may stream behind cell N only when no edge
connects them.

Bounded, process-local, stdlib-only.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from threading import Lock

_MAX = 256
_MAX_CELLS = 128          # session-ordered effect entries kept
_lock = Lock()
_notes: "OrderedDict[str, dict]" = OrderedDict()
_cells: list[dict] = []   # dispatched cells, session order
_seq = 0


def summarize(findings) -> str:
    """One-line human summary of a finding list (errors first)."""
    ordered = sorted(findings,
                     key=lambda f: 0 if f.severity == "error" else 1)
    if not ordered:
        return ""
    head = ordered[0]
    out = f"[{head.rule}] at L{head.line}: {head.message}"
    if len(ordered) > 1:
        rest = len(ordered) - 1
        out += f" (+{rest} more finding{'s' if rest > 1 else ''})"
    return out


def note(cell_sha1: str, findings) -> None:
    """Remember a vetted-and-dispatched cell's findings."""
    if not findings:
        return
    entry = {
        "summary": summarize(findings),
        "rules": sorted({f.rule for f in findings}),
        "errors": sum(1 for f in findings if f.severity == "error"),
        "warnings": sum(1 for f in findings
                        if f.severity == "warning"),
        "ts": time.time(),
    }
    with _lock:
        _notes.pop(cell_sha1, None)
        _notes[cell_sha1] = entry
        while len(_notes) > _MAX:
            _notes.popitem(last=False)


def lookup(cell_sha1: str | None) -> dict | None:
    if not cell_sha1:
        return None
    with _lock:
        entry = _notes.get(cell_sha1)
        return dict(entry) if entry is not None else None


def clear() -> None:
    global _seq
    with _lock:
        _notes.clear()
        del _cells[:]
        _seq = 0


# ----------------------------------------------------------------------
# effect footprints + the session dependency DAG (ISSUE 9)


def note_effects(cell_sha1: str, report) -> None:
    """Record one dispatched cell's effect footprint, in session
    order.  ``report`` is an :class:`~.effects.EffectReport` (or
    anything with a compatible ``as_dict``)."""
    global _seq
    if not cell_sha1:
        return
    try:
        summary = report.as_dict()
    except Exception:
        return
    with _lock:
        entry = {"seq": _seq, "sha": cell_sha1, "ts": time.time()}
        entry.update(summary)
        _seq += 1
        _cells.append(entry)
        while len(_cells) > _MAX_CELLS:
            _cells.pop(0)


def effects_log() -> list[dict]:
    """The session's dispatched-cell footprints, oldest first."""
    with _lock:
        return [dict(e) for e in _cells]


def effects_for(cell_sha1: str | None) -> dict | None:
    """The MOST RECENT footprint recorded for this cell hash."""
    if not cell_sha1:
        return None
    with _lock:
        for e in reversed(_cells):
            if e["sha"] == cell_sha1:
                return dict(e)
    return None


def _touched(entry: dict) -> set:
    return (set(entry.get("writes") or ())
            | set(entry.get("mutates") or ())
            | set(entry.get("deletes") or ()))


def _edge_names(earlier: dict, later: dict) -> list[str]:
    """Dependency names between two recorded cells — true (RAW,
    write→read) dependencies plus the anti/output hazards that also
    forbid reordering: WAR (earlier reads a name the later cell
    writes) and WAW (both write one name, final value is
    order-defined).  ``["*"]`` when either side is opaque
    (whole-namespace poison)."""
    if earlier.get("opaque") or later.get("opaque"):
        return ["*"]
    t_early, t_late = _touched(earlier), _touched(later)
    raw = t_early & set(later.get("reads") or ())
    war = set(earlier.get("reads") or ()) & t_late
    waw = t_early & t_late
    return sorted(raw | war | waw)


def hazard_names(earlier: dict, later: dict) -> list[str]:
    """Public form of the pairwise hazard test: the RAW/WAR/WAW names
    forbidding reorder between two footprint entries (``["*"]`` when
    either is opaque), empty when the pair may overlap freely.  This
    is the exact admission predicate of the async in-flight window
    (messaging/pipeline.py) — the same function that draws
    ``deps_dag``'s edges, so "no edge" and "admissible" can never
    drift apart."""
    return _edge_names(earlier, later)


def dag_from_entries(cells: list[dict]) -> dict:
    """The dependency DAG of an explicit entry list (each entry an
    ``EffectReport.as_dict()`` summary plus ``seq``/``sha``) — the
    pure core of :func:`deps_dag`, reusable by ``nbd-lint
    --deps-dot`` over files that never entered the session store."""
    edges = []
    for j, cj in enumerate(cells):
        for i in range(j):
            names = _edge_names(cells[i], cj)
            if names:
                edges.append({"src": cells[i]["seq"],
                              "dst": cj["seq"], "names": names})
    return {"nodes": cells, "edges": edges}


def dag_to_dot(dag: dict, labels: dict | None = None) -> str:
    """Graphviz dot of a :func:`deps_dag`-shaped DAG — the visually
    auditable form of the async-dispatch substrate (ROADMAP item 3):
    two cells may overlap exactly when no edge joins them.  WAR/WAW
    hazard edges are included, opaque cells drawn filled; ``labels``
    overrides the per-seq node label (``nbd-lint --deps-dot`` uses
    file names)."""
    labels = labels or {}
    out = ["digraph cell_deps {",
           "  rankdir=TB;",
           "  node [shape=box, fontsize=10];",
           '  label="per-session cell dependency DAG '
           '(RAW/WAR/WAW hazards; no edge = safe to overlap)";']
    for n in dag["nodes"]:
        seq = n["seq"]
        label = labels.get(seq)
        if label is None:
            label = f"#{seq} {str(n.get('sha') or '')[:10]}"
            verdict = n.get("collective_verdict")
            if verdict:
                label += f"\\n[{verdict}]"
        attrs = [f'label="{label}"']
        if n.get("opaque"):
            attrs.append('style=filled, fillcolor="#ffdddd"')
        out.append(f'  "c{seq}" [{", ".join(attrs)}];')
    for e in dag["edges"]:
        names = ", ".join(e["names"][:4])
        extra = len(e["names"]) - 4
        if extra > 0:
            names += f" +{extra}"
        out.append(f'  "c{e["src"]}" -> "c{e["dst"]}" '
                   f'[label="{names}", fontsize=8];')
    out.append("}")
    return "\n".join(out)


def deps_dag() -> dict:
    """The per-session cell dependency DAG: ``nodes`` in session
    order, ``edges`` as ``{"src": seq_i, "dst": seq_j, "names":
    [...]}`` for every ordered pair whose reordering could change a
    result — RAW (write→read), WAR (read→write), and WAW
    (write→write) hazards all count (opaque cells connect to
    everything, names ``["*"]``).  Cell j is safe to overlap/reorder
    with cell i exactly when no edge joins them — the declared
    contract for the async in-flight window."""
    with _lock:
        cells = [dict(e) for e in _cells]
    return dag_from_entries(cells)
