"""``nbd-lint`` — the static-analysis CLI (console script + CI gate).

Three modes:

- ``nbd-lint --self [ROOT]``: run the framework self-lint passes
  (analysis/selfcheck.py) over a repo checkout; nonzero exit on any
  finding.  This is CI's ``static-analysis`` job.
- ``nbd-lint FILE [FILE...]`` (or ``-`` for stdin): vet each file as
  a notebook cell with the SPMD analyzer; nonzero exit on
  error-severity findings (``--strict`` also fails on warnings).
  ``--ranks '[0,2]' --world 4`` supplies the dispatch context so the
  subset-collective rule arms.
- ``nbd-lint --knob-table``: print the README "Configuration
  reference" markdown table from the knob registry.
"""

from __future__ import annotations

import argparse
import os
import sys


def _repo_root(explicit: str | None) -> str | None:
    if explicit:
        return explicit
    # A checkout holds README.md next to the package dir.  From a
    # non-editable (wheel) install the package's parent is
    # site-packages — no README there, so fall back to the cwd before
    # giving up (running the knob-doc pass against a missing README
    # would flag every declared knob).
    import nbdistributed_tpu
    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.abspath(nbdistributed_tpu.__file__)))
    for cand in (pkg_parent, os.getcwd()):
        if os.path.isfile(os.path.join(cand, "README.md")) \
                and os.path.isdir(os.path.join(cand,
                                               "nbdistributed_tpu")):
            return cand
    return None


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nbd-lint",
        description="nbdistributed_tpu static analysis: SPMD cell "
                    "vetting and the framework self-lint")
    ap.add_argument("files", nargs="*",
                    help="cell/script files to vet ('-' = stdin)")
    ap.add_argument("--self", dest="self_lint", action="store_true",
                    help="run the framework self-lint passes")
    ap.add_argument("--root", default=None,
                    help="repo root for --self (default: the "
                         "installed package's checkout)")
    ap.add_argument("--ranks", default=None,
                    help="rankspec context for cell vetting, e.g. "
                         "'[0,2]'")
    ap.add_argument("--world", type=int, default=None,
                    help="world size context for cell vetting")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on warning-severity findings")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the configuration-reference markdown "
                         "table from the env-knob registry")
    args = ap.parse_args(argv)

    if args.knob_table:
        from ..utils.knobs import knob_table_markdown
        print(knob_table_markdown())
        return 0

    rc = 0
    if args.self_lint:
        from .selfcheck import run_self_lint
        root = _repo_root(args.root)
        if root is None:
            print("nbd-lint --self needs a repo checkout (README.md "
                  "next to nbdistributed_tpu/); run it from one or "
                  "pass --root", file=sys.stderr)
            return 2
        results = run_self_lint(root)
        total = 0
        for name, findings in results.items():
            status = "clean" if not findings else \
                f"{len(findings)} finding(s)"
            print(f"[{name}] {status}")
            for f in findings:
                print(f"  {f.render()}")
            total += len(findings)
        if total:
            print(f"\nnbd-lint --self: {total} finding(s)")
            rc = 1
        else:
            print("\nnbd-lint --self: all passes clean")

    if args.files:
        from ..magics import rankspec
        from .cellcheck import vet_cell
        ranks = None
        if args.ranks:
            world = args.world or 0
            if not world:
                print("--ranks needs --world", file=sys.stderr)
                return 2
            ranks = rankspec.parse_ranks(args.ranks, world)
        for path in args.files:
            if path == "-":
                src, label = sys.stdin.read(), "<stdin>"
            else:
                try:
                    with open(path, encoding="utf-8") as f:
                        src = f.read()
                except OSError as e:
                    print(f"{path}: {e}", file=sys.stderr)
                    rc = 2
                    continue
                label = path
            res = vet_cell(src, ranks=ranks, world=args.world)
            if not res.parsed:
                print(f"{label}: not analyzable (syntax error after "
                      f"IPython stripping) — would dispatch unvetted")
                continue
            for f in res.findings:
                print(f"{label}:{f.line}: [{f.severity}] [{f.rule}] "
                      f"{f.message}")
            bad = res.errors or (args.strict and res.warnings)
            if bad:
                rc = 1
            elif not res.findings:
                print(f"{label}: clean")

    if not args.self_lint and not args.files:
        ap.print_help()
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
