"""``nbd-lint`` — the static-analysis CLI (console script + CI gate).

Modes:

- ``nbd-lint --self [--root ROOT]``: run the framework self-lint
  passes (analysis/selfcheck.py + the analysis/concur.py concurrency
  passes) over a repo checkout; nonzero exit on any finding.  This is
  CI's ``static-analysis`` job.
- ``nbd-lint FILE [FILE...]`` (or ``-`` for stdin): vet each file as
  a notebook cell with the SPMD analyzer; nonzero exit on
  error-severity findings (``--strict`` also fails on warnings).
  ``--ranks '[0,2]' --world 4`` supplies the dispatch context so the
  subset-collective rule arms.
- ``nbd-lint --lock-graph [--root ROOT]``: emit the framework's
  acquires-while-holding lock-order graph as Graphviz dot — the
  reviewable documentation artifact CI uploads.
- ``nbd-lint --deps-dot FILE [FILE...]``: treat the files as one
  session's cells in order, infer their effect footprints, and emit
  the cell dependency DAG (RAW/WAR/WAW hazard edges) as dot — the
  ``%dist_lint deps --dot`` analog for scripts.
- ``nbd-lint --knob-table``: print the README "Configuration
  reference" markdown table from the knob registry.
- ``nbd-lint --shutdown-ledger [--root ROOT]``: emit the lifecycle
  pass's per-class resource ledger (every resource each registered
  class acquires, and how its shutdown surface releases it) as JSON
  — the reviewable artifact CI uploads next to the lock graph.

``--format json`` switches ``--self`` and file-vetting output to a
single machine-readable JSON document (findings as objects, the exit
code embedded) for CI annotations and editors.  ``--format sarif``
emits one SARIF 2.1.0 document instead (rule ids = self-lint pass
names / cell-vetting rule names, locations = repo-relative file +
line) so findings land in GitHub code scanning; the exit-code
contract below is unchanged in both formats.

Exit codes (pinned by tests/unit/test_analysis.py):

- ``0`` — clean: no findings (or none at the failing severity).
- ``1`` — findings: self-lint found violations, or a vetted file has
  error-severity findings (warnings too under ``--strict``).
- ``2`` — usage/environment error: no mode selected, unreadable
  input, or ``--self``/``--lock-graph`` outside a checkout.

When several files produce different codes, the HIGHEST applicable
code wins (an unreadable input exits 2 even if another file also had
findings) — order-independent by contract.

An UNPARSEABLE file (syntax error after IPython stripping) exits 0 by
default — the analyzer's never-block-dispatch contract — but exits 1
under ``--strict``, where the caller asked for hard guarantees and an
uninspectable cell cannot honestly be called clean.  JSON output
carries ``"parsed": false`` either way.
"""

from __future__ import annotations

import argparse
import json as _json
import os
import sys


def _read_source(path: str) -> tuple[str, str] | None:
    """``(source, label)`` for a file argument (``-`` = stdin), or
    None after printing the OSError — the one read-input helper both
    the vetting and ``--deps-dot`` modes share."""
    if path == "-":
        return sys.stdin.read(), "<stdin>"
    try:
        with open(path, encoding="utf-8") as f:
            return f.read(), path
    except OSError as e:
        print(f"{path}: {e}", file=sys.stderr)
        return None


# One-line rule descriptions for the SARIF rule catalog (self-lint
# pass names; the file mode derives its catalog from the findings).
_SELF_PASS_HELP = {
    "env-knobs": "every NBD_* knob is declared and documented",
    "codec-headers": "wire-extension registry matches the codec",
    "thread-shared-state": "shared mutations hold the owning lock",
    "protocol-coverage": "every sent message type has a handler and "
                         "every handler a sender",
    "lock-order": "the acquires-while-holding graph is acyclic",
    "blocking-under-lock": "no blocking IO while a lock is held",
    "callback-under-lock": "no stored callback invoked under a lock",
    "resource-leak": "acquired resources reach their release on all "
                     "paths including exception edges",
    "bracket-discipline": "paired mutate/unmutate brackets are "
                          "exception-safe",
    "shutdown-completeness": "every class-owned resource is released "
                             "by its shutdown surface",
}


def _sarif_document(results: list[dict]) -> dict:
    """One SARIF 2.1.0 run over ``[{rule, level, message, file,
    line}]`` result dicts.  Rule ids are the self-lint pass names or
    the cell-vetting rule names; locations are repo-relative."""
    seen_rules: dict[str, dict] = {}
    for name, text in _SELF_PASS_HELP.items():
        seen_rules[name] = {"id": name,
                            "shortDescription": {"text": text}}
    out_results = []
    for r in results:
        rid = r["rule"]
        seen_rules.setdefault(rid, {"id": rid, "shortDescription": {
            "text": f"cell-vetting rule {rid}"}})
        out_results.append({
            "ruleId": rid,
            "level": r["level"],
            "message": {"text": r["message"]},
            "locations": [{"physicalLocation": {
                # Repo-relative URI, no uriBaseId: GitHub resolves
                # relative URIs against the checkout root, and a
                # uriBaseId would need an originalUriBaseIds entry to
                # satisfy strict SARIF validators.
                "artifactLocation": {
                    "uri": r["file"].replace(os.sep, "/")},
                "region": {"startLine": max(1, int(r["line"]))},
            }}],
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "nbd-lint",
                "informationUri":
                    "https://github.com/Erland366/nbdistributed",
                "rules": sorted(seen_rules.values(),
                                key=lambda r: r["id"]),
            }},
            "columnKind": "utf16CodeUnits",
            "results": out_results,
        }],
    }


def _repo_root(explicit: str | None) -> str | None:
    if explicit:
        return explicit
    # A checkout holds README.md next to the package dir.  From a
    # non-editable (wheel) install the package's parent is
    # site-packages — no README there, so fall back to the cwd before
    # giving up (running the knob-doc pass against a missing README
    # would flag every declared knob).
    import nbdistributed_tpu
    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.abspath(nbdistributed_tpu.__file__)))
    for cand in (pkg_parent, os.getcwd()):
        if os.path.isfile(os.path.join(cand, "README.md")) \
                and os.path.isdir(os.path.join(cand,
                                               "nbdistributed_tpu")):
            return cand
    return None


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nbd-lint",
        description="nbdistributed_tpu static analysis: SPMD cell "
                    "vetting, the framework self-lint (incl. the "
                    "lock-discipline passes), and the graph exports")
    ap.add_argument("files", nargs="*",
                    help="cell/script files to vet ('-' = stdin)")
    ap.add_argument("--self", dest="self_lint", action="store_true",
                    help="run the framework self-lint passes")
    ap.add_argument("--root", default=None,
                    help="repo root for --self/--lock-graph (default: "
                         "the installed package's checkout)")
    ap.add_argument("--ranks", default=None,
                    help="rankspec context for cell vetting, e.g. "
                         "'[0,2]'")
    ap.add_argument("--world", type=int, default=None,
                    help="world size context for cell vetting")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on warning-severity findings")
    ap.add_argument("--format", choices=["text", "json", "sarif"],
                    default="text",
                    help="output format for --self / file vetting "
                         "(json: one document, findings as objects, "
                         "exit code embedded; sarif: one SARIF "
                         "2.1.0 document for GitHub code scanning)")
    ap.add_argument("--shutdown-ledger", action="store_true",
                    help="emit the lifecycle pass's per-class "
                         "resource ledger as JSON (the CI artifact)")
    ap.add_argument("--lock-graph", action="store_true",
                    help="emit the framework lock-order graph "
                         "(acquires-while-holding) as Graphviz dot")
    ap.add_argument("--deps-dot", action="store_true",
                    help="emit the FILES' cell dependency DAG "
                         "(effect-inferred RAW/WAR/WAW hazards) as "
                         "Graphviz dot")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the configuration-reference markdown "
                         "table from the env-knob registry")
    args = ap.parse_args(argv)

    if args.knob_table:
        from ..utils.knobs import knob_table_markdown
        print(knob_table_markdown())
        return 0

    if args.lock_graph:
        from .concur import lock_graph_dot
        root = _repo_root(args.root)
        if root is None:
            print("nbd-lint --lock-graph needs a repo checkout "
                  "(README.md next to nbdistributed_tpu/); run it "
                  "from one or pass --root", file=sys.stderr)
            return 2
        print(lock_graph_dot(root))
        return 0

    if args.shutdown_ledger:
        from .lifecycle import shutdown_ledger
        root = _repo_root(args.root)
        if root is None:
            print("nbd-lint --shutdown-ledger needs a repo checkout "
                  "(README.md next to nbdistributed_tpu/); run it "
                  "from one or pass --root", file=sys.stderr)
            return 2
        print(_json.dumps(shutdown_ledger(root), indent=1))
        return 0

    if args.deps_dot:
        if not args.files:
            print("nbd-lint --deps-dot needs at least one FILE "
                  "(each file = one session cell, in order)",
                  file=sys.stderr)
            return 2
        from .effects import infer_effects
        from .preflight import dag_from_entries, dag_to_dot
        entries, labels = [], {}
        for seq, path in enumerate(args.files):
            read = _read_source(path)
            if read is None:
                # Unlike vetting (per-file, continues), a DAG with a
                # missing cell is meaningless — abort.
                return 2
            src, label = read
            if label != "<stdin>":
                label = os.path.basename(label)
            entry = {"seq": seq, "sha": label}
            entry.update(infer_effects(src).as_dict())
            entries.append(entry)
            labels[seq] = f"#{seq} {label}"
        print(dag_to_dot(dag_from_entries(entries), labels=labels))
        return 0

    doc: dict = {}
    sarif_rows: list[dict] = []
    rc = 0
    if args.self_lint:
        from .selfcheck import run_self_lint
        root = _repo_root(args.root)
        if root is None:
            print("nbd-lint --self needs a repo checkout (README.md "
                  "next to nbdistributed_tpu/); run it from one or "
                  "pass --root", file=sys.stderr)
            return 2
        results = run_self_lint(root)
        total = sum(len(v) for v in results.values())
        if args.format == "sarif":
            for name, findings in results.items():
                for f in findings:
                    sarif_rows.append({
                        "rule": name, "level": "error",
                        "message": f.message,
                        "file": f.file, "line": f.line})
        elif args.format == "json":
            doc["mode"] = "self"
            doc["root"] = root
            doc["passes"] = {
                name: [{"file": f.file, "line": f.line,
                        "rule": f.rule, "message": f.message}
                       for f in findings]
                for name, findings in results.items()}
            doc["total"] = total
        else:
            for name, findings in results.items():
                status = "clean" if not findings else \
                    f"{len(findings)} finding(s)"
                print(f"[{name}] {status}")
                for f in findings:
                    print(f"  {f.render()}")
            if total:
                print(f"\nnbd-lint --self: {total} finding(s)")
            else:
                print("\nnbd-lint --self: all passes clean")
        if total:
            rc = 1

    if args.files:
        from ..magics import rankspec
        from .cellcheck import vet_cell
        ranks = None
        if args.ranks:
            world = args.world or 0
            if not world:
                print("--ranks needs --world", file=sys.stderr)
                return 2
            ranks = rankspec.parse_ranks(args.ranks, world)
        files_doc: dict = {}
        for path in args.files:
            read = _read_source(path)
            if read is None:
                rc = max(rc, 2)
                continue
            src, label = read
            res = vet_cell(src, ranks=ranks, world=args.world)
            # An unparseable cell never blocks dispatch (rc 0) — but
            # under --strict the caller asked for hard guarantees,
            # and a cell the analyzer could not inspect cannot be
            # called clean.
            bad = ((res.errors or (args.strict and res.warnings))
                   if res.parsed else args.strict)
            if args.format == "sarif":
                if not res.parsed:
                    # The JSON format's "parsed": false, as a result:
                    # an uninspectable cell is at least visible in
                    # code scanning (and a failure under --strict).
                    sarif_rows.append({
                        "rule": "not-analyzable",
                        "level": "warning" if args.strict else "note",
                        "message": "not analyzable (syntax error "
                                   "after IPython stripping) — "
                                   "would dispatch unvetted",
                        "file": label, "line": 1})
                else:
                    for f in res.findings:
                        sarif_rows.append({
                            "rule": f.rule,
                            "level": ("error"
                                      if f.severity == "error"
                                      else "warning"),
                            "message": f.message,
                            "file": label, "line": f.line})
            elif args.format == "json":
                files_doc[label] = {
                    "parsed": res.parsed,
                    "findings": [{"line": f.line,
                                  "severity": f.severity,
                                  "rule": f.rule,
                                  "message": f.message}
                                 for f in res.findings]
                    if res.parsed else []}
            elif not res.parsed:
                print(f"{label}: not analyzable (syntax error after "
                      f"IPython stripping) — "
                      + ("FAILED under --strict" if args.strict
                         else "would dispatch unvetted"))
            else:
                for f in res.findings:
                    print(f"{label}:{f.line}: [{f.severity}] "
                          f"[{f.rule}] {f.message}")
                if not res.findings:
                    print(f"{label}: clean")
            if bad:
                rc = max(rc, 1)
        if args.format == "json":
            doc.setdefault("mode", "files")
            if args.self_lint:
                doc["mode"] = "self+files"
            doc["files"] = files_doc

    if not args.self_lint and not args.files:
        ap.print_help()
        return 2
    if args.format == "sarif":
        print(_json.dumps(_sarif_document(sarif_rows), indent=1))
    elif args.format == "json":
        doc["exit_code"] = rc
        print(_json.dumps(doc, indent=1))
    return rc


if __name__ == "__main__":
    sys.exit(main())
