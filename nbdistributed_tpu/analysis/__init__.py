"""Static analysis (ISSUE 7): pre-dispatch SPMD cell vetting and the
framework self-lint.

Two halves:

- **Cell vetting** (:mod:`cellcheck`): an IPython-syntax-aware AST
  analyzer the ``%%distributed``/``%%rank`` magics run coordinator-
  side BEFORE ``send_to_ranks`` — rank-conditional collectives,
  subset-rankspec collectives, rank-conditional early exits, blocking
  host syncs in loops, namespace shadowing.  Findings annotate by
  default, hard-block under ``--strict``/``%dist_lint strict``, are
  flight-recorded and counted (``nbd_lint_findings_total{rule}``),
  and :mod:`preflight` lets a later hang verdict on a flagged cell
  cite the pre-flight finding.

- **Effect inference** (:mod:`effects`, ISSUE 9): per-cell
  :class:`~.effects.EffectReport` — name footprint (reads / writes /
  mutations / deletes, with an ``opaque`` verdict for dynamic
  escapes), the *ordered* collective footprint
  (none / exact / unknown), and host-sync/purity flags.  Consumed by
  the gateway scheduler's effects-aware admission
  (``NBD_POOL_SCHED_EFFECTS``) and the preflight store's per-session
  cell dependency DAG (``%dist_lint deps``).

- **Self-lint** (:mod:`selfcheck`, ``tools/nbd_lint.py --self``):
  custom AST passes over the framework itself — thread-shared-state
  discipline (including the gateway classes and the ``_locked``
  helper convention), the codec wire-extension registry, the
  env-knob registry (every ``NBD_*`` declared in utils/knobs.py and
  README-documented), and the protocol handler-coverage registry
  (every wire message type sent has a handler and vice versa, per
  plane).

- **Concurrency self-analysis** (:mod:`concur`, ISSUE 10): an
  interprocedural lockset analysis over the product tree — the
  lock-order (acquires-while-holding) graph with cycle detection and
  a dot export (``nbd-lint --lock-graph``), blocking-call-under-lock
  (``_LINT_BLOCKING_OK`` per-site exemptions), and
  callback-reentrancy-under-lock (``_LINT_CALLBACK_OK``) — the three
  bug shapes PR 8 burned review rounds finding by hand, mechanized.

- **Lifecycle self-analysis** (:mod:`lifecycle`, ISSUE 15): the
  acquire/release twin of :mod:`concur` — resource-leak (a declared
  acquire vocabulary must reach its release on all paths, with
  ownership transfer modeled), bracket-discipline (paired
  mutate/unmutate operations like the gateway serve counter and the
  mailbox claim/park pair must be exception-safe), and
  shutdown-completeness (a per-class resource ledger, exportable via
  ``nbd-lint --shutdown-ledger``; non-daemon threads joined, Popens
  waited, lock-taking daemon threads joined on close).  Per-site
  ``_LINT_LIFECYCLE_OK`` exemption tables; self-lint passes 8–10.

Everything here is stdlib-only (ast + re) and safe to import from
any layer.
"""

from .cellcheck import (COLLECTIVE_NAMES, FRAMEWORK_NAMES, Finding,
                        VetResult, vet_cell)
from .effects import (CollectiveSite, EffectReport, collective_class,
                      infer_effects)
from .ipycompat import strip_ipython

__all__ = ["vet_cell", "VetResult", "Finding", "strip_ipython",
           "COLLECTIVE_NAMES", "FRAMEWORK_NAMES", "EffectReport",
           "CollectiveSite", "infer_effects", "collective_class"]
