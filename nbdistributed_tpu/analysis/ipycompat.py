"""IPython-syntax-aware source cleaning for AST consumers.

Notebook cells are not quite Python: line magics (``%time f()``),
shell escapes (``!pip list``, ``files = !ls``), help syntax
(``obj?``/``?obj``) and a leading cell magic (``%%time``) all fail
``ast.parse``.  :func:`strip_ipython` rewrites exactly those lines to
``pass`` **without changing the line count or indentation**, so every
finding an AST pass reports still points at the user's real line —
the one shared helper for the cell analyzer and any future AST
consumer (satellite of ISSUE 7).

Two guards keep string literals intact: source that already parses is
returned verbatim (a ``!cmd`` line inside a triple-quoted template is
DATA, not IPython syntax), and the rewrite pass tracks triple-quote
state so a string's interior lines are never replaced even in cells
that genuinely mix multi-line strings with magic lines.

Cell magics (ISSUE 9 satellite): a leading ``%%name`` line governs
the WHOLE cell in IPython, and which rewrite is right depends on the
magic.  Python-body cell magics (``%%time``, ``%%capture``,
``%%prun``, …) execute the remainder as Python — the magic line
becomes ``pass`` and the rest is vetted normally, so a nested
``%%time`` first line no longer costs the cell its vetting.
Non-Python cell magics (``%%bash``, ``%%writefile``, ``%%html``, …)
treat the remainder as DATA — every line is masked to ``pass`` so the
cell parses cleanly (and correctly yields zero findings) instead of
coming back unparseable/unvetted.
"""

from __future__ import annotations

import ast
import re

# ``x = !cmd`` / ``x = %magic`` assignment capture: IPython grammar
# allows a simple target list before the escape.
_ASSIGN_ESCAPE = re.compile(
    r"^\s*[\w.]+(\s*,\s*[\w.]+)*\s*=\s*[!%]")
_HELP_SUFFIX = re.compile(r"^[^#'\"]*\?{1,2}\s*$")
# ``%magic`` lines need a word character right after the percent: a
# bare ``% b`` could be a wrapped modulo continuation line, which must
# survive untouched.  ``%%``-leading lines are ALWAYS IPython syntax —
# no Python statement or continuation can start with ``%%`` (``%`` is
# a binary operator; two in a row never parse), so even a bare or
# symbol-led ``%%…`` line is safe to rewrite.
_MAGIC_PREFIX = re.compile(r"%{1,2}\w")

# Cell magics whose body is NOT Python: the remainder is data for the
# magic, so the right vetting answer is "parses, nothing to report" —
# not "unparseable, unvetted".  (Python-body magics — %%time,
# %%timeit, %%capture, %%prun, %%px, %%distributed, %%rank, and
# unknown ones by default — keep the remainder and vet it.)
NON_PYTHON_CELL_MAGICS = frozenset({
    "bash", "sh", "script", "system", "cmd", "powershell", "perl",
    "ruby", "js", "javascript", "html", "latex", "svg", "markdown",
    "writefile", "file", "sql", "pypy", "python2",
})

_CELL_MAGIC_NAME = re.compile(r"^%%([\w.]+)")


def non_python_cell_magic(source: str) -> str | None:
    """The leading non-Python cell magic's name (``"bash"`` for a
    ``%%bash`` cell), or None when the cell is (possibly magic-headed)
    Python.  The sentinel effect consumers need: a masked non-Python
    cell parses as all-``pass`` but still has REAL host side effects
    (filesystem writes, subprocesses), so it must never be reported
    pure/reorderable."""
    lines = source.splitlines()
    first = lines[0].strip() if lines else ""
    m = _CELL_MAGIC_NAME.match(first)
    if m and m.group(1).split(".")[0] in NON_PYTHON_CELL_MAGICS:
        return m.group(1).split(".")[0]
    return None


def _is_ipython_line(stripped: str) -> bool:
    if not stripped:
        return False
    if stripped.startswith(("!", "?")):
        return True
    if stripped.startswith("%%"):
        return True
    if stripped.startswith("%") and _MAGIC_PREFIX.match(stripped):
        return True
    if _ASSIGN_ESCAPE.match(stripped):
        return True
    # Trailing ``?``/``??`` help (``obj.method?``) — but not inside a
    # comment or string, which the cheap regex above excludes.
    if _HELP_SUFFIX.match(stripped):
        return True
    return False


_TRIPLE = re.compile(r"'''|\"\"\"")


def _track_triple(line: str, in_string: str | None) -> str | None:
    """Advance the open-triple-quote state across one line.  Inline
    comments are honored only outside a string; escaped quotes and
    single-quoted strings containing triple-quote text are rare enough
    in notebook cells that the parse-first shortcut above handles
    them."""
    pos = 0
    while True:
        if in_string is None:
            hash_at = line.find("#", pos)
            m = _TRIPLE.search(line, pos)
            if not m or (hash_at != -1 and hash_at < m.start()):
                return None
            in_string = m.group(0)
            pos = m.end()
        else:
            close = line.find(in_string, pos)
            if close == -1:
                return in_string
            in_string = None
            pos = close + 3


def strip_ipython(source: str) -> str:
    """Replace IPython-only lines with ``pass`` (indentation kept) so
    the result parses with ``ast.parse`` while every surviving node
    keeps its original line number.  Sources that already parse —
    pure Python, including multi-line strings whose content LOOKS
    like shell/magic syntax — come back unchanged."""
    try:
        ast.parse(source)
        return source
    except (SyntaxError, ValueError):
        pass
    if non_python_cell_magic(source) is not None:
        # The whole cell is the magic's (non-Python) payload: mask
        # every line so the result parses and reports nothing, instead
        # of the remainder failing ast.parse and blinding the vetting.
        indent_pass = "\n".join(
            "pass" for _ in source.splitlines()) or "pass"
        if source.endswith("\n"):
            indent_pass += "\n"
        return indent_pass
    out: list[str] = []
    changed = False
    in_string: str | None = None
    for line in source.splitlines():
        stripped = line.strip()
        if in_string is None and _is_ipython_line(stripped):
            indent = line[:len(line) - len(line.lstrip())]
            out.append(indent + "pass")
            changed = True
        else:
            in_string = _track_triple(line, in_string)
            out.append(line)
    if not changed:
        return source
    cleaned = "\n".join(out)
    if source.endswith("\n"):
        cleaned += "\n"
    return cleaned
