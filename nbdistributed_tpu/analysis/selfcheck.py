"""Self-lint: custom AST passes over the framework's own source.

Run by ``tools/nbd_lint.py --self`` (the CI ``static-analysis`` job)
and by the ``lint``-marked unit tests.  Four registry/discipline
passes live here, each encoding a project invariant that used to live
only in review comments; :func:`run_self_lint` additionally folds in
the three :mod:`concur` concurrency passes (lock-order graph,
blocking-call-under-lock, callback-reentrancy):

1. **env-knob registry** (:func:`check_env_knobs`): every ``NBD_*``
   string in the product tree (``nbdistributed_tpu/``, ``tools/``,
   ``bench.py``) must be declared in ``utils/knobs.py`` and
   documented in README's configuration reference.  Undocumented
   knobs fail CI.

2. **codec wire-extension registry** (:func:`check_codec_headers`):
   the optional frame-header keys ``encode``/``decode`` handle and
   the heartbeat-ping piggyback fields the worker writes must match
   ``messaging/codec.py``'s ``WIRE_EXTENSIONS`` table exactly —
   declared-but-unused and used-but-undeclared both fail.

3. **thread-shared-state discipline**
   (:func:`check_thread_shared_state`): in classes that own a
   ``self._lock`` (coordinator, watchdog, supervisor, and — since
   ISSUE 9 — the gateway's daemon/registry/scheduler, whose fields
   are touched from listener/serve/eviction threads), every
   read-modify-write of ``self`` state (``+=``, container mutation)
   outside a ``with self._lock:`` block is a finding, unless the
   attribute is listed in the module's ``_LINT_SINGLE_WRITER``
   exemption table (the documented single-writer / thread-safe-
   container pattern).  Plain attribute rebinds are allowed — that is
   the documented atomic-replace pattern.  A method whose name ends
   in ``_locked`` ASSERTS its callers hold ``self._lock``: its body
   is treated as locked, and any call to a ``self.*_locked`` helper
   from an unlocked context is itself a finding — the convention that
   lets lock-held helpers stay honest instead of blanket-exempt.

4. **protocol handler coverage**
   (:func:`check_protocol_coverage`, ISSUE 10): per wire plane
   (coordinator→worker requests, worker→coordinator notices,
   tenant→gateway, gateway→tenant notices, manager→agent,
   agent→manager notices), every message-type literal a sender puts
   on the wire must have a registered handler on the receiving side,
   and every registered handler must have at least one product-tree
   sender — used-but-unhandled and handled-but-unsent both fail,
   with the ``_PROTOCOL_EXTERNAL`` exemption table for intentionally
   external types (the ``WIRE_EXTENSIONS`` pass, directionally per
   plane).

Stdlib-only; every finding carries ``file:line`` so CI output is
clickable.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass

_NBD_FULL = re.compile(r"^NBD_[A-Z][A-Z0-9_]*$")

# Product scan scope, relative to the repo root.  Tests and examples
# SET knobs (monkeypatch, notebook parametrization) but only the
# product tree READS them — declarations cover readers.
_PRODUCT_DIRS = ("nbdistributed_tpu", "tools")
_PRODUCT_FILES = ("bench.py",)

# Container-constructor names recognized when classifying ``__init__``
# attributes for the thread pass.
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "OrderedDict",
                    "defaultdict", "Counter"}
_MUTATORS = {"append", "appendleft", "add", "update", "pop", "popleft",
             "popitem", "remove", "discard", "clear", "setdefault",
             "extend", "insert"}

_THREAD_CHECKED_FILES = (
    os.path.join("nbdistributed_tpu", "messaging", "coordinator.py"),
    os.path.join("nbdistributed_tpu", "resilience", "watchdog.py"),
    os.path.join("nbdistributed_tpu", "resilience", "supervisor.py"),
    # The PR 8 gateway postdated the pass and was exempt by omission
    # (ISSUE 9 satellite): daemon fields are shared between the
    # tenant-plane listener thread, per-request serve threads, and
    # the eviction/manifest threads; the scheduler between every
    # submitter.
    os.path.join("nbdistributed_tpu", "gateway", "daemon.py"),
    os.path.join("nbdistributed_tpu", "gateway", "tenancy.py"),
    os.path.join("nbdistributed_tpu", "gateway", "scheduler.py"),
    # The serving plane (ISSUE 11): the manager's request table is
    # shared between tenant-plane submit threads and the decode
    # driver thread.
    os.path.join("nbdistributed_tpu", "gateway", "serving.py"),
    # Elastic pools (ISSUE 16): membership is shared between the
    # resize thread, the listener, and the manifest writer; the
    # router/autoscaler are included so their locking stays honest
    # as they grow state.
    os.path.join("nbdistributed_tpu", "gateway", "membership.py"),
    os.path.join("nbdistributed_tpu", "gateway", "router.py"),
    os.path.join("nbdistributed_tpu", "resilience", "autoscaler.py"),
    # Serving observatory (ISSUE 18): the request table and util ring
    # are shared between the gateway listener, per-request serve
    # threads, and the decode driver; perfbase is pure functions but
    # rides the list so any future cache/memo grows a lock.
    os.path.join("nbdistributed_tpu", "observability", "servingobs.py"),
    os.path.join("nbdistributed_tpu", "observability", "perfbase.py"),
    # Training integrity guard (ISSUE 19): TrainGuard's counters and
    # snapshot ring are mutated on the train-loop thread while the
    # heartbeat thread reads the published snapshot.
    os.path.join("nbdistributed_tpu", "resilience", "trainguard.py"),
)


@dataclass
class SelfFinding:
    file: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


def _iter_product_files(root: str):
    for d in _PRODUCT_DIRS:
        base = os.path.join(root, d)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [n for n in dirnames
                           if n != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)
    for f in _PRODUCT_FILES:
        path = os.path.join(root, f)
        if os.path.exists(path):
            yield path


def _parse(path: str) -> ast.Module | None:
    try:
        with open(path, encoding="utf-8") as f:
            return ast.parse(f.read(), path)
    except (OSError, SyntaxError):
        return None


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, root)


# ----------------------------------------------------------------------
# pass 1: env-knob registry


def check_env_knobs(root: str, readme: str | None = None
                    ) -> list[SelfFinding]:
    from ..utils import knobs

    findings: list[SelfFinding] = []
    for path in _iter_product_files(root):
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            s = node.value
            if s.endswith("_") and s.startswith("NBD_"):
                # Dynamic composition prefix (f-string builders).
                if _NBD_FULL.match(s) and s not in knobs.PREFIXES:
                    findings.append(SelfFinding(
                        _rel(root, path), node.lineno, "env-knob",
                        f"dynamic knob prefix {s!r} is not declared "
                        f"in utils/knobs.py PREFIXES"))
                continue
            if _NBD_FULL.match(s) and s not in knobs.KNOBS:
                findings.append(SelfFinding(
                    _rel(root, path), node.lineno, "env-knob",
                    f"{s} is read/written here but not declared in "
                    f"utils/knobs.py — declare it (and document it "
                    f"in README's configuration reference)"))
    # README documentation check.
    readme_path = readme or os.path.join(root, "README.md")
    try:
        with open(readme_path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        text = ""
    for name in sorted(knobs.KNOBS):
        if not re.search(rf"\b{re.escape(name)}\b", text):
            findings.append(SelfFinding(
                "README.md", 0, "env-knob",
                f"declared knob {name} is not documented in README "
                f"(regenerate the table: nbd-lint --knob-table)"))
    return findings


# ----------------------------------------------------------------------
# pass 2: codec wire-extension registry


def _func(tree: ast.Module, name: str) -> ast.FunctionDef | None:
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _method(tree: ast.Module, cls: str, name: str
            ) -> ast.FunctionDef | None:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) and sub.name == name:
                    return sub
    return None


def _subscript_str_key(node: ast.AST, varname: str) -> str | None:
    """``varname["key"]`` → "key"."""
    if (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == varname
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)):
        return node.slice.value
    return None


def check_codec_headers(root: str) -> list[SelfFinding]:
    from ..messaging.codec import BASE_HEADER_KEYS, WIRE_EXTENSIONS

    findings: list[SelfFinding] = []
    declared_header = {k for k, v in WIRE_EXTENSIONS.items()
                       if v["plane"] == "header"}
    declared_ping = {k for k, v in WIRE_EXTENSIONS.items()
                     if v["plane"] == "ping"}

    codec_path = os.path.join(root, "nbdistributed_tpu", "messaging",
                              "codec.py")
    tree = _parse(codec_path)
    if tree is None:
        return [SelfFinding("nbdistributed_tpu/messaging/codec.py", 0,
                            "codec-header", "could not parse codec.py")]
    rel_codec = _rel(root, codec_path)

    enc = _func(tree, "encode")
    emitted: set[str] = set()
    for node in ast.walk(enc) if enc else ():
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                key = _subscript_str_key(tgt, "header")
                if key is not None:
                    emitted.add(key)
    emitted -= set(BASE_HEADER_KEYS)

    dec = _func(tree, "decode")
    read: set[str] = set()
    for node in ast.walk(dec) if dec else ():
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "header"
                and node.args
                and isinstance(node.args[0], ast.Constant)):
            read.add(node.args[0].value)
    read -= set(BASE_HEADER_KEYS)

    for key in sorted(emitted - declared_header):
        findings.append(SelfFinding(
            rel_codec, enc.lineno, "codec-header",
            f"encode() emits optional header {key!r} not declared in "
            f"WIRE_EXTENSIONS"))
    for key in sorted(read - declared_header):
        findings.append(SelfFinding(
            rel_codec, dec.lineno, "codec-header",
            f"decode() reads optional header {key!r} not declared in "
            f"WIRE_EXTENSIONS"))
    for key in sorted(declared_header - emitted):
        findings.append(SelfFinding(
            rel_codec, enc.lineno if enc else 0, "codec-header",
            f"WIRE_EXTENSIONS declares header {key!r} but encode() "
            f"never emits it"))
    for key in sorted(declared_header - read):
        findings.append(SelfFinding(
            rel_codec, dec.lineno if dec else 0, "codec-header",
            f"WIRE_EXTENSIONS declares header {key!r} but decode() "
            f"never reads it"))

    # Ping plane: the worker heartbeat's data dict.
    worker_path = os.path.join(root, "nbdistributed_tpu", "runtime",
                               "worker.py")
    wtree = _parse(worker_path)
    if wtree is None:
        findings.append(SelfFinding(
            "nbdistributed_tpu/runtime/worker.py", 0, "codec-header",
            "could not parse worker.py"))
        return findings
    hb = None
    for node in ast.walk(wtree):
        if isinstance(node, ast.FunctionDef) and node.name == "_heartbeat":
            hb = node
            break
    written: set[str] = set()
    for node in ast.walk(hb) if hb else ():
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                key = _subscript_str_key(tgt, "data")
                if key is not None:
                    written.add(key)
                if isinstance(tgt, ast.Name) and tgt.id == "data" \
                        and isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            written.add(k.value)
    rel_worker = _rel(root, worker_path)
    for key in sorted(written - declared_ping):
        findings.append(SelfFinding(
            rel_worker, hb.lineno if hb else 0, "codec-header",
            f"heartbeat piggybacks ping field {key!r} not declared in "
            f"WIRE_EXTENSIONS (plane 'ping')"))
    for key in sorted(declared_ping - written):
        findings.append(SelfFinding(
            rel_worker, hb.lineno if hb else 0, "codec-header",
            f"WIRE_EXTENSIONS declares ping field {key!r} but the "
            f"heartbeat never sends it"))
    return findings


# ----------------------------------------------------------------------
# pass 3: thread-shared-state discipline


def _self_attr(node: ast.AST) -> str | None:
    """``self.X`` → "X"."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _module_exemptions(tree: ast.Module) -> dict[str, str]:
    """Module-level ``_LINT_SINGLE_WRITER = {"Class.attr": "why"}``."""
    out: dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_LINT_SINGLE_WRITER"
                and isinstance(node.value, ast.Dict)):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(v, ast.Constant):
                    out[str(k.value)] = str(v.value)
    return out


class _ThreadPass(ast.NodeVisitor):
    def __init__(self, relpath: str, cls: str, containers: set[str],
                 exempt: dict[str, str], method: str = ""):
        self.relpath = relpath
        self.cls = cls
        self.containers = containers
        self.exempt = exempt
        # The `_locked` suffix asserts "caller holds self._lock":
        # the body is analyzed as locked, and unlocked CALLS to such
        # helpers are flagged below.
        self.locked = 1 if method.endswith("_locked") else 0
        self.findings: list[SelfFinding] = []

    def _is_exempt(self, attr: str) -> bool:
        return f"{self.cls}.{attr}" in self.exempt

    def _flag(self, node: ast.AST, attr: str, what: str) -> None:
        if self._is_exempt(attr):
            return
        self.findings.append(SelfFinding(
            self.relpath, node.lineno, "thread-shared-state",
            f"{self.cls}.{attr}: {what} outside `with self._lock:` — "
            f"use the lock, replace atomically (plain rebind), or "
            f"document the single-writer pattern in "
            f"_LINT_SINGLE_WRITER"))

    # -- lock tracking --------------------------------------------------

    def _with_takes_lock(self, node: ast.With) -> bool:
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and "lock" in attr:
                return True
        return False

    def visit_With(self, node: ast.With) -> None:
        if self._with_takes_lock(node):
            self.locked += 1
            self.generic_visit(node)
            self.locked -= 1
        else:
            self.generic_visit(node)

    # -- mutation patterns ----------------------------------------------

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _self_attr(node.target)
        if attr is not None and not self.locked:
            self._flag(node, attr, "read-modify-write (`+=`)")
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if not self.locked:
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    attr = _self_attr(tgt.value)
                    if attr is not None and attr in self.containers:
                        self._flag(node, attr, "container item write")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        if not self.locked:
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    attr = _self_attr(tgt.value)
                    if attr is not None and attr in self.containers:
                        self._flag(node, attr, "container item delete")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if not self.locked and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr is not None and attr in self.containers:
                self._flag(node, attr,
                           f"container mutation (.{node.func.attr})")
        if not self.locked and isinstance(node.func, ast.Attribute) \
                and node.func.attr.endswith("_locked") \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            self._flag(node, node.func.attr,
                       "call to a lock-asserting `*_locked` helper")
        self.generic_visit(node)


def check_thread_shared_state(root: str) -> list[SelfFinding]:
    findings: list[SelfFinding] = []
    for rel in _THREAD_CHECKED_FILES:
        path = os.path.join(root, rel)
        tree = _parse(path)
        if tree is None:
            continue
        exempt = _module_exemptions(tree)
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            init = None
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) \
                        and sub.name == "__init__":
                    init = sub
                    break
            if init is None:
                continue
            has_lock = False
            containers: set[str] = set()
            for stmt in ast.walk(init):
                if isinstance(stmt, ast.Assign):
                    tgts = stmt.targets
                elif isinstance(stmt, ast.AnnAssign) \
                        and stmt.value is not None:
                    tgts = [stmt.target]
                else:
                    continue
                for tgt in tgts:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    if "lock" in attr:
                        has_lock = True
                    v = stmt.value
                    if isinstance(v, (ast.Dict, ast.List, ast.Set)):
                        containers.add(attr)
                    elif isinstance(v, ast.Call):
                        fn = v.func
                        ctor = (fn.id if isinstance(fn, ast.Name)
                                else fn.attr
                                if isinstance(fn, ast.Attribute)
                                else None)
                        if ctor in _CONTAINER_CTORS:
                            containers.add(attr)
            if not has_lock:
                continue
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) \
                        and sub.name != "__init__":
                    p = _ThreadPass(rel.replace(os.sep, "/"),
                                    node.name, containers, exempt,
                                    method=sub.name)
                    p.visit(sub)
                    findings.extend(p.findings)
    return findings


# ----------------------------------------------------------------------
# pass 4: protocol handler coverage (ISSUE 10 satellite)
#
# Every message type a sender puts on a wire plane must have a
# registered handler on the receiving side, and every registered
# handler must have at least one sender — used-but-unhandled silently
# drops requests (the peer replies "unknown type" at best), and
# handled-but-unsent is dead protocol surface that rots.  Mirrors the
# PR 7 WIRE_EXTENSIONS registry pass, directionally per plane.

# Intentionally external message types: sent or consumed outside the
# product tree (tests, operator probes) or implied by a default.
_PROTOCOL_EXTERNAL = {
    "worker-notice:response":
        "Message.reply()'s default msg_type — every worker handler "
        "reply carries it without a literal at the send site",
    "agent-notice:response":
        "Message.reply()'s default msg_type — every agent handler "
        "reply; the client correlates it by msg_id",
    "agent:ping":
        "agent liveness probe for tests and operators; sent from "
        "outside the product tree by design",
    "tenant-notice:response":
        "tenant_import reconstructs migrated parked results as "
        "mailbox entries — they leave the gateway only inside a "
        "mailbox drain's results dict, never as standalone frames",
}

# Sender-method msg_type positional index (after any leading
# ranks/rank argument).  ``submit`` is the non-blocking dispatch the
# bulk-transfer plane rides (xfer_chunk / xfer_read go out through it
# exclusively) — same (ranks, msg_type, ...) shape as send_to_ranks.
_SEND_METHODS = {"send_to_ranks": 1, "send_to_rank": 1, "post": 1,
                 "send_to_all": 0, "request": 0, "submit": 1}


def _rel_paths(root: str, rels) -> list[str]:
    return [os.path.join(root, *r.split("/")) for r in rels]


def _literal_arg(call: ast.Call, idx: int) -> str | None:
    if len(call.args) > idx and isinstance(call.args[idx], ast.Constant) \
            and isinstance(call.args[idx].value, str):
        return call.args[idx].value
    return None


def _sent_request_types(root: str, files=None, methods=None,
                        functions=None) -> dict[str, tuple[str, int]]:
    """``{msg_type: (relpath, line)}`` for literal-typed sender
    calls.  ``files=None`` scans the whole product tree;
    ``functions`` maps plain-function senders to their msg_type arg
    index (e.g. the tenant plane's ``_admin_request``)."""
    methods = methods if methods is not None else _SEND_METHODS
    functions = functions or {}
    out: dict[str, tuple[str, int]] = {}
    paths = (_rel_paths(root, files) if files is not None
             else list(_iter_product_files(root)))
    for path in paths:
        tree = _parse(path)
        if tree is None:
            continue
        rel = _rel(root, path).replace(os.sep, "/")
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in methods:
                t = _literal_arg(node, methods[fn.attr])
            elif isinstance(fn, ast.Name) and fn.id in functions:
                t = _literal_arg(node, functions[fn.id])
            else:
                continue
            if t is not None:
                out.setdefault(t, (rel, node.lineno))
    return out


def _constructed_types(root: str, file: str, cls: str | None = None
                       ) -> dict[str, tuple[str, int]]:
    """``Message(msg_type="X")`` / ``msg.reply(msg_type="X")`` /
    ``msg.reply("X")`` literals, optionally restricted to one class's
    body (sender and receiver classes share files)."""
    path = os.path.join(root, *file.split("/"))
    tree = _parse(path)
    out: dict[str, tuple[str, int]] = {}
    if tree is None:
        return out
    scope: ast.AST = tree
    if cls is not None:
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls:
                scope = node
                break
    for node in ast.walk(scope):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        t = None
        if isinstance(fn, ast.Name) and fn.id == "Message":
            for kw in node.keywords:
                if kw.arg == "msg_type" \
                        and isinstance(kw.value, ast.Constant):
                    t = kw.value.value
        elif isinstance(fn, ast.Attribute) and fn.attr == "reply":
            t = _literal_arg(node, 0)
            for kw in node.keywords:
                if kw.arg == "msg_type" \
                        and isinstance(kw.value, ast.Constant):
                    t = kw.value.value
        if isinstance(t, str):
            out.setdefault(t, (file, node.lineno))
    return out


def _handled_types(root: str, file: str, cls: str | None = None
                   ) -> dict[str, tuple[str, int]]:
    """Registered handler types in one receiver module: ``handlers =
    {"X": ...}`` dict literals, ``*.msg_type``/``mt``/``t`` equality
    and tuple-membership comparisons, and membership in module-level
    frozenset literals (``_PRE_HELLO``).  A bare ``msg_type``
    parameter is SENDER-side plumbing (``send_to_ranks(..., msg_type)``
    branches) and deliberately does not count.  ``cls`` restricts the
    scan to one class — the agent file holds both the server
    (``HostAgent``) and the client (``AgentClient``) dispatch."""
    path = os.path.join(root, *file.split("/"))
    tree = _parse(path)
    out: dict[str, tuple[str, int]] = {}
    if tree is None:
        return out
    rel = file

    # Module-level frozenset/set/tuple literals of strings, by name.
    named_sets: dict[str, list[str]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = node.value
            elts = None
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Name) \
                    and v.func.id == "frozenset" and v.args \
                    and isinstance(v.args[0], (ast.Set, ast.Tuple,
                                               ast.List)):
                elts = v.args[0].elts
            elif isinstance(v, (ast.Set, ast.Tuple)):
                elts = v.elts
            if elts is not None:
                vals = [e.value for e in elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
                if vals:
                    named_sets[node.targets[0].id] = vals

    def _is_type_expr(e: ast.AST) -> bool:
        return ((isinstance(e, ast.Attribute) and e.attr == "msg_type")
                or (isinstance(e, ast.Name) and e.id in ("mt", "t")))

    scope: ast.AST = tree
    if cls is not None:
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls:
                scope = node
                break
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "handlers" \
                and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str):
                    out.setdefault(k.value, (rel, k.lineno))
        elif isinstance(node, ast.Compare) and _is_type_expr(node.left):
            for op, cmp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Eq,)) \
                        and isinstance(cmp, ast.Constant) \
                        and isinstance(cmp.value, str):
                    out.setdefault(cmp.value, (rel, node.lineno))
                elif isinstance(op, ast.In):
                    if isinstance(cmp, (ast.Tuple, ast.Set, ast.List)):
                        for e in cmp.elts:
                            if isinstance(e, ast.Constant) \
                                    and isinstance(e.value, str):
                                out.setdefault(e.value,
                                               (rel, node.lineno))
                    elif isinstance(cmp, ast.Name) \
                            and cmp.id in named_sets:
                        for v in named_sets[cmp.id]:
                            out.setdefault(v, (rel, node.lineno))
    return out


def _protocol_planes(root: str) -> list[dict]:
    """Each plane: sent-literal map + handled-type map.  Kept as a
    function (not a constant) so tests can point the collectors at a
    synthetic tree."""
    worker_rx = "nbdistributed_tpu/runtime/worker.py"
    coord_rx = "nbdistributed_tpu/messaging/coordinator.py"
    daemon_rx = "nbdistributed_tpu/gateway/daemon.py"
    client_rx = "nbdistributed_tpu/gateway/client.py"
    agent_rx = "nbdistributed_tpu/manager/hostagent.py"
    return [
        {"name": "worker",
         # ``submit`` is the non-blocking dispatch path: the bulk-
         # transfer plane's xfer_chunk/xfer_read frames go out through
         # it exclusively (messaging/xfer.py), never via send_to_*.
         "sent": _sent_request_types(
             root, methods={"send_to_ranks": 1, "send_to_rank": 1,
                            "send_to_all": 0, "post": 1, "submit": 1}),
         "handled": _handled_types(root, worker_rx)},
        {"name": "worker-notice",
         "sent": _constructed_types(root, worker_rx),
         "handled": _handled_types(root, coord_rx)},
        {"name": "tenant",
         # router.py is in the sender list (ISSUE 16): today it sends
         # only through client.py's admin helpers, but a direct send
         # added there later must not escape the coverage pass.
         "sent": _sent_request_types(
             root, files=[client_rx,
                          "nbdistributed_tpu/gateway/router.py"],
             methods={"request": 0},
             functions={"_admin_request": 3}),
         "handled": _handled_types(root, daemon_rx)},
        {"name": "tenant-notice",
         # The serving plane (gateway/serving.py) pushes its
         # serve_tokens/serve_done notices through the daemon's
         # delivery bridges — its constructed types are tenant-plane
         # notices exactly like the daemon's own.
         "sent": {**_constructed_types(root, daemon_rx,
                                       cls="GatewayDaemon"),
                  **_constructed_types(
                      root, "nbdistributed_tpu/gateway/serving.py")},
         "handled": _handled_types(root, client_rx)},
        {"name": "agent",
         "sent": {**_sent_request_types(
                      root, files=[agent_rx,
                                   "nbdistributed_tpu/manager/"
                                   "process_manager.py"],
                      methods={"request": 0}),
                  **_constructed_types(root, agent_rx,
                                       cls="AgentClient")},
         "handled": _handled_types(root, agent_rx, cls="HostAgent")},
        {"name": "agent-notice",
         "sent": _constructed_types(root, agent_rx, cls="HostAgent"),
         "handled": _handled_types(root, agent_rx,
                                   cls="AgentClient")},
    ]


def check_protocol_coverage(root: str, planes=None,
                            external=None) -> list[SelfFinding]:
    planes = planes if planes is not None else _protocol_planes(root)
    external = external if external is not None else _PROTOCOL_EXTERNAL
    findings: list[SelfFinding] = []
    for plane in planes:
        name = plane["name"]
        sent, handled = plane["sent"], plane["handled"]
        notice = name.endswith("-notice")
        for t in sorted(set(sent) - set(handled)):
            if f"{name}:{t}" in external:
                continue
            rel, line = sent[t]
            findings.append(SelfFinding(
                rel, line, "protocol-coverage",
                f"[{name} plane] message type {t!r} is sent here but "
                f"no receiver handles it — register a handler or "
                f"exempt it in _PROTOCOL_EXTERNAL with a reason"))
        for t in sorted(set(handled) - set(sent)):
            if f"{name}:{t}" in external:
                continue
            rel, line = handled[t]
            kind = "notice" if notice else "request"
            findings.append(SelfFinding(
                rel, line, "protocol-coverage",
                f"[{name} plane] handler for {t!r} is registered "
                f"here but nothing in the product tree sends that "
                f"{kind} — dead protocol surface; remove it or "
                f"exempt it in _PROTOCOL_EXTERNAL with a reason"))
    return findings


# ----------------------------------------------------------------------


def run_self_lint(root: str) -> dict[str, list[SelfFinding]]:
    """All ten passes; ``{pass_name: findings}`` (empty = clean):
    the four registry/discipline passes here, the three
    :mod:`concur` concurrency passes (5–7), and the three
    :mod:`lifecycle` passes (8–10: resource-leak,
    bracket-discipline, shutdown-completeness).  None are
    skippable — CI gates on every key."""
    from .concur import ConcurAnalysis, run_concur_lint
    from .lifecycle import run_lifecycle_lint
    results = {
        "env-knobs": check_env_knobs(root),
        "codec-headers": check_codec_headers(root),
        "thread-shared-state": check_thread_shared_state(root),
        "protocol-coverage": check_protocol_coverage(root),
    }
    # One interprocedural collection pass, shared by the lock passes
    # and the lifecycle shutdown pass.
    an = ConcurAnalysis(root)
    results.update(run_concur_lint(root, an=an))
    results.update(run_lifecycle_lint(root, concur=an))
    return results
