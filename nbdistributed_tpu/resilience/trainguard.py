"""Training integrity guard (ISSUE 19): SDC detection and recovery
for the train step itself.

The resilience arc so far hardened everything *around* the computation
— processes, links, hangs, tenants, serving — but a silently corrupted
parameter, a NaN gradient, or a poisoned batch still flowed through
``make_ddp_step`` unchecked.  This module closes that gap with four
cooperating mechanisms:

1. **Guarded step** — ``make_tp_train_step(..., guard=True)`` fuses a
   device-side finite check on the gradients (the fp32 global
   grad-norm², one reduction riding the program that already pays the
   dp all-reduce) and *skips* the update when it is non-finite:
   params and optimizer state come back bitwise unchanged.  The host
   side (:class:`TrainGuard`) resolves the per-step ``aux`` verdicts
   **lagged and batched** (one device-side stack + one transfer per
   ~lag steps), so no step's critical path gains a host sync — the
   skip decision itself never leaves the device.

2. **Replica-consistency audit** — every N steps, each rank folds its
   params into a 2×32-bit fingerprint (position-weighted modular sums
   over the raw bit words: any single bit flip in any leaf changes it,
   provably — an odd weight times 2^k is never 0 mod 2^32), all ranks
   all-gather the fingerprints and compute the SAME majority verdict
   from the SAME gathered data, so the repair collectives stay aligned
   without any extra coordination.  A minority rank is **repaired** by
   re-broadcasting params + optimizer state from the lowest majority
   rank; with no majority (2-rank split, 3-way tie) the guard falls
   back to restoring the durable checkpoint.  A repeatedly-diverging
   rank is escalated as a quarantine suspect — surfaced through the
   ``tg`` heartbeat piggyback for the coordinator's Supervisor.

   Data-parallel replication makes the invariant exact ("Automatic
   Cross-Replica Sharding of Weight Update in Data-Parallel Training",
   PAPERS.md): under DDP and ZeRO-1/2 the *params* are replicated
   bitwise, so their fingerprints must agree even while the optimizer
   moments are dp-sharded.

3. **Rollback** — a bounded ring of in-memory snapshots (device-side
   ``jnp.copy`` trees, taken only while the guard has no outstanding
   skips) at one cadence, durable checkpoints via the existing async
   save at a coarser one.  A blown consecutive-skip budget or a
   confirmed loss spike (rolling median/MAD with consecutive
   confirmation) rolls back to the last good snapshot; the caller's
   data stream keeps advancing, so the poison batch is never retried.

4. **Bit-flip chaos** — :class:`~.faults.CorruptSpec` entries on the
   process fault plan fire inside :meth:`TrainGuard.step` (before the
   snapshot/audit of that step), flipping seeded bits of a named param
   leaf on a chosen rank — the deterministic SDC the audit exists to
   catch, injectable via ``%dist_chaos --corrupt`` or
   ``NBD_CORRUPT_SPEC``.

Thread model: every mutation happens on the worker's serial request
loop — the one thread that calls :meth:`TrainGuard.step`.  The
counters and containers shared with that loop's rarer paths are
guarded by ``self._lock``; the per-step hot path itself mutates only
single-writer state (``_i``, ``_pending``) with GIL-atomic operations
and takes no lock.  The heartbeat thread reads only the
atomically-rebound ``_snap`` dict (the ``tg`` ping field), never the
containers.
"""

from __future__ import annotations

import functools
import threading
import time
from collections import deque
from dataclasses import dataclass

from ..observability import flightrec
from ..observability import metrics as obs_metrics
from ..utils import knobs
from . import faults

# (analysis/selfcheck.py): attributes with exactly one writer thread
# (or GIL-atomic mutation) that deliberately skip the lock.
_LINT_SINGLE_WRITER = {
    "TrainGuard._i":
        "written only by the thread calling step(); the heartbeat "
        "thread reads the atomically-rebound _snap dict and describe() "
        "reads a GIL-atomic int — the hot path must not pay a lock "
        "acquisition per train step",
    "TrainGuard._pending":
        "appended only by the thread calling step() and drained by "
        "the same thread in _resolve_pending (deque ops are GIL-"
        "atomic); no other thread touches the queue",
}

# ----------------------------------------------------------------------
# device-side fingerprints

_CHUNK = 1 << 15  # words per scan chunk: bounds the transient weight
# arrays to 128 KiB regardless of leaf size


def _to_words(x):
    """Reinterpret an array's raw bits as a flat uint32 word vector
    (device-side, no host copy).  Sub-word dtypes widen losslessly;
    64-bit dtypes split into two words."""
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(x).reshape(-1)
    if x.dtype == jnp.bool_:
        return x.astype(jnp.uint32)
    size = jnp.dtype(x.dtype).itemsize
    if size == 4:
        return lax.bitcast_convert_type(x, jnp.uint32)
    if size == 2:
        return lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    if size == 1:
        return lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
    if size == 8:
        return lax.bitcast_convert_type(x, jnp.uint32).reshape(-1)
    raise TypeError(f"cannot fingerprint dtype {x.dtype}")


def _fold_words(words):
    """Fold a flat uint32 word vector to a (2,) uint32 fingerprint.
    Two independent position-weighted lanes with natural uint32
    wraparound.  A single bit flip in word i changes the word by
    ±2^k, so lane A moves by ±2^k·(2i+1): odd × 2^k is never
    ≡ 0 (mod 2^32) for k ≤ 31 — every single-bit flip is
    detected.  Lane B's independent odd weights make multi-flip
    cancellation across both lanes vanishingly unlikely."""
    import jax
    import jax.numpy as jnp

    n = words.shape[0]
    pad = (-n) % _CHUNK
    if pad:
        words = jnp.concatenate(
            [words, jnp.zeros((pad,), jnp.uint32)])
    chunks = words.reshape(-1, _CHUNK)
    j = jnp.arange(_CHUNK, dtype=jnp.uint32)

    def body(carry, w):
        a, b, base = carry
        idx = base + j
        wa = (idx << jnp.uint32(1)) | jnp.uint32(1)
        wb = (idx * jnp.uint32(2654435761)) | jnp.uint32(1)
        a = a + jnp.sum(w * wa)
        b = b + jnp.sum(w * wb)
        return (a, b, base + jnp.uint32(_CHUNK)), None

    init = (jnp.uint32(0), jnp.uint32(0), jnp.uint32(0))
    (a, b, _), _ = jax.lax.scan(body, init, chunks)
    return jnp.stack([a, b])


@functools.lru_cache(maxsize=None)
def _leaf_fp_fn():
    """One jitted program per process: bitcast + fold fused, so the
    whole per-leaf fingerprint is a single dispatch (jit caches per
    leaf shape/dtype under the hood)."""
    import jax

    return jax.jit(lambda x: _fold_words(_to_words(x)))


def leaf_fingerprint(x):
    """(2,) uint32 device array fingerprinting one leaf's exact bits."""
    return _leaf_fp_fn()(x)


@functools.lru_cache(maxsize=None)
def _stack_fn(n: int):
    """Jitted n-way stack of small same-shape device arrays (packed
    step verdicts, per-leaf fingerprints): turns n tiny host reads
    into one dispatch + one transfer."""
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda *vs: jnp.stack(vs))


@functools.lru_cache(maxsize=None)
def _copy_fn():
    """Jitted whole-tree copy for snapshots/rollbacks: one compiled
    dispatch per tree (jit caches per structure) instead of one eager
    ``copy`` primitive per leaf — the eager version costs ~0.4 ms per
    leaf in dispatch overhead alone, which dominated the snapshot
    cadence on the CPU bench."""
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda t: jax.tree_util.tree_map(jnp.copy, t))


def _mix32(h: int) -> int:
    """murmur3 fmix32: a bijective avalanche on 32-bit ints."""
    h &= 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def tree_fingerprint(tree) -> tuple[int, int]:
    """Fold a whole pytree to one ``(a, b)`` pair of 32-bit ints:
    per-leaf device fingerprints mixed host-side in deterministic
    ``tree_flatten`` order.  Each leaf's fingerprint is salted with
    its position and avalanched (:func:`_mix32`, a bijection) before
    the polynomial fold — the odd multiplier is invertible mod 2^32,
    so any change to any single leaf provably changes the fold, and
    the per-position salt keeps swapped identical-shape leaves from
    cancelling (a plain ``(a ^ f) * P + i`` fold really does collide
    when one leaf's fingerprint is 2^31 and another's is 0: the
    difference times the even ``P - 1`` vanishes mod 2^32)."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return 0, 0
    # Per-leaf fingerprints stay on device and come back in ONE
    # stacked transfer — a per-leaf ``np.asarray`` costs a full host
    # round-trip each (and the first one stalls on the whole run-ahead
    # queue; the rest should not repeat that toll).
    fps = [leaf_fingerprint(leaf) for leaf in leaves]
    rows = (np.asarray(_stack_fn(len(fps))(*fps)) if len(fps) > 1
            else np.asarray(fps[0])[None])
    a = b = 0
    for i, (fa, fb) in enumerate(rows):
        sa = (0x9E3779B9 * (i + 1)) & 0xFFFFFFFF
        sb = (0x632BE5AB * (i + 1)) & 0xFFFFFFFF
        a = (a * 0x01000193 + _mix32(int(fa) ^ sa)) & 0xFFFFFFFF
        b = (b * 0x01000193 + _mix32(int(fb) ^ sb)) & 0xFFFFFFFF
    return a, b


# ----------------------------------------------------------------------
# majority vote

@dataclass(frozen=True)
class AuditVerdict:
    """Outcome of one replica-consistency audit.  ``majority_rank`` is
    the lowest rank holding the strict-majority fingerprint (the
    repair broadcast root), or None when no fingerprint holds a strict
    majority — a 2-rank split or an N-way tie, where naming a culprit
    is impossible and the only trustworthy state is the durable
    checkpoint."""
    ok: bool
    majority_rank: int | None
    minority: tuple[int, ...]


def vote(fps) -> AuditVerdict:
    """Majority verdict over per-rank fingerprints (rank = list
    index).  Pure and deterministic: every rank feeds it the same
    all-gathered rows and must reach the same verdict, which is what
    keeps the repair collectives aligned."""
    fps = [tuple(int(v) for v in f) for f in fps]
    if not fps:
        raise ValueError("vote needs at least one fingerprint")
    counts: dict[tuple, int] = {}
    for f in fps:
        counts[f] = counts.get(f, 0) + 1
    if len(counts) == 1:
        return AuditVerdict(ok=True, majority_rank=None, minority=())
    world = len(fps)
    majority_fp = None
    for f, n in counts.items():
        if n > world // 2:
            majority_fp = f
            break
    if majority_fp is None:
        return AuditVerdict(ok=False, majority_rank=None,
                            minority=tuple(range(world)))
    ranks = [r for r, f in enumerate(fps) if f == majority_fp]
    minority = tuple(r for r, f in enumerate(fps) if f != majority_fp)
    return AuditVerdict(ok=False, majority_rank=min(ranks),
                        minority=minority)


# ----------------------------------------------------------------------
# loss-spike detection

class SpikeDetector:
    """Rolling median/MAD outlier detector with consecutive
    confirmation.  A loss above ``median + nmad·MAD`` is *suspect*;
    ``confirm`` consecutive suspects make it *confirmed* (one bad
    batch is a skip problem, a run of them is divergence).  Suspect
    losses never enter the history — a spike must not drag its own
    baseline up until it stops looking like one."""

    def __init__(self, *, window: int = 64, nmad: float = 8.0,
                 confirm: int = 2, min_history: int = 16):
        self._hist: deque[float] = deque(maxlen=max(4, int(window)))
        self.nmad = float(nmad)
        self.confirm = max(1, int(confirm))
        self.min_history = max(2, int(min_history))
        self._streak = 0
        # Median/MAD are recomputed every ``window // 8`` accepted
        # losses, not every observation: with a 64-deep window the
        # baseline cannot move meaningfully in 8 steps, and the two
        # O(n log n) sorts were the single largest per-step host cost
        # in the guarded train loop.
        self._refresh_every = max(1, self._hist.maxlen // 8)
        self._since_refresh: int | None = None  # None = stats stale
        self._med = 0.0
        self._mad = 0.0

    def _refresh_stats(self) -> None:
        hist = sorted(self._hist)
        self._med = hist[len(hist) // 2]
        self._mad = sorted(
            abs(h - self._med) for h in hist)[len(hist) // 2]
        self._since_refresh = 0

    def observe(self, loss: float) -> str:
        """Feed one resolved (finite) loss; returns ``"ok"``,
        ``"suspect"``, or ``"confirmed"``."""
        import math
        if not math.isfinite(loss):
            # Non-finite losses belong to the skip path, not the spike
            # baseline.
            return "suspect"
        if len(self._hist) < self.min_history:
            self._hist.append(loss)
            self._streak = 0
            self._since_refresh = None
            return "ok"
        if (self._since_refresh is None
                or self._since_refresh >= self._refresh_every):
            self._refresh_stats()
        med, mad = self._med, self._mad
        # MAD floor: a perfectly flat loss (mad = 0) must not turn
        # float jitter into spikes.
        floor = 1e-9 + 1e-3 * abs(med)
        if loss > med + self.nmad * max(mad, floor):
            self._streak += 1
            return ("confirmed" if self._streak >= self.confirm
                    else "suspect")
        self._hist.append(loss)
        self._since_refresh += 1
        self._streak = 0
        return "ok"

    def reset_streak(self) -> None:
        self._streak = 0


# ----------------------------------------------------------------------
# chaos: applying a CorruptSpec to a live pytree

def apply_corrupt(tree, spec, seed: int = 0):
    """Damage one leaf of ``tree`` per ``spec`` (see
    :class:`~.faults.CorruptSpec`); returns ``(new_tree, leaf_path)``.
    Deterministic in ``(seed, spec)``.  The mutation happens on a host
    copy and is re-placed with the leaf's own sharding — only
    fully-addressable leaves can be corrupted (globally-sharded arrays
    have no rank-local bytes to flip)."""
    import random as _random
    import zlib

    import jax
    import numpy as np

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    idx = None
    for i, (path, _leaf) in enumerate(flat):
        name = jax.tree_util.keystr(path)
        if spec.name == "*" or spec.name in name:
            idx = i
            break
    if idx is None:
        known = [jax.tree_util.keystr(p) for p, _ in flat[:8]]
        raise ValueError(
            f"corrupt spec names {spec.name!r} but no param leaf path "
            f"matches (leaf paths: {known}{'...' if len(flat) > 8 else ''})")
    path, leaf = flat[idx]
    name = jax.tree_util.keystr(path)
    is_jax = isinstance(leaf, jax.Array)
    if is_jax and not leaf.is_fully_addressable:
        raise ValueError(
            f"cannot corrupt {name}: leaf spans devices this process "
            f"cannot address (globally sharded array)")
    host = np.array(leaf)  # fresh writable host copy
    rng = _random.Random((int(seed) * 1_000_003)
                         ^ zlib.crc32(name.encode())
                         ^ (spec.rank * 65_537 + spec.step))
    if spec.mode == "bitflip":
        view = host.view(np.uint8).reshape(-1)
        for _ in range(spec.bits):
            pos = rng.randrange(view.size * 8)
            view[pos // 8] ^= np.uint8(1 << (pos % 8))
    else:  # "scale"
        flatv = host.reshape(-1)
        c = min(spec.count, flatv.size)
        start = rng.randrange(flatv.size - c + 1)
        flatv[start:start + c] = flatv[start:start + c] * spec.scale
    new_leaf = jax.device_put(host, leaf.sharding) if is_jax else host
    leaves = [l for _, l in flat]
    leaves[idx] = new_leaf
    return jax.tree_util.tree_unflatten(treedef, leaves), name


# ----------------------------------------------------------------------
# the guard

class TrainGuard:
    """Host-side orchestrator around a guarded train step.

    ``step_fn`` must be built with ``guard=True``
    (:func:`~nbdistributed_tpu.parallel.tensor_parallel.make_tp_train_step`,
    ``make_ddp_step``, or the zero.py builders) so it returns
    ``(params, opt_state, loss, aux)``.  The guard owns the training
    state::

        g = TrainGuard(step, params, opt_state)
        for batch in batches:
            loss = g.step(batch)      # device scalar, unresolved
        final = g.params

    Per-step cost while healthy: one pending-deque append; verdicts
    of past steps are read back in device-batched groups (one stack
    dispatch + one transfer per ~lag steps) — zero extra syncs on the
    current step's critical path.  Audits, snapshots, and durable
    checkpoints run at their own cadences and drain the queue first.

    Rollback semantics: the caller's batch stream keeps advancing —
    the guard never re-feeds the poison batch, it restores known-good
    params/opt state and trains on.
    """

    def __init__(self, step_fn, params, opt_state, *,
                 skip_budget: int | None = None,
                 audit_every: int | None = None,
                 snapshot_every: int | None = None,
                 snapshot_keep: int | None = None,
                 checkpoint_every: int | None = None,
                 checkpoint_path: str | None = None,
                 spike_window: int | None = None,
                 spike_nmad: float | None = None,
                 spike_confirm: int | None = None,
                 quarantine_after: int | None = None,
                 rank: int | None = None, escalate=None,
                 clock=time.monotonic):
        self._fn = step_fn
        self._params = params
        self._opt_state = opt_state
        self._clock = clock
        self._escalate = escalate
        self._skip_budget = (knobs.get_int("NBD_GUARD_SKIP_BUDGET", 3)
                             if skip_budget is None else int(skip_budget))
        self._audit_every = (knobs.get_int("NBD_GUARD_AUDIT_EVERY", 50)
                             if audit_every is None else int(audit_every))
        self._snapshot_every = (
            knobs.get_int("NBD_GUARD_SNAPSHOT_EVERY", 50)
            if snapshot_every is None else int(snapshot_every))
        keep = (knobs.get_int("NBD_GUARD_SNAPSHOT_KEEP", 2)
                if snapshot_keep is None else int(snapshot_keep))
        self._ckpt_every = (knobs.get_int("NBD_GUARD_CKPT_EVERY", 0)
                            if checkpoint_every is None
                            else int(checkpoint_every))
        self._ckpt_path = (checkpoint_path
                           if checkpoint_path is not None
                           else knobs.get_str("NBD_GUARD_CKPT_PATH"))
        self._quarantine_after = (
            knobs.get_int("NBD_GUARD_QUARANTINE_AFTER", 2)
            if quarantine_after is None else int(quarantine_after))
        self._spike = SpikeDetector(
            window=(knobs.get_int("NBD_GUARD_SPIKE_WINDOW", 64)
                    if spike_window is None else spike_window),
            nmad=(knobs.get_float("NBD_GUARD_SPIKE_NMAD", 8.0)
                  if spike_nmad is None else spike_nmad),
            confirm=(knobs.get_int("NBD_GUARD_SPIKE_CONFIRM", 2)
                     if spike_confirm is None else spike_confirm))
        if rank is None:
            try:
                from ..parallel import collectives
                rank = collectives.rank()
            except Exception:
                rank = 0
        self._rank = int(rank)
        # Aux verdicts resolve LAGGED and BATCHED: once more than
        # 2×lag steps are pending, the oldest lag entries are stacked
        # on device and read back in ONE transfer.  A per-step host
        # read of even a 12-byte scalar costs ~50 µs of fixed jax
        # transfer machinery, and reading a verdict the device hasn't
        # reached yet stalls the host behind the run-ahead queue —
        # batching amortizes the first and a deep lag hides the
        # second.  Verdict latency is bounded at 2×lag steps, which
        # matches the default audit cadence, and audits, snapshots,
        # and finish() drain the queue anyway (a drain at an event
        # already blocks, so resolution there is free).
        self._lag = 25
        self._lock = threading.Lock()
        self._pending: deque[tuple] = deque()
        self._snapshots: deque[tuple] = deque(maxlen=max(1, keep))
        self._events: deque[dict] = deque(maxlen=256)
        self._diverge: dict[int, int] = {}
        self._suspects: tuple[int, ...] = ()
        self._escalated: set[int] = set()
        self._i = 0
        self._skips = 0
        self._skip_streak = 0
        self._audits = 0
        self._mismatches = 0
        self._repairs = 0
        self._rollbacks = 0
        self._spikes = 0
        self._last_audit_step: int | None = None
        self._last_verdict = "none"
        self._ckpt_async = None
        self._snap: dict = {}
        reg = obs_metrics.registry()
        self._m_skips = reg.counter(
            "nbd_guard_skips_total", "guarded steps skipped on "
            "non-finite gradients")
        self._m_audits = reg.counter(
            "nbd_guard_audits_total", "replica-consistency audits run")
        self._m_mismatches = reg.counter(
            "nbd_guard_mismatches_total", "audits that found replica "
            "fingerprint divergence")
        self._m_repairs = reg.counter(
            "nbd_guard_repairs_total", "divergent replicas repaired "
            "(majority re-broadcast or checkpoint restore)")
        self._m_rollbacks = reg.counter(
            "nbd_guard_rollbacks_total", "rollbacks to an in-memory "
            "snapshot (blown skip budget / confirmed loss spike)")
        with self._lock:
            self._publish_locked()
        # Step-0 baseline snapshot: rollback always has a target.
        if self._snapshot_every:
            self._take_snapshot(0)
        # Warm the per-leaf fingerprint programs now (local, no
        # collective) so the first in-loop audit pays dispatch, not XLA
        # compilation — compiling mid-training is exactly the stall the
        # lagged-resolve design exists to avoid.
        if self._audit_every:
            tree_fingerprint(self._params)
        global _ACTIVE
        _ACTIVE = self
        flightrec.record("guard_start", rank=self._rank,
                         skip_budget=self._skip_budget,
                         audit_every=self._audit_every,
                         snapshot_every=self._snapshot_every)

    # -- public state --------------------------------------------------

    @property
    def params(self):
        return self._params

    @property
    def opt_state(self):
        return self._opt_state

    @property
    def step_index(self) -> int:
        with self._lock:
            return self._i

    # -- the per-step path ---------------------------------------------

    def step(self, batch):
        """Run one guarded train step; returns the (unresolved) device
        loss.  Order matters: chaos corruption fires first (so this
        step's snapshot/audit see it exactly as a real SDC would be
        seen — *after* the damage), then snapshot / audit / durable
        checkpoint at their cadences, then the fused device step is
        dispatched, and only THEN older verdicts resolve — in batched
        groups whose single host read covers many long-materialized
        steps and overlaps the new step's in-flight compute instead of
        stalling the pipeline.  A rollback landing in that resolution
        simply replaces the in-flight assignment: the restored
        snapshot wins and the poisoned entries are dropped from the
        pending deque."""
        if not is_enabled():
            out = self._fn(self._params, self._opt_state, batch)
            self._params, self._opt_state = out[0], out[1]
            self._i += 1
            return out[2]
        # Hot path discipline: ``self._i`` has exactly one writer (the
        # thread calling step), so the cadence gates read it unlocked —
        # a healthy non-cadence step runs zero lock acquisitions and
        # zero method calls before the dispatch below.
        i = self._i
        if faults.process_plan() is not None:
            self._inject_corruption()
        if i:
            if self._snapshot_every and not i % self._snapshot_every:
                self._maybe_snapshot()
            if self._audit_every and not i % self._audit_every:
                self._maybe_audit()
            if self._ckpt_every and self._ckpt_path \
                    and not i % self._ckpt_every:
                self._maybe_checkpoint()
        out = self._fn(self._params, self._opt_state, batch)
        if len(out) != 4:
            raise TypeError(
                "TrainGuard needs a guarded step returning (params, "
                "opt_state, loss, aux) — build it with guard=True "
                "(make_ddp_step / make_tp_train_step / zero builders)")
        params, opt_state, loss, aux = out
        self._params, self._opt_state = params, opt_state
        # deque.append and the int rebind are each GIL-atomic, and the
        # heartbeat thread only ever *reads* _i — no lock needed here.
        self._pending.append((self._i, loss, aux))
        self._i += 1
        if len(self._pending) >= 2 * self._lag:
            self._resolve_pending(drain=False)
        return loss

    def finish(self) -> dict:
        """Drain every pending verdict (end of the training loop) and
        return :meth:`describe`."""
        self._resolve_pending(drain=True)
        return self.describe()

    # -- verdict resolution (lagged) ------------------------------------

    def _resolve_pending(self, *, drain: bool) -> None:
        with self._lock:
            n = len(self._pending)
            if not n or (not drain and n < 2 * self._lag):
                return
            take = n if drain else n - self._lag
            batch = [self._pending.popleft() for _ in range(take)]
        import numpy as np

        # Batch the packed-verdict reads: stack every pending "v" lane
        # on device with one (cached-jit) dispatch and pull the whole
        # block in one transfer.
        packed = [aux["v"] for _, _, aux in batch
                  if aux.get("v") is not None]
        if len(packed) > 1:
            rows = np.asarray(_stack_fn(len(packed))(*packed))
        elif packed:
            rows = np.asarray(packed[0])[None]
        ri = 0
        for idx, loss, aux in batch:
            if aux.get("v") is not None:
                okf, lossf, gnorm = rows[ri]
                ri += 1
                rolled = self._after_verdict(idx, bool(okf),
                                             float(lossf), float(gnorm))
            else:
                ok = bool(aux["ok"])
                # gnorm is only flight-recorded on a skip: don't pay a
                # device read for it on the (overwhelmingly common)
                # healthy step.
                gnorm = float("nan") if ok else float(aux["gnorm"])
                rolled = self._after_verdict(idx, ok, float(loss),
                                             gnorm)
            if rolled:
                # A rollback just restored older state and cleared the
                # shared pending queue; the rest of this local batch
                # predates the restore and must be dropped with it.
                return

    def _after_verdict(self, idx: int, ok: bool, loss: float,
                       gnorm: float) -> bool:
        """Apply one resolved verdict; returns True when it triggered
        a rollback (the pending queue was cleared)."""
        if not ok:
            self._m_skips.inc()
            with self._lock:
                self._skips += 1
                self._skip_streak += 1
                streak = self._skip_streak
                # Retroactively invalidate speculative snapshots taken
                # after this (just-resolved) bad step: the params they
                # captured may already carry the corruption that made
                # these gradients non-finite.
                dropped = 0
                while self._snapshots and self._snapshots[-1][0] > idx:
                    self._snapshots.pop()
                    dropped += 1
                self._publish_locked()
            if dropped:
                self._event("snapshot_dropped", after=idx, n=dropped)
            flightrec.record("guard_skip", step=idx, gnorm=gnorm,
                             streak=streak)
            self._event("skip", step=idx, streak=streak)
            if self._skip_budget and streak > self._skip_budget:
                self._rollback(f"skip budget blown: {streak} "
                               f"consecutive non-finite steps "
                               f"(budget {self._skip_budget})",
                               step=idx)
                return True
            return False
        with self._lock:
            self._skip_streak = 0
        verdict = self._spike.observe(loss)
        if verdict == "confirmed":
            with self._lock:
                self._spikes += 1
            self._event("spike", step=idx, loss=loss)
            flightrec.record("guard_spike", step=idx, loss=loss)
            self._rollback(f"loss spike confirmed at step {idx} "
                           f"(loss {loss:g})", step=idx)
            return True
        elif verdict == "suspect":
            self._event("spike_suspect", step=idx, loss=loss)
        return False

    # -- snapshots / rollback -------------------------------------------

    def _maybe_snapshot(self) -> None:
        if not self._snapshot_every:
            return
        i = self._i
        if i == 0 or i % self._snapshot_every:
            return
        # Never snapshot mid-skip-streak: the last snapshot must stay
        # the last KNOWN-GOOD state the streak can roll back to.  This
        # gate sees only *resolved* verdicts — the snapshot is taken
        # SPECULATIVELY, without flushing the device pipeline to
        # resolve the in-flight ones (the flush cost ~1 ms of lost
        # run-ahead per event).  If a still-pending step later resolves
        # as a skip, :meth:`_after_verdict` retroactively drops every
        # snapshot taken after it, which restores exactly the
        # drain-first semantics.
        with self._lock:
            streak = self._skip_streak
        if streak:
            return
        self._take_snapshot(i)

    def _take_snapshot(self, i: int) -> None:
        # One fused dispatch for both trees: two eager jit calls cost
        # ~2× the host-side dispatch for the same device work.
        p, o = _copy_fn()((self._params, self._opt_state))
        with self._lock:
            self._snapshots.append((i, p, o))
        self._event("snapshot", step=i)

    def _rollback(self, reason: str, *, step: int) -> None:
        with self._lock:
            snap = self._snapshots[-1] if self._snapshots else None
            self._pending.clear()
            self._skip_streak = 0
        self._spike.reset_streak()
        if snap is None:
            if self._restore_checkpoint(reason):
                return
            flightrec.record("guard_rollback_unavailable",
                             reason=reason, step=step)
            self._event("rollback_unavailable", step=step,
                        reason=reason)
            return
        idx, p, o = snap
        # Restore COPIES: the restored buffers get donated into the
        # next step, and the ring entry must survive for a second
        # rollback.
        self._params, self._opt_state = _copy_fn()((p, o))
        self._m_rollbacks.inc()
        with self._lock:
            self._rollbacks += 1
            self._publish_locked()
        flightrec.record("guard_rollback", reason=reason, frm=step,
                         to=idx)
        self._event("rollback", frm=step, to=idx, reason=reason)

    # -- durable checkpoints --------------------------------------------

    def _maybe_checkpoint(self) -> None:
        if not self._ckpt_every or not self._ckpt_path:
            return
        with self._lock:
            i = self._i
        if i == 0 or i % self._ckpt_every:
            return
        from ..runtime import checkpoint

        prev = self._ckpt_async
        if prev is not None and not prev.done():
            return  # still draining; this cadence tick is skipped
        if prev is not None:
            self._ckpt_async = None
            try:
                prev.wait(0)
            except Exception as e:  # surfaced, never fatal
                flightrec.record("guard_ckpt_failed", error=str(e)[:200])
                self._event("ckpt_failed", error=str(e)[:200])
        try:
            from ..parallel import collectives
            world = collectives.world_size()
        except Exception:
            world = 1
        ns = {"params": self._params, "opt_state": self._opt_state}
        self._ckpt_async = checkpoint.save_async(
            self._ckpt_path, ns, ["params", "opt_state"],
            rank=self._rank, world_size=world)
        self._event("checkpoint", step=i)

    def _restore_checkpoint(self, reason: str) -> bool:
        if not self._ckpt_path:
            return False
        from ..runtime import checkpoint

        ns: dict = {}
        try:
            checkpoint.restore(self._ckpt_path, ns,
                               ["params", "opt_state"], rank=self._rank)
        except Exception as e:
            flightrec.record("guard_restore_failed", reason=reason,
                             error=str(e)[:200])
            self._event("restore_failed", reason=reason,
                        error=str(e)[:200])
            return False
        self._params = ns["params"]
        self._opt_state = ns["opt_state"]
        self._m_repairs.inc()
        with self._lock:
            self._repairs += 1
            self._publish_locked()
        flightrec.record("guard_restore", reason=reason,
                         path=self._ckpt_path)
        self._event("restore", reason=reason)
        return True

    # -- replica-consistency audit --------------------------------------

    def _maybe_audit(self) -> None:
        if not self._audit_every:
            return
        with self._lock:
            i = self._i
        if i == 0 or i % self._audit_every:
            return
        self.audit()

    def audit(self) -> AuditVerdict:
        """Run one replica-consistency audit NOW.  Collective-aligned
        by construction: every rank reaches it at the same step index
        (the cadence is step-count-based and rollbacks never rewind
        the index), computes the verdict from identical all-gathered
        rows, and therefore issues identical repair collectives."""
        self._resolve_pending(drain=True)
        import numpy as np

        from ..parallel import collectives

        self._m_audits.inc()
        with self._lock:
            self._audits += 1
            i = self._i
        fa, fb = tree_fingerprint(self._params)
        world = collectives.world_size()
        if world == 1:
            verdict = AuditVerdict(ok=True, majority_rank=None,
                                   minority=())
            self._record_audit(i, verdict)
            return verdict
        import jax.numpy as jnp
        # Split each uint32 lane into two int32-safe half-words for
        # the gather: exact on every backend, no x64 flag needed.
        vec = jnp.asarray([fa >> 16, fa & 0xFFFF, fb >> 16, fb & 0xFFFF],
                          dtype=jnp.int32)
        rows = np.asarray(collectives.all_gather(vec))
        fps = [((int(r[0]) << 16) | int(r[1]),
                (int(r[2]) << 16) | int(r[3])) for r in rows]
        verdict = vote(fps)
        self._record_audit(i, verdict)
        if verdict.ok:
            return verdict
        self._m_mismatches.inc()
        with self._lock:
            self._mismatches += 1
            for r in verdict.minority:
                self._diverge[r] = self._diverge.get(r, 0) + 1
            suspects = tuple(sorted(
                r for r, c in self._diverge.items()
                if c >= self._quarantine_after > 0))
            self._suspects = suspects
            fresh = [r for r in suspects if r not in self._escalated]
            self._escalated.update(fresh)
            self._publish_locked()
        flightrec.record("guard_mismatch", step=i,
                         minority=list(verdict.minority),
                         majority_rank=verdict.majority_rank)
        self._event("mismatch", step=i,
                    minority=list(verdict.minority),
                    majority_rank=verdict.majority_rank)
        if verdict.majority_rank is not None:
            self._repair(verdict)
        else:
            self._restore_checkpoint(
                f"audit at step {i} found no majority fingerprint "
                f"({len(set(fps))} distinct across {world} ranks)")
        for r in fresh:
            flightrec.record("guard_quarantine_suspect", suspect=r,
                             diverges=self._diverge.get(r))
            self._event("quarantine_suspect", suspect=r)
            if self._escalate is not None:
                try:
                    self._escalate(r, f"rank {r} diverged in "
                                      f"{self._diverge.get(r)} audits")
                except Exception:
                    pass  # advisory: escalation must never break training
        return verdict

    def _record_audit(self, i: int, verdict: AuditVerdict) -> None:
        if verdict.ok:
            v = "ok"
        elif verdict.majority_rank is not None:
            v = "repair:" + ",".join(str(r) for r in verdict.minority)
        else:
            v = "no-majority"
        with self._lock:
            self._last_audit_step = i
            self._last_verdict = v
            self._publish_locked()
        flightrec.record("guard_audit", step=i, ok=verdict.ok,
                         verdict=v)
        self._event("audit", step=i, verdict=v)

    def _repair(self, verdict: AuditVerdict) -> None:
        import jax

        from ..parallel import collectives

        root = verdict.majority_rank

        def rebroadcast(tree):
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            fixed = [collectives.broadcast(l, root=root)
                     for l in leaves]
            return jax.tree_util.tree_unflatten(treedef, fixed)

        # Both trees: the minority rank's optimizer moments were built
        # from gradients of corrupted params — untrusted derived state
        # that would re-diverge the repaired params within steps.
        # (Caveat: the mask-and-sum broadcast canonicalizes -0.0 to
        # +0.0; negative zeros in live training state are effectively
        # nonexistent, and every rank receives the same bits either
        # way.)
        self._params = rebroadcast(self._params)
        self._opt_state = rebroadcast(self._opt_state)
        self._m_repairs.inc()
        with self._lock:
            self._repairs += 1
            self._skip_streak = 0
            self._publish_locked()
        flightrec.record("guard_repair", root=root,
                         minority=list(verdict.minority))
        self._event("repair", root=root,
                    minority=list(verdict.minority))

    # -- chaos -----------------------------------------------------------

    def _inject_corruption(self) -> None:
        plan = faults.process_plan()
        if plan is None or not plan.has_corrupt():
            return
        with self._lock:
            i = self._i
        for spec in plan.corrupt_due(self._rank, i):
            self._params, leaf = apply_corrupt(self._params, spec,
                                               plan.seed)
            plan.note_corrupt(spec, step=i, leaf=leaf)
            self._event("corrupt", step=i, leaf=leaf, mode=spec.mode)

    # -- reporting -------------------------------------------------------

    def _event(self, kind: str, **kw) -> None:
        with self._lock:
            self._events.append({"ts": self._clock(), "kind": kind,
                                 **kw})

    def _publish_locked(self) -> None:
        # Atomically-rebound snapshot for the heartbeat thread (the
        # `tg` ping field) — it never touches the containers above.
        snap = {"sk": self._skips, "as": self._last_audit_step,
                "v": self._last_verdict, "rb": self._rollbacks,
                "rp": self._repairs}
        if self._suspects:
            snap["qr"] = list(self._suspects)
        self._snap = snap

    def describe(self) -> dict:
        with self._lock:
            return {"step": self._i, "skips": self._skips,
                    "skip_streak": self._skip_streak,
                    "skip_budget": self._skip_budget,
                    "audits": self._audits,
                    "mismatches": self._mismatches,
                    "repairs": self._repairs,
                    "rollbacks": self._rollbacks,
                    "spikes": self._spikes,
                    "last_audit_step": self._last_audit_step,
                    "last_verdict": self._last_verdict,
                    "suspects": list(self._suspects),
                    "snapshot_steps": [s[0] for s in self._snapshots],
                    "events": list(self._events)[-8:]}


def guard_ddp(loss_fn, optimizer, mesh, params, opt_state, *,
              dp_axis: str = "dp", donate: bool = True,
              **guard_kw) -> TrainGuard:
    """Convenience: build a guarded DDP step and wrap it in a
    :class:`TrainGuard` in one call."""
    from ..parallel import data_parallel

    step = data_parallel.make_ddp_step(loss_fn, optimizer, mesh,
                                       dp_axis=dp_axis, donate=donate,
                                       guard=True)
    return TrainGuard(step, params, opt_state, **guard_kw)


# ----------------------------------------------------------------------
# process-level surface (worker heartbeat / %dist_guard)

_ACTIVE: TrainGuard | None = None
_ENABLED: bool | None = None


def is_enabled() -> bool:
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = knobs.get_bool("NBD_GUARD", True)
    return _ENABLED


def set_enabled(on: bool) -> None:
    """``%dist_guard on|off``: toggles the host-side machinery
    (verdict resolution, audits, snapshots, rollback, chaos
    injection).  The device-side finite gate is compiled into the
    step and stays."""
    global _ENABLED
    _ENABLED = bool(on)


def snapshot() -> dict | None:
    """Compact state for the heartbeat ``tg`` piggyback, or None when
    no guard is live in this process.  Reads one atomically-rebound
    dict — safe from any thread."""
    g = _ACTIVE
    return g._snap if g is not None else None


def status() -> dict:
    """Full status for the ``%dist_guard`` magic's worker handler."""
    g = _ACTIVE
    out: dict = {"enabled": is_enabled(), "active": g is not None}
    if g is not None:
        out.update(g.describe())
    return out


def reset_for_tests() -> None:
    global _ACTIVE, _ENABLED
    _ACTIVE = None
    _ENABLED = None
