"""Auto-heal supervisor: from manual ``%dist_heal`` to a control loop.

Consumes the two liveness signals the stack already produces —
``ProcessManager`` death callbacks (authoritative: the child exited)
and coordinator-side heartbeat freshness (``last_ping``/``last_seen``)
— and maintains a per-rank state machine:

    alive ⇄ degraded          (heartbeats stale / resumed — a slow or
                               wedged host, NOT grounds for restart)
    alive|degraded → dead     (process exit; only this triggers heal)
    dead → healing → alive    (auto-heal under the restart budget)

``jax.distributed`` worlds are fixed-membership — a dead rank cannot
rejoin a live coordination service — so healing is always a FULL
restart + state restore (replay the recorded ``%dist_init``, restore
the last checkpoint), never a single-rank rejoin.  The heal callback
is pluggable: the magic layer wires ``%dist_heal`` replay; tests wire
a direct cluster rebuild.

The restart budget (``max_restarts`` per ``restart_window_s``) caps
crash-loops: a world that keeps dying stops being restarted and the
transition log says so, instead of burning TPU quota respawning a
broken program forever.  Every transition lands in a bounded event log
surfaced by ``%dist_status``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from ..observability import flightrec
from .partition import PartitionSentry

ALIVE = "alive"
DEGRADED = "degraded"
DEAD = "dead"
HEALING = "healing"
# Host-level partition suspicion (ISSUE 6): every rank on one host
# went silent/dead together while the rest of the fleet is fine.  NOT
# grounds for healing until the partition grace expires — the far side
# is (probably) alive, orphaned, and holding state.
SUSPECT = "suspect-partition"
# Training-integrity quarantine suspicion (ISSUE 19): the rank's
# TrainGuard kept landing in the audit minority — its arithmetic is
# producing different bits than the rest of the replica set (SDC-class
# hardware suspicion).  ADVISORY and STICKY: the rank stays in the
# liveness state machine (it is alive and being repaired), the label
# rides %dist_status until a heal replaces the world — it never
# triggers healing by itself.
QUARANTINE = "quarantine-suspect"


@dataclass(frozen=True)
class SupervisorPolicy:
    degraded_after_s: float = 6.0     # 3 missed heartbeats
    poll_s: float = 0.5
    max_restarts: int = 3
    restart_window_s: float = 600.0
    auto_heal: bool = True
    # Assemble a postmortem bundle (observability/postmortem.py) for
    # newly-dead ranks BEFORE healing replaces the world — the heal is
    # what destroys the evidence a human would want afterwards.
    postmortem: bool = True
    # Partition grace (multi-host worlds): how long whole-host silence
    # is ridden out as a SUSPECTED partition before the host is
    # declared lost and healing proceeds.  None = NBD_PARTITION_GRACE_S
    # (default 30 s).  Must stay below the workers' orphan TTL, or a
    # healed link finds its orphans already self-terminated.
    partition_grace_s: float | None = None


class Supervisor:
    """One supervision loop over a (comm, pm) pair.

    ``heal()`` — required for auto-heal — must rebuild the world and
    restore state; it may return a fresh ``(comm, pm)`` pair (the
    usual case: healing replaces both) which the supervisor rebinds
    to.  It runs on the supervisor's own thread, never on the process
    monitor's callback thread.
    """

    def __init__(self, policy: SupervisorPolicy | None = None, *,
                 heal=None, clock=time.time):
        self.policy = policy or SupervisorPolicy()
        self._heal_fn = heal
        self._clock = clock
        self.events: deque[dict] = deque(maxlen=256)
        # Monotonic count of recorded events: the deque above is
        # bounded (display/debugging), so totals must not be derived
        # from its length (a crash-looping world would saturate at the
        # maxlen and report a frozen number).
        self.transitions = 0
        self.heals_done = 0
        self.heals_failed = 0
        # Newest postmortem bundle manifest captured by this
        # supervisor (None until a death is processed).
        self.last_postmortem: dict | None = None
        self._postmortem_pending: set[int] = set()
        self._state: dict[int, str] = {}
        # Advisory quarantine suspicions (ISSUE 19): rank → detail.
        # Parallel to the liveness states on purpose — a quarantined
        # rank is alive and supervised normally; this is a sticky
        # label, not a lifecycle stage.
        self._quarantined: dict[int, str] = {}
        self._sentry: PartitionSentry | None = None
        self._restarts: deque[float] = deque()
        self._comm = None
        self._pm = None
        self._pm_hooked: int | None = None  # id(pm) with our callback
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._pending_heal = False
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    # lifecycle

    def _hook_pm(self, pm) -> None:
        """Move the death callback to ``pm`` (lock held).  Detaching
        from the previous ProcessManager matters even though a healed
        world's old pm is dying anyway: a stopped-and-reattached cycle
        on the SAME pm must not accumulate callbacks to retired state."""
        if self._pm_hooked == id(pm):
            return
        old = self._pm
        if old is not None and self._pm_hooked == id(old):
            remove = getattr(old, "remove_death_callback", None)
            if remove is not None:
                remove(self._on_death)
        pm.add_death_callback(self._on_death)
        self._pm_hooked = id(pm)

    def attach(self, comm, pm) -> None:
        """Bind to a live cluster and start (or resume, after a
        ``stop()``) supervising.  Multi-host worlds (the process
        manager carries a rank→host map with ≥2 hosts) get a
        :class:`~.partition.PartitionSentry`: whole-host silence is a
        suspected partition, not N deaths."""
        hosts = dict(getattr(pm, "hosts", None) or {})
        with self._lock:
            self._hook_pm(pm)
            self._comm, self._pm = comm, pm
            self._state = {r: ALIVE for r in range(comm.num_workers)}
            self._quarantined = {}
            self._pending_heal = False
            self._sentry = PartitionSentry(
                hosts, local_host=getattr(comm, "local_host", "local"),
                grace_s=self.policy.partition_grace_s,
                source="supervisor", clock=self._clock)
            if not self._sentry.active:
                self._sentry = None
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._wake.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="nbd-supervisor",
                                            daemon=True)
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            pm = self._pm
            if pm is not None and self._pm_hooked == id(pm):
                remove = getattr(pm, "remove_death_callback", None)
                if remove is not None:
                    remove(self._on_death)
            self._pm_hooked = None
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)

    def on_own_thread(self) -> bool:
        return threading.current_thread() is self._thread

    # ------------------------------------------------------------------
    # inputs

    def _on_death(self, rank: int, rc: int | None) -> None:
        """ProcessManager monitor callback — must not block: record and
        wake the supervisor thread, which owns the (slow) heal."""
        with self._lock:
            if self._state.get(rank) in (DEAD, HEALING):
                return
            self._transition(rank, DEAD, f"process exit (code {rc})")
            self._pending_heal = True
            self._postmortem_pending.add(rank)
        self._wake.set()

    def _transition(self, rank, to: str, detail: str = "") -> None:
        # Callers hold the lock; re-acquiring the RLock here costs
        # nothing and keeps the method safe for the stray direct call.
        # The concurrency self-lint (analysis/concur.py) records this
        # as a reentrant self-edge in the lock-order graph — a plain
        # Lock here would fail CI as a self-deadlock.
        with self._lock:
            frm = self._state.get(rank)
            if frm == to:
                return
            if rank is not None:
                self._state[rank] = to
            self.transitions += 1
            self.events.append({"ts": self._clock(), "rank": rank,
                                "from": frm, "to": to, "detail": detail})
        # Mirror every transition into the crash-surviving flight ring:
        # the in-memory event deque dies with the coordinator process.
        flightrec.record("supervisor_transition", rank=rank,
                         frm=frm, to=to, detail=detail)

    def note_quarantine_suspect(self, rank: int, detail: str = "") -> None:
        """Mark ``rank`` as a training-integrity quarantine suspect
        (ISSUE 19).  Advisory + sticky + idempotent: recorded once in
        the event log / flight ring, surfaced by ``%dist_status``, and
        cleared only when a new world attaches or a heal replaces the
        fleet.  Never schedules a heal — a rank producing wrong bits
        is still a live rank, and the repair path (majority
        re-broadcast) already fixed its state; this is the operator
        signal to retire the hardware."""
        with self._lock:
            if rank in self._quarantined:
                return
            self._quarantined[rank] = detail
            self.transitions += 1
            self.events.append({"ts": self._clock(), "rank": rank,
                                "from": self._state.get(rank),
                                "to": QUARANTINE, "detail": detail})
        flightrec.record("supervisor_transition", rank=rank,
                         frm=None, to=QUARANTINE, detail=detail)

    # ------------------------------------------------------------------
    # loop

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.policy.poll_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._scan_staleness()
                self._scan_guard()
                self._scan_partitions()
                self._capture_postmortems()
                if self.policy.auto_heal and self._heal_needed():
                    self._heal_once()
            except Exception:
                # The supervision loop must survive its own bugs —
                # a dead supervisor is exactly the failure mode this
                # subsystem exists to prevent.
                import traceback
                traceback.print_exc()

    def _scan_staleness(self) -> None:
        with self._lock:
            comm = self._comm
            ranks = [r for r, s in self._state.items()
                     if s in (ALIVE, DEGRADED)]
        if comm is None:
            return
        now = self._clock()
        for rank in ranks:
            ping = comm.last_ping(rank)
            seen = comm.last_seen(rank)
            candidates = [t for t in ((ping[0] if ping else None), seen)
                          if t is not None]
            if not candidates:
                continue  # never heard from it; bring-up owns that
            age = now - max(candidates)
            with self._lock:
                st = self._state.get(rank)
                if age > self.policy.degraded_after_s and st == ALIVE:
                    self._transition(rank, DEGRADED,
                                     f"no heartbeat for {age:.1f}s")
                elif age <= self.policy.degraded_after_s \
                        and st == DEGRADED:
                    self._transition(rank, ALIVE, "heartbeat resumed")

    def _scan_guard(self) -> None:
        """Harvest training-integrity quarantine suspects from the
        heartbeat ``tg`` piggyback (ISSUE 19) — pings only, no status
        probe: a worker mid-cell still reports.  Any rank's guard may
        name any suspect (verdicts are computed identically on every
        rank), so the union over all pings is taken."""
        with self._lock:
            comm = self._comm
        if comm is None:
            return
        for r in range(comm.num_workers):
            ping = comm.last_ping(r)
            if not ping:
                continue
            tg = (ping[1] or {}).get("tg")
            if not isinstance(tg, dict):
                continue
            for suspect in tg.get("qr") or ():
                if isinstance(suspect, int):
                    self.note_quarantine_suspect(
                        suspect, f"rank {r}'s guard reports repeated "
                                 f"audit divergence (tg.qr)")

    # ------------------------------------------------------------------
    # partition suspicion (multi-host worlds)

    def _scan_partitions(self) -> None:
        """Feed the sentry one liveness snapshot and apply its
        transitions: whole-host silence → SUSPECT (heal deferred),
        recovery → ALIVE, grace expiry → DEAD + heal."""
        sentry = self._sentry
        if sentry is None:
            return
        with self._lock:
            comm = self._comm
            states = dict(self._state)
        if comm is None:
            return
        now = self._clock()
        silent: set[int] = set()
        fresh: set[int] = set()
        for r in range(comm.num_workers):
            ping = comm.last_ping(r)
            seen = comm.last_seen(r)
            ts = [t for t in ((ping[0] if ping else None), seen)
                  if t is not None]
            if not ts:
                continue  # never heard from; bring-up owns it
            if now - max(ts) <= self.policy.degraded_after_s:
                fresh.add(r)
            else:
                silent.add(r)
        dead = {r for r, s in states.items() if s == DEAD}
        events = sentry.observe(silent, dead, fresh, now=now)
        if not events:
            return
        with self._lock:
            for ev in events:
                if ev["event"] == "suspected":
                    for r in ev["ranks"]:
                        # Known process-deaths keep their DEAD state
                        # (that fact survives the suspicion); the heal
                        # deferral works off the sentry's host state,
                        # not the rank label.
                        if self._state.get(r) != DEAD:
                            self._transition(
                                r, SUSPECT,
                                f"host {ev['host']}: every rank silent "
                                f"at once — suspected partition; heal "
                                f"deferred {sentry.grace_s:.0f}s")
                elif ev["event"] == "healed":
                    for r in ev["ranks"]:
                        st = self._state.get(r)
                        # A DEAD rank only resurrects if IT was heard
                        # from: one sibling's ping proves the LINK is
                        # back, not that a rank whose process exited
                        # mid-partition lives — resurrecting it here
                        # would clear the pending heal and leave the
                        # fleet permanently short.
                        if st in (SUSPECT, DEGRADED) \
                                or (st == DEAD and r in fresh):
                            self._transition(
                                r, ALIVE,
                                f"host {ev['host']}: partition healed "
                                f"— rank heard from again")
                elif ev["event"] == "expired":
                    for r in ev["ranks"]:
                        self._transition(
                            r, DEAD,
                            f"host {ev['host']}: partition grace "
                            f"expired — treating host as lost")
                        self._postmortem_pending.add(r)
                    self._pending_heal = True
        self._wake.set()

    def _heal_needed(self) -> bool:
        """Is a heal both pending and currently allowed?  Deferred
        while every unhealthy rank sits behind a link the sentry still
        suspects (the far side is riding its orphan grace); cleared
        entirely when the world recovered on its own (a healed
        partition must not trigger a respawn of a healthy fleet)."""
        with self._lock:
            if not self._pending_heal:
                return False
            dead = [r for r, s in self._state.items() if s == DEAD]
            unhealthy = {r for r, s in self._state.items()
                         if s in (DEAD, SUSPECT)}
            if not dead and not unhealthy:
                self._pending_heal = False
                return False
            if not dead:
                # Only SUSPECT ranks remain: the sentry owns them.
                return False
        sentry = self._sentry
        if sentry is not None and unhealthy and \
                unhealthy <= sentry.suspected_ranks():
            return False
        return True

    # ------------------------------------------------------------------
    # postmortems

    def _capture_postmortems(self) -> None:
        """Bundle the newly-dead ranks' black boxes on the supervisor's
        own thread, BEFORE any heal replaces the world.  Best-effort by
        contract: a full postmortem disk must never block recovery."""
        with self._lock:
            dead = sorted(self._postmortem_pending)
            self._postmortem_pending.clear()
            comm = self._comm
        if not dead or not self.policy.postmortem or comm is None:
            return
        try:
            from ..observability import postmortem as pm_mod
            manifest = pm_mod.capture(
                comm, dead, reason=f"supervisor: ranks {dead} died")
        except Exception:
            manifest = None
        if manifest is not None:
            self.last_postmortem = manifest
            with self._lock:
                self.transitions += 1
                self.events.append({
                    "ts": self._clock(), "rank": None,
                    "from": DEAD, "to": DEAD,
                    "detail": f"postmortem → {manifest['dir']}"})

    # ------------------------------------------------------------------
    # healing

    def _heal_once(self) -> None:
        with self._lock:
            self._pending_heal = False
            now = self._clock()
            while (self._restarts and now - self._restarts[0]
                    > self.policy.restart_window_s):
                self._restarts.popleft()
            if len(self._restarts) >= self.policy.max_restarts:
                self.transitions += 1
                self.events.append({
                    "ts": now, "rank": None, "from": DEAD, "to": DEAD,
                    "detail": (f"restart budget exhausted "
                               f"({self.policy.max_restarts} per "
                               f"{self.policy.restart_window_s:.0f}s); "
                               f"manual %dist_heal required")})
                return
            self._restarts.append(now)
            dead = sorted(r for r, s in self._state.items() if s == DEAD)
            for r in list(self._state):
                self._transition(r, HEALING,
                                 f"auto-heal (dead ranks {dead})")
        heal = self._heal_fn
        try:
            result = heal() if heal is not None else None
        except Exception as e:
            with self._lock:
                self.heals_failed += 1
                for r in list(self._state):
                    self._transition(r, DEAD, f"heal failed: {e}")
                # Transient respawn failures (port in TIME_WAIT, slow
                # attach) must not silently end supervision: retry on
                # the next poll, bounded by the restart budget — each
                # attempt consumed a slot, so a genuinely broken world
                # stops at "budget exhausted", not in a tight loop.
                self._pending_heal = True
            return
        if self._stop.is_set():
            # stop() raced the (slow) respawn: the heal callback may
            # have brought a world up that nobody is supervising now.
            # Don't rebind — surface it so the operator can decide.
            with self._lock:
                self.transitions += 1
                self.events.append({
                    "ts": self._clock(), "rank": None,
                    "from": HEALING, "to": ALIVE,
                    "detail": "heal completed AFTER supervisor stop — "
                              "the respawned world is unsupervised; "
                              "shut it down manually if unwanted"})
            return
        with self._lock:
            if result is not None:
                comm, pm = result
                self._hook_pm(pm)
                self._comm, self._pm = comm, pm
                self._state = {r: HEALING
                               for r in range(comm.num_workers)}
            for r in list(self._state):
                self._transition(r, ALIVE, "healed")
            # A heal replaces the processes (and their state was
            # restored from a good checkpoint): stale quarantine
            # suspicions would smear the fresh world.
            self._quarantined = {}
            self.heals_done += 1
            comm, pm = self._comm, self._pm
        # Durable-session manifest upkeep: the healed fleet's pids and
        # endpoint must replace the dead ones, or a later %dist_attach
        # would adopt corpses.  (The magic-layer heal path rewrites the
        # manifest through %dist_init anyway; this covers direct
        # Supervisor embeddings.)  Best-effort by contract.
        if comm is not None and pm is not None:
            try:
                from . import session as session_mod
                session_mod.refresh_after_heal(comm, pm)
            except Exception:
                pass

    # ------------------------------------------------------------------
    # reporting

    def healthy(self) -> bool:
        with self._lock:
            return (bool(self._state)
                    and all(s == ALIVE for s in self._state.values()))

    def status(self) -> dict:
        sentry = self._sentry
        with self._lock:
            return {"states": dict(self._state),
                    "restarts_used": len(self._restarts),
                    "max_restarts": self.policy.max_restarts,
                    "auto_heal": self.policy.auto_heal,
                    "heals_done": self.heals_done,
                    "heals_failed": self.heals_failed,
                    "transitions": self.transitions,
                    "suspected_hosts": (sentry.suspected_hosts()
                                        if sentry is not None else {}),
                    "quarantined": dict(self._quarantined),
                    "last_postmortem": (self.last_postmortem or {})
                    .get("dir"),
                    "events": list(self.events)}

    def describe(self) -> str:
        """Human-readable block for ``%dist_status``."""
        st = self.status()
        icon = {ALIVE: "●", DEGRADED: "◐", DEAD: "✖", HEALING: "🩹",
                SUSPECT: "⚡", QUARANTINE: "🔶"}
        quarantined = st["quarantined"]
        ranks = " ".join(
            ("🔶" if r in quarantined else "") +
            f"{icon.get(s, '?')}{r}:{s}"
            for r, s in sorted(st["states"].items()))
        lines = [f"🛡  supervisor: {ranks or '(no ranks)'} · "
                 f"restarts {st['restarts_used']}/{st['max_restarts']} "
                 f"in window · heals {st['heals_done']} ok"
                 + (f", {st['heals_failed']} failed"
                    if st["heals_failed"] else "")
                 + ("" if st["auto_heal"] else " · auto-heal OFF")]
        if self._sentry is not None:
            note = self._sentry.describe()
            if note:
                lines.append(f"   {note}")
        if quarantined:
            lines.append("   🔶 quarantine suspects: " + ", ".join(
                f"rank {r} ({d})" if d else f"rank {r}"
                for r, d in sorted(quarantined.items())))
        for ev in list(st["events"])[-5:]:
            rank = "world" if ev["rank"] is None else f"rank {ev['rank']}"
            lines.append(f"   {time.strftime('%H:%M:%S', time.localtime(ev['ts']))} "
                         f"{rank}: {ev['from']} → {ev['to']}"
                         + (f" ({ev['detail']})" if ev["detail"] else ""))
        return "\n".join(lines)
