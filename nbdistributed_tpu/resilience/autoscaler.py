"""Pressure-driven pool autoscaling policy (ISSUE 16).

``PoolAutoscaler`` is the decision half of elastic pools: it watches
the three load signals that already exist — scheduler queue depth,
serving backlog, and the latency observatory's queue-stage p95 — and
answers "should the world grow or shrink, and to what size".  It is a
pure fake-clock state machine in the ``SkewDetector`` mold: no
threads, no IO, no ``time.time()`` — the gateway daemon feeds it
snapshots on its own cadence and executes whatever it decides through
the resize path (drain barrier + epoch bump + respawn).

Flap resistance is structural, not tuned: pressure must be *sustained*
for ``sustain_s`` before a grow (a single spike resets the clock when
it clears), idleness must be sustained for ``idle_s`` before a shrink,
and every executed resize opens a ``cooldown_s`` window during which
no new decision fires.  Min/max clamping is absolute — a world outside
the band is pulled back in without waiting for sustain.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from ..utils import knobs


@dataclass
class AutoscalePolicy:
    """Thresholds; defaults from the ``NBD_AUTOSCALE_*`` knobs."""
    min_workers: int = 1
    max_workers: int = 8
    interval_s: float = 5.0      # daemon poll cadence (not used here)
    up_queue: int = 4            # queued cells that count as pressure
    up_backlog: int = 8          # pending serve requests ditto
    up_p95_s: float = 2.0        # queue-stage p95 ditto
    sustain_s: float = 15.0      # pressure persistence before a grow
    idle_s: float = 120.0        # idle persistence before a shrink
    cooldown_s: float = 60.0     # post-resize decision blackout

    @classmethod
    def from_env(cls, env=None) -> "AutoscalePolicy":
        return cls(
            min_workers=knobs.get_int("NBD_AUTOSCALE_MIN", 1, env=env),
            max_workers=knobs.get_int("NBD_AUTOSCALE_MAX", 8, env=env),
            interval_s=knobs.get_float("NBD_AUTOSCALE_INTERVAL_S", 5.0,
                                       env=env),
            up_queue=knobs.get_int("NBD_AUTOSCALE_UP_QUEUE", 4,
                                   env=env),
            up_backlog=knobs.get_int("NBD_AUTOSCALE_UP_BACKLOG", 8,
                                     env=env),
            up_p95_s=knobs.get_float("NBD_AUTOSCALE_UP_P95_S", 2.0,
                                     env=env),
            sustain_s=knobs.get_float("NBD_AUTOSCALE_SUSTAIN_S", 15.0,
                                      env=env),
            idle_s=knobs.get_float("NBD_AUTOSCALE_IDLE_S", 120.0,
                                   env=env),
            cooldown_s=knobs.get_float("NBD_AUTOSCALE_COOLDOWN_S",
                                       60.0, env=env),
        )

    def describe(self) -> str:
        return (f"band {self.min_workers}:{self.max_workers} · "
                f"grow on queue>{self.up_queue} | "
                f"backlog>{self.up_backlog} | "
                f"queue-p95>{self.up_p95_s:.1f}s sustained "
                f"{self.sustain_s:.0f}s · shrink after "
                f"{self.idle_s:.0f}s idle · cooldown "
                f"{self.cooldown_s:.0f}s")


@dataclass
class Decision:
    action: str        # "grow" | "shrink"
    target: int        # new world size
    reason: str        # human-readable signal, flight-recorded
    # The full structured audit record behind this verdict (ISSUE 18):
    # pressure inputs, sustain/cooldown state, clamp flag — what the
    # daemon flight-records and postmortem bundles carry.
    record: dict | None = field(default=None, compare=False)


class PoolAutoscaler:
    """Pure decision loop: ``observe(now, ...)`` consumes one load
    snapshot and returns a :class:`Decision` or None.  The caller
    (the daemon's autoscale thread) reports execution back through
    :meth:`note_resized` — failed resizes too, so a wedged grow can't
    be retried at poll frequency."""

    def __init__(self, policy: AutoscalePolicy | None = None):
        self.policy = policy or AutoscalePolicy()
        self._pressure_since: float | None = None
        self._idle_since: float | None = None
        self._cooldown_until: float = 0.0
        self.decisions_total = 0
        # Audit trail (ISSUE 18): one structured record per observe()
        # call — inputs, pressure signals, sustain/cooldown state,
        # verdict — rendered by ``%dist_pool status --autoscale`` and
        # carried into postmortem bundles via the daemon's flight
        # records.  Same thread discipline as the rest of the state
        # machine: the daemon's autoscale thread is the only writer.
        self._decisions: deque = deque(maxlen=128)

    def decisions(self, last: int | None = None) -> list[dict]:
        """Recent audit records, oldest first."""
        recs = list(self._decisions)
        return recs[-last:] if last else recs

    def note_resized(self, now: float) -> None:
        """A resize just executed (or failed): open the cooldown and
        drop the persistence clocks — the new world starts clean."""
        self._cooldown_until = now + self.policy.cooldown_s
        self._pressure_since = None
        self._idle_since = None

    # ------------------------------------------------------------------

    def observe(self, now: float, *, world_size: int, queued: int = 0,
                active: int = 0, backlog: int = 0,
                queue_p95_s: float = 0.0) -> Decision | None:
        pol = self.policy
        # Audit record (ISSUE 18): every observation leaves one —
        # verdict or hold — naming the inputs and clock state that
        # drove it, so a resize (or its absence) is explainable after
        # the fact.
        rec = {
            "ts": round(now, 3),
            "world": int(world_size),
            "inputs": {"queued": int(queued), "active": int(active),
                       "backlog": int(backlog),
                       "queue_p95_s": round(float(queue_p95_s), 3)},
            "pressure": [],
            "sustain_s": 0.0,
            "idle_for_s": 0.0,
            "cooldown_s": round(max(0.0, self._cooldown_until - now),
                                1),
            "verdict": "hold", "target": None, "reason": None,
            "clamp": False,
        }

        def _audit(d: Decision | None,
                   clamp: bool = False) -> Decision | None:
            if d is not None:
                self.decisions_total += 1
                rec["verdict"] = d.action
                rec["target"] = d.target
                rec["reason"] = d.reason
                rec["clamp"] = clamp
                d.record = rec
            self._decisions.append(rec)
            return d

        # Band clamping is unconditional: a world outside min:max is
        # wrong regardless of load and regardless of cooldown (the arm
        # moment itself may find a too-small pool).
        if world_size < pol.min_workers:
            return _audit(Decision("grow", pol.min_workers,
                                   f"world {world_size} below min "
                                   f"{pol.min_workers}"), clamp=True)
        if world_size > pol.max_workers:
            return _audit(Decision("shrink", pol.max_workers,
                                   f"world {world_size} above max "
                                   f"{pol.max_workers}"), clamp=True)

        if now < self._cooldown_until:
            # Blackout: no decision, AND no clock arming — load seen
            # during the cooldown is tainted by the resize itself (the
            # drain barrier accumulates queue by design), so pressure
            # must re-sustain against the new world.
            rec["reason"] = "cooldown"
            return _audit(None)

        pressure = rec["pressure"]
        if pol.up_queue and queued > pol.up_queue:
            pressure.append(f"queue {queued}>{pol.up_queue}")
        if pol.up_backlog and backlog > pol.up_backlog:
            pressure.append(f"backlog {backlog}>{pol.up_backlog}")
        if pol.up_p95_s and queue_p95_s > pol.up_p95_s:
            pressure.append(f"queue-p95 {queue_p95_s:.2f}s"
                            f">{pol.up_p95_s:.1f}s")
        idle = not pressure and queued == 0 and active == 0 \
            and backlog == 0

        # Persistence clocks: a signal that clears resets its clock —
        # that is the whole no-flap-on-a-spike story.
        if pressure:
            if self._pressure_since is None:
                self._pressure_since = now
        else:
            self._pressure_since = None
        if idle:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        if self._pressure_since is not None:
            rec["sustain_s"] = round(now - self._pressure_since, 1)
        if self._idle_since is not None:
            rec["idle_for_s"] = round(now - self._idle_since, 1)

        if (pressure and self._pressure_since is not None
                and now - self._pressure_since >= pol.sustain_s
                and world_size < pol.max_workers):
            target = min(pol.max_workers, max(world_size + 1,
                                              world_size * 2))
            return _audit(Decision(
                "grow", target,
                f"{', '.join(pressure)} sustained "
                f"{now - self._pressure_since:.0f}s"))

        if (idle and self._idle_since is not None
                and now - self._idle_since >= pol.idle_s
                and world_size > pol.min_workers):
            target = max(pol.min_workers, world_size // 2)
            return _audit(Decision(
                "shrink", target,
                f"idle {now - self._idle_since:.0f}s"))
        return _audit(None)
