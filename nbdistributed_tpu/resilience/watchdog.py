"""Collective hang watchdog + stuck-cell doctor (ISSUE 5).

The failure model so far has a blind spot between "alive" and "dead":
heartbeats prove the *process* lives, ``WorkerDied`` fires only on
death, and the collective-hazard guard catches subset cells *before*
launch — but a rank wedged *inside* an eager collective, a
data-dependent infinite loop, or a straggler far behind its peers
hangs the mesh silently until a human notices.  At pod scale this is
the dominant failure mode ("Exploring the limits of Concurrency in ML
Training on Google TPUs", arXiv:2011.03641; the Podracer
architectures, arXiv:2104.06272 — both treat straggler/stall
detection as a precondition for running fleets unattended).  The
reference's only remedy for a stuck cell is cluster destruction.

Three cooperating pieces, the NCCL-flight-recorder analog for this
stack:

- **Progress** (worker side): ``runtime/collective_guard.py`` keeps a
  monotonic per-process collective sequence — ``(seq, op,
  entered-at, in-flight)`` — and the heartbeat thread piggybacks it
  (plus the in-flight request id and optional per-cell deadline) on
  every ping, so the coordinator sees each rank's position in the
  collective stream *mid-cell*, through the one channel that does not
  go through the worker's serial request loop.

- **Detection** (this module): :class:`SkewDetector` is a pure state
  machine over those positions.  Three verdict kinds, all distinct
  from "slow":

  * ``skew`` — cross-rank divergence on the same cell: peers entered
    collective #N (or already finished the cell) while a rank sits
    below #N with no progress for ``skew_s``.  The signature case —
    "ranks 0–2 entered ``all_reduce`` #7, rank 3 never did".
  * ``stall`` — a rank busy beyond ``stall_s`` with zero collective
    progress (the pure-Python infinite loop; also a collective ALL
    ranks entered that never completes).
  * ``deadline`` — the cell carried its own budget
    (``%%distributed --deadline S``) and blew it.

  A uniformly-slow cell — every rank advancing through the same
  sequence together, or every rank inside the same collective under
  ``stall_s`` — produces **no** verdict: progress resets the timers,
  and equal positions are not skew.

- **Escalation + diagnosis**: :class:`HangWatchdog` runs the detector
  on a coordinator thread and walks a configurable ladder per hung
  cell — ``warn`` (print + flight + metric) → ``dump`` (SIGUSR1 →
  per-rank faulthandler stack files under ``NBD_RUN_DIR``) →
  ``interrupt`` (SIGINT via the existing InterruptGate discipline:
  the cell aborts with a KeyboardInterrupt error reply, the worker
  survives) → ``heal`` (the supervisor's full respawn+restore).
  Every step is flight-recorded and counted.  :func:`hang_report`
  assembles the ``%dist_doctor`` bundle: per-rank collective
  positions, the skew table, busy ages, freshly-dumped stacks, and
  each ring's last flight events — naming the lagging rank(s) and
  the divergence point.

Policy comes from ``NBD_HANG_*`` env knobs (overridable by
``%dist_watchdog``)::

    NBD_HANG=0              master off switch (workers skip the
                            heartbeat piggyback; one flag check)
    NBD_HANG_POLL_S=1.0     watchdog poll cadence
    NBD_HANG_SKEW_S=20      lag persistence before a skew verdict
    NBD_HANG_STALL_S=120    busy-with-no-progress before a stall
    NBD_HANG_ESCALATE=warn,dump      the ladder (also: interrupt,heal)
    NBD_HANG_GRACE_S=15     pause between ladder steps

Stdlib-only (no JAX import), like the rest of this package.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass

from ..observability import flightrec
from ..observability import metrics as obs_metrics
from ..utils import knobs
from .partition import PartitionSentry

LADDER_STEPS = ("warn", "dump", "interrupt", "heal")


def _preflight_note(cell_sha1: str | None) -> dict | None:
    """The pre-dispatch lint finding recorded for this cell's source
    hash, if the analyzer flagged it (analysis/preflight) — a hang
    verdict landing on a flagged cell cites it, closing the loop
    between the static warning and the runtime failure."""
    if not cell_sha1:
        return None
    try:
        from ..analysis import preflight
        return preflight.lookup(cell_sha1)
    except Exception:
        return None


def parse_ladder(raw: str) -> tuple[str, ...]:
    """Parse a comma-separated escalation ladder; unknown step names
    are an error (a typo'd ladder must not silently never escalate —
    the FaultPlan unknown-key philosophy)."""
    steps = tuple(s.strip() for s in raw.split(",") if s.strip())
    unknown = [s for s in steps if s not in LADDER_STEPS]
    if unknown:
        raise ValueError(f"unknown escalation step(s) {unknown} "
                         f"(known: {list(LADDER_STEPS)})")
    return steps


@dataclass(frozen=True)
class HangPolicy:
    enabled: bool = True
    poll_s: float = 1.0
    skew_s: float = 20.0
    stall_s: float = 120.0
    grace_s: float = 15.0
    escalate: tuple = ("warn", "dump")
    # Pings older than this carry FROZEN busy state, not live state:
    # judging them would extrapolate busy_s without bound and flag a
    # silent-but-finished rank as stalled.  A silent rank is the
    # supervisor's degraded/dead domain, never a hang verdict.  (A
    # genuinely wedged rank keeps heartbeating — the ping thread is
    # separate — so the hang path is unaffected.)  4× the worker's
    # 2 s heartbeat cadence.
    hb_stale_s: float = 8.0

    def __post_init__(self):
        unknown = [s for s in self.escalate if s not in LADDER_STEPS]
        if unknown:
            raise ValueError(f"unknown escalation step(s) {unknown} "
                             f"(known: {list(LADDER_STEPS)})")

    @classmethod
    def from_env(cls, env=None) -> "HangPolicy":
        kw: dict = {
            "enabled": knobs.get_bool("NBD_HANG", True, env=env),
            "poll_s": knobs.get_float("NBD_HANG_POLL_S", cls.poll_s,
                                      env=env),
            "skew_s": knobs.get_float("NBD_HANG_SKEW_S", cls.skew_s,
                                      env=env),
            "stall_s": knobs.get_float("NBD_HANG_STALL_S", cls.stall_s,
                                       env=env),
            "grace_s": knobs.get_float("NBD_HANG_GRACE_S", cls.grace_s,
                                       env=env),
        }
        raw = knobs.get_str("NBD_HANG_ESCALATE", env=env)
        if raw:
            kw["escalate"] = parse_ladder(raw)
        return cls(**kw)

    @classmethod
    def from_env_lenient(cls, env=None) -> "HangPolicy":
        """:meth:`from_env`, but a malformed ``NBD_HANG_ESCALATE``
        degrades to the default ladder (numeric knobs still honored)
        instead of raising — for surfaces that must keep working when
        the env is the very problem being diagnosed (``%dist_status``,
        the doctor, ``%dist_watchdog on`` recovering from the typo).
        Auto-arming stays strict so the typo is reported once, at
        ``%dist_init``."""
        try:
            return cls.from_env(env)
        except ValueError:
            env2 = dict(os.environ if env is None else env)
            env2.pop("NBD_HANG_ESCALATE", None)
            return cls.from_env(env2)

    def describe(self) -> str:
        return (f"skew {self.skew_s:.0f}s · stall {self.stall_s:.0f}s "
                f"· poll {self.poll_s:.1f}s · ladder "
                f"{'→'.join(self.escalate) or '(none)'} "
                f"(grace {self.grace_s:.0f}s)")


# ----------------------------------------------------------------------
# detection


class SkewDetector:
    """Pure hang-detection state machine over per-rank views.

    ``observe(now, ranks, pending)`` consumes one snapshot and returns
    the verdicts active *right now* (empty list = healthy).  A rank
    view is the heartbeat piggyback, coordinator-adjusted::

        {"busy_id":  in-flight request id (None when idle),
         "busy_type": message type, "busy_s": seconds busy,
         "deadline": per-cell budget seconds or None,
         "seq": collective sequence number (0 = none yet),
         "op": last collective op entered, "in": still inside it,
         "cops": collectives this cell has made so far,
         "rep": step index of an in-flight --repeat loop (None
                otherwise; advancing steps count as progress),
         "hb_age": seconds since the last ping}

    ``pending`` is ``CommunicationManager.pending_snapshot()`` —
    which ranks already responded to the cell is the straggler
    evidence.  State is only per-rank progress timestamps, so the
    detector is trivially unit-testable with synthetic sequences and
    a fake clock.
    """

    def __init__(self, policy: HangPolicy | None = None):
        self.policy = policy or HangPolicy()
        # rank -> ((busy_id, seq, in_flight), since): the "no progress"
        # clock.  Any change — a new collective entered, a collective
        # completed, a different cell, going idle — resets it.
        self._prog: dict[int, tuple] = {}
        # (cell, rank) -> since: how long the rank has LOOKED lagging
        # (behind busy peers / wedged while peers responded).  A skew
        # verdict requires this divergence itself to persist for
        # skew_s, not just the rank's no-progress clock: heartbeats
        # propagate positions with up to a ping-interval of lag, so a
        # healthy lockstep cell with long inter-collective gaps shows
        # a one-poll phantom divergence while the slower ping is in
        # flight — phantoms clear on the next ping, real lag does not.
        self._lag: dict[tuple, float] = {}

    def reset(self) -> None:
        self._prog.clear()
        self._lag.clear()

    # ------------------------------------------------------------------

    def observe(self, now: float, ranks: dict, pending: dict | None = None
                ) -> list[dict]:
        pol = self.policy
        pending = pending or {}
        for r, v in ranks.items():
            # The "no progress" key: any change — a new collective, a
            # collective completed, a different cell, going idle, or a
            # --repeat loop advancing a step (ISSUE 14) — resets the
            # stall clock.
            key = (v.get("busy_id"), v.get("seq"), v.get("in"),
                   v.get("rep"))
            prev = self._prog.get(r)
            if prev is None or prev[0] != key:
                self._prog[r] = (key, now)
        verdicts: list[dict] = []
        flagged: set = set()

        # Group busy ranks by the cell they are executing.  A busy rank
        # without a busy_id (pre-hang-protocol worker) gets a per-rank
        # pseudo-cell: no skew grouping, but stall/deadline still work.
        cells: dict = {}
        for r, v in ranks.items():
            if v.get("busy_s") is None:
                continue
            mid = v.get("busy_id") or f"?cell-rank{r}"
            cells.setdefault(mid, []).append(r)
        # Divergence clocks for finished cells are dead state.
        for key in [k for k in self._lag if k[0] not in cells]:
            del self._lag[key]

        # --- skew: divergence inside one cell -------------------------
        for mid, members in sorted(cells.items()):
            pend = pending.get(mid) or {}
            responded = sorted(pend.get("responded") or ())
            seqs = {r: int(ranks[r].get("seq") or 0) for r in members}
            # Compare CELL-LOCAL positions (collectives entered this
            # cell), not the process-lifetime sequence: lifetime seqs
            # diverge permanently and harmlessly — a hazard-raising
            # subset collective advances only the caller, a broken
            # hang leaves the laggard one behind forever — and
            # comparing them would flag every later slow-but-healthy
            # cell as skewed.  Cells are SPMD (same code on every
            # rank), so equal cell positions = in step.
            pos = {r: int(ranks[r].get("cops") or 0) for r in members}
            maxpos = max(pos.values())
            lagging, waited = [], 0.0
            for r in sorted(members):
                behind = pos[r] < maxpos
                # Straggler: peers FINISHED the cell while this rank is
                # still INSIDE a collective — wedged where nobody will
                # ever join it.  ``in`` is required: a rank merely
                # doing long rank-local work after its collectives
                # (peers responded, cops == maxpos, not inside) is
                # healthy asymmetry, not skew — if it is genuinely
                # stuck, the stall detector owns it.
                straggler = bool(responded) and bool(ranks[r].get("in"))
                key = (mid, r)
                if not (behind or straggler):
                    self._lag.pop(key, None)
                    continue
                lag_since = self._lag.setdefault(key, now)
                stale_s = now - self._prog[r][1]
                # BOTH clocks must blow the window: the rank made no
                # progress for skew_s AND has looked lagging that long
                # (see _lag above for why divergence-age matters).
                if stale_s < pol.skew_s or now - lag_since < pol.skew_s:
                    continue
                lagging.append(r)
                waited = max(waited, stale_s)
            if not lagging:
                continue
            flagged.add(mid)
            if any(pos[r] < maxpos for r in lagging):
                ahead_members = [r for r in members if pos[r] == maxpos]
                div_seq = max(seqs[r] for r in ahead_members)
                div_op = ranks[ahead_members[0]].get("op")
                ahead = sorted(set(responded) | set(ahead_members))
                detail = (f"ranks {ahead} entered {div_op or '?'} "
                          f"#{div_seq} but rank(s) "
                          f"{sorted(lagging)} never did "
                          f"(stuck at #{min(seqs[r] for r in lagging)}"
                          f" for {waited:.1f}s)")
            else:
                l0 = lagging[0]
                div_seq = seqs[l0]
                div_op = ranks[l0].get("op")
                ahead = responded
                where = (f"stuck inside {div_op or '?'} #{div_seq}"
                         if ranks[l0].get("in") else
                         f"no collective progress since "
                         f"{div_op or '?'} #{div_seq}")
                detail = (f"ranks {ahead} finished the cell but "
                          f"rank(s) {sorted(lagging)} are {where} "
                          f"({waited:.1f}s)")
            verdicts.append({"kind": "skew", "cell": mid,
                             "ranks": sorted(lagging), "peers": ahead,
                             "seq": div_seq, "op": div_op,
                             "waited_s": round(waited, 1),
                             "detail": detail})

        # --- stall: busy beyond the window with zero progress ---------
        stall_cells: dict = {}
        for r, v in ranks.items():
            if v.get("busy_s") is None:
                continue
            mid = v.get("busy_id") or f"?cell-rank{r}"
            if mid in flagged:
                continue
            stale_s = now - self._prog[r][1]
            if v["busy_s"] > pol.stall_s and stale_s > pol.stall_s:
                stall_cells.setdefault(mid, []).append(r)
        for mid, rs in sorted(stall_cells.items()):
            flagged.add(mid)
            v0 = ranks[rs[0]]
            busy = max(ranks[r].get("busy_s") or 0 for r in rs)
            col = (f" (last collective {v0.get('op')} "
                   f"#{v0.get('seq')})" if v0.get("seq") else
                   " (no collectives this cell)")
            verdicts.append({
                "kind": "stall", "cell": mid, "ranks": sorted(rs),
                "peers": sorted(pending.get(mid, {})
                                .get("responded") or ()),
                "seq": v0.get("seq"), "op": v0.get("op"),
                "waited_s": round(busy, 1),
                "detail": (f"rank(s) {sorted(rs)} busy "
                           f"{busy:.1f}s with no collective "
                           f"progress{col} — beyond the "
                           f"{pol.stall_s:.0f}s stall window")})

        # --- deadline: the cell blew its own budget -------------------
        dl_cells: dict = {}
        for r, v in ranks.items():
            dl = v.get("deadline")
            if not dl or v.get("busy_s") is None:
                continue
            mid = v.get("busy_id") or f"?cell-rank{r}"
            if mid in flagged:
                continue
            if v["busy_s"] > dl:
                dl_cells.setdefault(mid, []).append(r)
        for mid, rs in sorted(dl_cells.items()):
            busy = max(ranks[r].get("busy_s") or 0 for r in rs)
            dl = max(ranks[r].get("deadline") or 0 for r in rs)
            verdicts.append({
                "kind": "deadline", "cell": mid, "ranks": sorted(rs),
                "peers": sorted(pending.get(mid, {})
                                .get("responded") or ()),
                "seq": ranks[rs[0]].get("seq"),
                "op": ranks[rs[0]].get("op"),
                "waited_s": round(busy, 1),
                "detail": (f"rank(s) {sorted(rs)} busy {busy:.1f}s — "
                           f"past the cell's --deadline "
                           f"{dl:.0f}s budget")})
        return verdicts


# ----------------------------------------------------------------------
# the watchdog thread


class HangWatchdog:
    """Coordinator-side hang watchdog: polls heartbeat piggybacks,
    runs the :class:`SkewDetector`, and walks the escalation ladder
    per hung cell.  Lifecycle mirrors the Supervisor: ``attach(comm,
    pm)`` starts (or re-binds) the thread, ``stop()`` ends it; the
    ``heal`` callable — optional, wired by the magics to the
    supervisor/%dist_heal machinery — may return a fresh ``(comm,
    pm)`` pair to re-bind to."""

    def __init__(self, policy: HangPolicy | None = None, *,
                 heal=None, clock=time.time):
        self.policy = policy or HangPolicy()
        self._heal_fn = heal
        self._clock = clock
        self.detector = SkewDetector(self.policy)
        self.events: deque[dict] = deque(maxlen=256)
        # Monotonic totals (the deque is bounded — display only).
        self.verdicts_total = 0
        self.cells_flagged = 0
        self.cells_resolved = 0
        self.escalations: dict[str, int] = {}
        self.last_verdicts: list[dict] = []
        self._hangs: dict = {}  # cell -> {"step","next_ts","first_ts","verdict"}
        self._sentry: PartitionSentry | None = None
        self._comm = None
        self._pm = None
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    # lifecycle

    def attach(self, comm, pm=None) -> None:
        hosts = dict(getattr(pm, "hosts", None) or {})
        with self._lock:
            self._comm, self._pm = comm, pm
            self._hangs.clear()
            self.detector.reset()
            self.last_verdicts = []
            # Host-level failure domains (ISSUE 6): whole-host
            # heartbeat loss is a suspected partition — those ranks'
            # silence is the supervisor's problem (and their apparent
            # lag frozen data), never grounds for a hang verdict.
            self._sentry = PartitionSentry(
                hosts, local_host=getattr(comm, "local_host", "local"),
                source="watchdog", clock=self._clock)
            if not self._sentry.active:
                self._sentry = None
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="nbd-hang-watchdog",
                                            daemon=True)
            self._thread.start()

    def set_policy(self, policy: HangPolicy) -> None:
        """Reconfigure IN PLACE: active-hang ladder progress, counters,
        and event history survive a policy change (stopping and
        replacing the watchdog mid-hang would re-run ladder steps
        already taken).  The loop reads ``policy.poll_s`` each
        iteration, so the new cadence applies from the next poll."""
        with self._lock:
            self.policy = policy
            self.detector.policy = policy

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)

    def on_own_thread(self) -> bool:
        return threading.current_thread() is self._thread

    def _loop(self) -> None:
        while not self._stop.wait(self.policy.poll_s):
            try:
                self.poll_once()
            except Exception:
                # The watchdog must survive its own bugs — a dead
                # watchdog is exactly the silent failure mode this
                # subsystem exists to eliminate.
                import traceback
                traceback.print_exc()

    # ------------------------------------------------------------------
    # one assessment

    def rank_views(self, now: float | None = None) -> dict:
        """Build the detector's per-rank views from the coordinator's
        heartbeat state (dead processes excluded — they are the
        supervisor's domain, not a hang)."""
        now = self._clock() if now is None else now
        with self._lock:
            comm, pm = self._comm, self._pm
        if comm is None:
            return {}
        alive = None
        if pm is not None:
            try:
                alive = set(pm.alive_ranks())
            except Exception:
                alive = None
        views: dict = {}
        for r in range(comm.num_workers):
            if alive is not None and r not in alive:
                continue
            ping = comm.last_ping(r)
            if ping is None:
                continue
            arrival, data = ping
            data = data or {}
            age = max(0.0, now - arrival)
            v: dict = {"hb_age": round(age, 3)}
            if (data.get("busy_s") is not None
                    and age <= self.policy.hb_stale_s):
                # Extrapolate to "now": the ping said busy_s as of its
                # send; the rank has been busy for the ping age since.
                # Pings past hb_stale_s are frozen data — the rank may
                # long have finished — and are excluded from verdicts
                # (the supervisor owns silent ranks).
                v["busy_s"] = float(data["busy_s"]) + age
                v["busy_type"] = data.get("busy_type")
                v["busy_id"] = data.get("busy_id")
                v["deadline"] = data.get("busy_deadline")
            col = data.get("col") or {}
            if col:
                v["seq"] = col.get("seq")
                v["op"] = col.get("op")
                v["in"] = col.get("in")
                v["col_age"] = (col.get("age") or 0) + age
                v["cops"] = col.get("cops")
            rep = data.get("rep") or {}
            if rep:
                # Step-loop progress (ISSUE 14): a --repeat cell
                # advancing through steps is healthy forward motion —
                # the detector folds this into its progress key so a
                # long collective-free training loop never reads as a
                # stall while it is actually stepping.
                v["rep"] = rep.get("i")
            views[r] = v
        return views

    def poll_once(self, now: float | None = None) -> list[dict]:
        """One detection + escalation pass (the loop body, callable
        directly by tests and the doctor)."""
        now = self._clock() if now is None else now
        with self._lock:
            comm = self._comm
        if comm is None:
            return []
        views = self.rank_views(now)
        suspected: set = set()
        sentry = self._sentry
        if sentry is not None:
            silent: set = set()
            fresh: set = set()
            for r in range(comm.num_workers):
                ping = comm.last_ping(r)
                if ping is None:
                    continue
                (fresh if now - ping[0] <= self.policy.hb_stale_s
                 else silent).add(r)
            for ev in sentry.observe(silent, set(), fresh, now=now):
                self._event("partition",
                            f"host {ev['host']}: {ev['event']} "
                            f"(ranks {ev['ranks']})")
            suspected = sentry.suspected_ranks()
        try:
            pending = comm.pending_snapshot()
        except Exception:
            pending = {}
        verdicts = self.detector.observe(now, views, pending)
        # Tenant attribution (gateway pools): pending requests are
        # tenant-tagged, so a verdict on a pooled cell names the one
        # notebook whose cell wedged the mesh — blame lands on the
        # right tenant, not the pool.
        for v in verdicts:
            tn = (pending.get(v["cell"]) or {}).get("tenant")
            if tn and not v.get("tenant"):
                v["tenant"] = tn
                v["detail"] = f"[tenant {tn}] " + v["detail"]
        if suspected:
            # A suspected-partition host's ranks are unreachable, not
            # hung: their apparent lag is frozen data.  Verdicts that
            # blame only them are suppressed (the supervisor's
            # partition machinery owns that failure domain).
            verdicts = [v for v in verdicts
                        if not set(v["ranks"]) <= suspected]
        reg = obs_metrics.registry()
        due_steps: list[tuple] = []
        with self._lock:
            self.last_verdicts = verdicts
            active = {v["cell"]: v for v in verdicts}
            for cell, v in active.items():
                st = self._hangs.get(cell)
                if st is None:
                    # Newly HUNG — distinct from slow, by construction.
                    st = {"step": 0, "next_ts": now, "first_ts": now,
                          "verdict": v}
                    # The analyzer told you so: when the hung cell was
                    # flagged pre-dispatch, the verdict carries the
                    # finding (the doctor and postmortem render it).
                    note = _preflight_note(
                        (pending.get(cell) or {}).get("cell_sha1"))
                    if note:
                        st["preflight"] = note["summary"]
                    self._hangs[cell] = st
                    self.cells_flagged += 1
                    self.verdicts_total += 1
                    reg.counter("nbd_hang_verdicts_total",
                                "cells flagged HUNG by the watchdog",
                                {"kind": v["kind"]}).inc()
                    flightrec.record("hang_verdict", kind=v["kind"],
                                     cell=str(cell)[:16],
                                     ranks=v["ranks"], seq=v.get("seq"),
                                     op=v.get("op"),
                                     tenant=v.get("tenant"),
                                     preflight=st.get("preflight"))
                    self._event("verdict", v["detail"], cell=cell,
                                kind=v["kind"], ranks=v["ranks"])
                    if "preflight" in st:
                        self._event(
                            "preflight",
                            "pre-flight lint had flagged this cell "
                            "before dispatch: " + st["preflight"],
                            cell=cell)
                st["verdict"] = v
                if "preflight" in st:
                    v["preflight"] = st["preflight"]
                ladder = self.policy.escalate
                if st["step"] < len(ladder) and now >= st["next_ts"]:
                    step = ladder[st["step"]]
                    st["step"] += 1
                    st["next_ts"] = now + self.policy.grace_s
                    due_steps.append((step, cell, v))
            for cell in [c for c in self._hangs if c not in active]:
                st = self._hangs.pop(cell)
                self.cells_resolved += 1
                flightrec.record("hang_resolved", cell=str(cell)[:16],
                                 after_steps=st["step"])
                self._event("resolved",
                            f"hang cleared after "
                            f"{st['step']} ladder step(s)", cell=cell)
            reg.gauge("nbd_hang_active",
                      "cells currently flagged HUNG").set(
                len(self._hangs))
        # Ladder steps run OUTSIDE the lock: a step can print, signal
        # processes, or run a minutes-long heal — none of which may
        # block status()/describe() readers (%dist_status during a
        # heal must still render).
        for step, cell, v in due_steps:
            self._run_step(step, cell, v)
        return verdicts

    # ------------------------------------------------------------------
    # escalation ladder

    def _event(self, event: str, detail: str, **extra) -> None:
        # Callers arrive with and without the lock held; the RLock
        # makes re-acquiring free for the former.  The concurrency
        # self-lint (analysis/concur.py) records this as a reentrant
        # self-edge in the lock-order graph — a plain Lock here would
        # fail CI as a self-deadlock.
        with self._lock:
            self.events.append({"ts": self._clock(), "event": event,
                                "detail": detail, **extra})

    def _run_step(self, step: str, cell, verdict: dict) -> None:
        with self._lock:
            self.escalations[step] = self.escalations.get(step, 0) + 1
        obs_metrics.registry().counter(
            "nbd_hang_escalations_total",
            "escalation ladder steps executed",
            {"step": step}).inc()
        flightrec.record("hang_escalation", step=step,
                         cell=str(cell)[:16], ranks=verdict["ranks"])
        self._event("escalation", f"{step}: {verdict['detail']}",
                    cell=cell, step=step)
        try:
            if step == "warn":
                print(f"\n⚠️ hang watchdog [{verdict['kind'].upper()}]: "
                      f"{verdict['detail']} — %dist_doctor for the "
                      f"full report")
                if verdict.get("preflight"):
                    print(f"   ↳ pre-flight lint flagged this cell "
                          f"before dispatch: {verdict['preflight']}")
            elif step == "dump":
                pm = self._pm
                if pm is not None and hasattr(pm, "dump_stacks"):
                    signaled = pm.dump_stacks(None)
                    self._event("stacks",
                                f"SIGUSR1 stack dump → ranks "
                                f"{signaled} (stacks-rank*.txt under "
                                f"{knobs.get_str('NBD_RUN_DIR', '?')})",
                                cell=cell)
            elif step == "interrupt":
                # Interrupt ALL ranks, not just the laggards: peers
                # blocked inside the same collective must abort too,
                # or the subset-interrupt footgun (%dist_interrupt's
                # documented caveat) leaves them wedged.
                pm = self._pm
                if pm is not None:
                    signaled = pm.interrupt(None)
                    print(f"🛑 hang watchdog: interrupted ranks "
                          f"{signaled} to break the hung cell")
            elif step == "heal":
                heal = self._heal_fn
                if heal is None:
                    self._event("heal-skipped",
                                "heal step reached but no heal "
                                "callback wired", cell=cell)
                    return
                result = heal()
                if result is not None:
                    comm, pm = result
                    with self._lock:
                        self._comm, self._pm = comm, pm
                        self._hangs.clear()
                        self.detector.reset()
        except Exception as e:
            self._event("step-failed", f"{step} failed: {e}", cell=cell)

    # ------------------------------------------------------------------
    # reporting

    def status(self) -> dict:
        sentry = self._sentry
        with self._lock:
            return {
                "policy": self.policy.describe(),
                "suspected_hosts": (sentry.suspected_hosts()
                                    if sentry is not None else {}),
                "active": {str(c): {"kind": st["verdict"]["kind"],
                                    "ranks": st["verdict"]["ranks"],
                                    "steps_taken": st["step"],
                                    "since": st["first_ts"]}
                           for c, st in self._hangs.items()},
                "cells_flagged": self.cells_flagged,
                "cells_resolved": self.cells_resolved,
                "escalations": dict(self.escalations),
                "last_verdicts": list(self.last_verdicts),
                "events": list(self.events),
            }

    def describe(self) -> str:
        st = self.status()
        lines = [f"🐕 hang watchdog: {st['policy']} · flagged "
                 f"{st['cells_flagged']} · resolved "
                 f"{st['cells_resolved']}"
                 + (f" · escalations {st['escalations']}"
                    if st["escalations"] else "")]
        for c, a in st["active"].items():
            lines.append(f"   ⚠ HUNG [{a['kind']}] cell {c[:12]}… "
                         f"ranks {a['ranks']} "
                         f"({a['steps_taken']} ladder step(s) taken)")
        for ev in list(st["events"])[-4:]:
            lines.append(
                f"   {time.strftime('%H:%M:%S', time.localtime(ev['ts']))} "
                f"{ev['event']}: {ev['detail'][:110]}")
        return "\n".join(lines)


# ----------------------------------------------------------------------
# the stuck-cell doctor


def _stack_file(run_dir: str, rank: int) -> str | None:
    """Newest per-pid stack file for ``rank`` (file names carry the
    writer pid, like the flight rings, so a healed rank never clobbers
    its dead predecessor's dumps)."""
    prefix = f"stacks-rank{rank}."
    try:
        names = [n for n in os.listdir(run_dir)
                 if n.startswith(prefix) and n.endswith(".txt")]
    except OSError:
        return None
    if not names:
        return None
    paths = [os.path.join(run_dir, n) for n in names]
    paths.sort(key=lambda p: (os.path.getmtime(p), p), reverse=True)
    return paths[0]


def _stack_tail(run_dir: str, rank: int,
                lines: int) -> tuple[str, str] | None:
    """(path, last-N-lines) of the rank's newest stack dump, or None.
    One lookup serves both: resolving the path twice would double the
    directory scan AND risk labeling the tail with a different file
    than the one read (a heal can mint a newer one in between)."""
    path = _stack_file(run_dir, rank)
    if path is None:
        return None
    try:
        with open(path) as f:
            content = f.read()
    except OSError:
        return None
    if not content.strip():
        return None
    return path, "\n".join(content.rstrip().splitlines()[-lines:])


def hang_report(comm, pm=None, watchdog: HangWatchdog | None = None, *,
                dump_stacks: bool = True, stack_wait_s: float = 0.8,
                stack_lines: int = 30, flight_lines: int = 6,
                async_window: dict | None = None) -> str:
    """Assemble the ``%dist_doctor`` report: per-rank collective
    positions and busy ages, the skew table naming lagging rank(s)
    and the divergence point, active watchdog verdicts, freshly
    dumped per-rank stacks (SIGUSR1 → faulthandler), and each flight
    ring's last events.  Read-mostly: the only cluster interaction is
    the optional stack-dump signal — nothing goes through the
    workers' (possibly wedged) serial request loops.

    ``async_window`` (an ``AsyncExecutor.snapshot()``) names the
    async-pipelined cells among the in-flight requests (ISSUE 14):
    with >1 cell in flight, "which request is the mesh actually
    executing and which are streamed behind it" is exactly what a
    hang report must answer."""
    now = time.time()
    wd = watchdog
    # Lenient env parse: a typo'd NBD_HANG_ESCALATE is exactly why the
    # watchdog failed to auto-start — the DIAGNOSTIC must still run.
    policy = (wd.policy if wd is not None
              else HangPolicy.from_env_lenient())
    # Detection-READ-ONLY on purpose: the doctor reports the standing
    # watchdog's latest assessment (at most poll_s stale) instead of
    # driving poll_once itself — a poll executes due escalation-ladder
    # steps (interrupt! heal!), and a report/postmortem capture must
    # never perturb the very state it is recording.
    if wd is not None:
        views = wd.rank_views(now)
        verdicts = list(wd.last_verdicts)
    else:
        tmp = HangWatchdog(policy)
        tmp._comm, tmp._pm = comm, pm
        views = tmp.rank_views(now)
        verdicts = []
    lines = [
        "nbdistributed_tpu stuck-cell doctor",
        "=" * 35,
        f"time    : {time.strftime('%Y-%m-%dT%H:%M:%S')}",
        f"world   : {getattr(comm, 'num_workers', '?')} workers",
        f"policy  : {policy.describe()}",
    ]
    # Multi-host worlds: per-host link health (RTT from the clock
    # estimator's min-RTT samples, heartbeat ages, redeliveries as the
    # loss proxy) plus any partition suspicion — "which link is sick"
    # before "which rank is stuck".
    hosts_map = dict(getattr(pm, "hosts", None) or {})
    if len(set(hosts_map.values()) | {getattr(comm, "local_host",
                                              "local")}) > 1:
        try:
            ls = comm.link_stats()
        except Exception:
            ls = None
        if ls:
            from .partition import format_link_suffix
            lines.append("")
            lines.append("hosts / links (rtt = min clock-sample RTT; "
                         "retries ≈ frames a link ate):")
            for h, hs in sorted(ls["hosts"].items()):
                lines.append(f"   {h:<14} ranks {hs['ranks']} · "
                             f"{format_link_suffix(hs)}")
        sentry = getattr(wd, "_sentry", None) if wd is not None else None
        if sentry is not None:
            note = sentry.describe()
            if note:
                lines.append(f"   {note}")
    lines += [
        "",
        f"{'rank':<5}{'busy':<22}{'hb-age':<8}{'col#':<6}"
        f"{'op':<22}{'in':<4}{'col-age':<9}{'cell-ops':<8}",
    ]
    lines.append("─" * len(lines[-1]))
    world = getattr(comm, "num_workers", 0) or 0
    seqs: dict[int, int] = {}
    for r in range(world):
        v = views.get(r)
        if v is None:
            state = "(no heartbeat — dead or never attached)"
            lines.append(f"{r:<5}{state}")
            continue
        busy = "-"
        if v.get("busy_s") is not None:
            busy = f"{v.get('busy_type')} {v['busy_s']:.1f}s"
            if v.get("deadline"):
                busy += f"/{v['deadline']:.0f}s"
        seqs[r] = int(v.get("seq") or 0)
        col_age = v.get("col_age")
        col_age_s = f"{col_age:.1f}" if col_age is not None else "-"
        lines.append(
            f"{r:<5}{busy:<22}{v.get('hb_age', 0):<8.1f}"
            f"{str(v.get('seq', '-')):<6}{str(v.get('op') or '-'):<22}"
            f"{('y' if v.get('in') else '-'):<4}"
            f"{col_age_s:<9}{str(v.get('cops', '-')):<8}")
    # Skew table: who is behind whom, among BUSY ranks only and by
    # CELL-LOCAL position (process-lifetime seqs diverge permanently
    # and harmlessly after a hazard-raise or a broken hang — they are
    # shown per-rank above, but must not be called "lagging").
    pos = {r: int((views[r].get("cops") or 0))
           for r in range(world)
           if views.get(r) is not None
           and views[r].get("busy_s") is not None}
    lines.append("")
    if pos:
        maxpos = max(pos.values())
        lag = sorted(r for r, p in pos.items() if p < maxpos)
        if lag and maxpos:
            lines.append(
                f"skew    : busy ranks' max cell position #{maxpos} "
                f"(global seq #{max(seqs.get(r, 0) for r in pos)}); "
                f"lagging rank(s) {lag} at "
                f"{sorted(set(pos[r] for r in lag))}")
        else:
            lines.append(f"skew    : none — all busy ranks at cell "
                         f"position #{maxpos}")
    else:
        lines.append("skew    : (no busy ranks)")
    # In-flight requests.
    try:
        pend = comm.pending_snapshot()
    except Exception:
        pend = {}
    async_cells = {c.get("msg_id"): c
                   for c in (async_window or {}).get("cells", ())
                   if c.get("msg_id")}
    if pend:
        lines.append("")
        lines.append("in-flight requests:")
        for mid, p in sorted(pend.items()):
            missing = sorted(set(p["expect"]) - set(p["responded"]))
            age = (f"{now - p['sent_at']:.1f}s" if p.get("sent_at")
                   else "?")
            who = (f" · tenant {p['tenant']}" if p.get("tenant")
                   else "")
            ac = async_cells.get(mid)
            tag = ""
            if ac is not None:
                tag = (f" · ⧗ async cell #{ac['seq']}"
                       + (" (holds the collective stream)"
                          if ac.get("collective") != "free" else ""))
            lines.append(f"   {mid[:12]}… {p.get('type') or '?'} "
                         f"age {age} · responded {p['responded']} · "
                         f"waiting on {missing}{who}{tag}")
            note = _preflight_note(p.get("cell_sha1"))
            if note:
                lines.append(f"      ↳ pre-flight lint flagged this "
                             f"cell before dispatch: "
                             f"{note['summary']}")
    if async_window and async_window.get("depth"):
        lines.append(
            f"async   : window {async_window['depth']}/"
            f"{async_window['window']} in flight — the per-rank loop "
            f"is serial, so streamed cells behind the busy one are "
            f"QUEUED on the worker, not hung")
    # Verdicts.
    lines.append("")
    if verdicts:
        lines.append("verdicts:")
        for v in verdicts:
            lines.append(f"   ⚠ HUNG [{v['kind']}] {v['detail']}")
            if v.get("preflight"):
                lines.append(f"      ↳ pre-flight lint flagged this "
                             f"cell before dispatch: {v['preflight']}")
    elif wd is not None:
        lines.append("verdicts: none — nothing HUNG by current policy")
    else:
        lines.append("verdicts: (no watchdog attached — positions "
                      "only; %dist_watchdog on)")
    if wd is not None and wd.escalations:
        lines.append(f"escalations so far: {dict(wd.escalations)}")
    # Stacks: freshly dumped, then read back.
    run_d = knobs.get_str("NBD_RUN_DIR") or ""
    if dump_stacks and pm is not None and hasattr(pm, "dump_stacks"):
        signaled = pm.dump_stacks(None)
        if signaled:
            time.sleep(stack_wait_s)  # let faulthandler write
        lines.append("")
        lines.append(f"stacks (SIGUSR1 → ranks {signaled}):")
        for r in range(world):
            res = _stack_tail(run_d, r, stack_lines) if run_d else None
            if res is None:
                lines.append(f"-- rank {r}: no stack file")
                continue
            path, tail = res
            lines.append(f"-- rank {r} ({path}):")
            lines.append(tail)
    # Flight-ring tails.
    if run_d:
        lines.append("")
        lines.append("last flight events:")
        import json as _json
        for key in [*range(world), "coordinator"]:
            proc = key if key == "coordinator" else f"rank{key}"
            ring = flightrec.read_latest(run_d, proc)
            if ring is None:
                lines.append(f"-- {proc}: no ring")
                continue
            lines.append(f"-- {proc} ({ring['recovered']} events"
                         + (", TORN tail" if ring.get("torn_tail")
                            else "") + "):")
            for ev in ring["events"][-flight_lines:]:
                ts = time.strftime("%H:%M:%S",
                                   time.localtime(ev.get("ts", 0)))
                detail = {k: v for k, v in ev.items()
                          if k not in ("t", "ts")}
                lines.append(f"     {ts} {ev.get('t', '?'):<20} "
                             f"{_json.dumps(detail, default=str)[:100]}")
    return "\n".join(lines)
