"""Seeded, deterministic fault injection for the control plane.

A :class:`FaultPlan` sits on a transport send path (``WorkerChannel.
send`` / ``CoordinatorListener.send_to_ranks`` / the native listener's
Python wrapper) and decides, per outgoing frame, whether to drop,
delay, duplicate, or truncate it — plus two process-level faults the
worker loop consults directly: heartbeat freeze and SIGKILL at a
chosen message index.

Determinism is the design center: every per-frame decision is a pure
function of ``(seed, frame index)``, so a fixed seed replays the exact
same fault sequence as long as the frame order is deterministic.  To
keep it deterministic in practice, frames whose message type is in
``exempt`` (heartbeat ``ping`` by default — the heartbeat thread's
cadence is wall-clock, not program order) bypass the plan without
consuming an index.

Wire-visible effects map to real failure modes:

- **drop** — lost frame on a flaky link; recovered by the retry layer
  (requests) or by request redelivery reaching the dedup cache
  (replies).
- **delay** — a slow host / congested DCN hop.
- **duplicate** — retransmission at a lower layer; must be absorbed by
  the worker's ReplayCache and the coordinator's late-response drop.
- **truncate** — mid-frame connection tear: the receiver's framer sees
  garbage, drops the connection, and the death/disconnect machinery
  must take over (this fault is connection-fatal by design).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable

from ..observability import flightrec

DEFAULT_EXEMPT = ("ping",)

_SPEC_KEYS = frozenset({
    "seed", "drop", "delay_p", "delay_s", "duplicate", "truncate",
    "freeze_heartbeat", "kill_rank", "kill_at", "exempt",
    "freeze_rank", "freeze_at", "freeze_s", "links", "corrupt",
    "xfer_drop", "xfer_corrupt",
})

# A frame is a BULK-TRANSFER frame (targetable by xfer_drop /
# xfer_corrupt) when it is a chunk request outright, or a reply big
# enough that only a chunk payload can be riding it — worker replies
# are all msg_type "response", so pull-side chunks are recognized by
# size.  64 KiB is far above any control reply and far below the
# minimum chunk size.
_XFER_BULK_MIN_BYTES = 64 << 10


def _is_xfer_bulk(kind: str | None, nbytes: int) -> bool:
    if kind == "xfer_chunk":
        return True
    return kind == "response" and nbytes >= _XFER_BULK_MIN_BYTES

_LINK_KEYS = frozenset({
    "hosts", "after_s", "for_s", "latency_s", "loss", "bw_bytes_s",
})

_CORRUPT_KEYS = frozenset({
    "rank", "step", "name", "mode", "bits", "scale", "count",
})

_CORRUPT_MODES = ("bitflip", "scale")


class CorruptSpec:
    """One silent-data-corruption injection (ISSUE 19): damage a named
    array on rank ``rank`` at guarded-train step ``step``.

    Unlike the frame faults above, corruption targets the *data plane*
    — the parameters a guarded train step (resilience/trainguard.py)
    is about to consume — so the replica-consistency audit has a
    deterministic SDC to detect, attribute, and repair.

    - ``name`` — substring match against the pytree leaf path
      (``jax.tree_util.keystr``); ``"*"`` matches the first leaf.
    - ``mode`` — ``bitflip`` XORs ``bits`` seeded bit positions in the
      leaf's raw bytes (the classic cosmic-ray/SDC model: any bit,
      including exponent bits that turn the value NaN/inf); ``scale``
      multiplies a seeded contiguous run of ``count`` elements by
      ``scale`` (a bounded numeric skew that stays finite).
    - One-shot semantics with ``>=`` on the step index, like
      ``kill_at``/``freeze_at``: a skipped step can never disarm it.

    Positions are pure functions of the owning plan's seed and this
    spec's fields, so a fixed seed replays the exact same corruption.
    """

    def __init__(self, *, rank: int, step: int, name: str = "*",
                 mode: str = "bitflip", bits: int = 1,
                 scale: float = 4.0, count: int = 1):
        self.rank = int(rank)
        self.step = int(step)
        if self.rank < 0 or self.step < 0:
            raise ValueError(f"corrupt spec rank/step must be >= 0 "
                             f"(got rank={rank!r}, step={step!r})")
        if not isinstance(name, str) or not name:
            raise ValueError(f"corrupt spec needs a non-empty leaf-path "
                             f"name (or '*'), got {name!r}")
        if mode not in _CORRUPT_MODES:
            raise ValueError(f"corrupt spec mode must be one of "
                             f"{_CORRUPT_MODES}, got {mode!r}")
        self.name = name
        self.mode = mode
        self.bits = int(bits)
        self.scale = float(scale)
        self.count = int(count)
        if self.bits < 1 or self.count < 1:
            raise ValueError(f"corrupt spec bits/count must be >= 1 "
                             f"(got bits={bits!r}, count={count!r})")

    @classmethod
    def from_spec(cls, spec: dict) -> "CorruptSpec":
        if not isinstance(spec, dict):
            raise TypeError(f"corrupt spec must be a dict, got "
                            f"{type(spec).__name__}")
        unknown = set(spec) - _CORRUPT_KEYS
        if unknown:
            raise ValueError(f"unknown corrupt spec keys "
                             f"{sorted(unknown)} "
                             f"(known: {sorted(_CORRUPT_KEYS)})")
        if "rank" not in spec or "step" not in spec:
            raise ValueError(f"corrupt spec needs both rank and step "
                             f"(got {sorted(spec)})")
        return cls(**spec)

    def spec(self) -> dict:
        return {"rank": self.rank, "step": self.step, "name": self.name,
                "mode": self.mode, "bits": self.bits,
                "scale": self.scale, "count": self.count}


class LinkSpec:
    """Shaping for one host-pair link (ISSUE 6).

    ``hosts`` is an unordered pair of host labels (``"*"`` matches any
    host); the remaining knobs describe what the link does to frames
    crossing it:

    - ``after_s``/``for_s`` — a **partition window**: starting
      ``after_s`` seconds after the plan is installed, the link drops
      every frame for ``for_s`` seconds (0 = forever).  Workers sever
      their connection on the first blocked send, so the far side
      rides the orphan machinery exactly as it would when a real DCN
      link blackholes and TCP keepalive finally tears the stream.
    - ``latency_s`` — added one-way delay per frame (a slow hop).
    - ``loss`` — per-frame drop probability (seeded per link).
    - ``bw_bytes_s`` — bandwidth cap: each frame sleeps
      ``len(frame)/bw`` before the write (a saturated link).

    Heartbeats are NOT exempt from link shaping (unlike the per-frame
    faults): a partition that let pings through would be undetectable,
    which is the opposite of the point.
    """

    def __init__(self, *, hosts, after_s: float | None = None,
                 for_s: float | None = None, latency_s: float = 0.0,
                 loss: float = 0.0, bw_bytes_s: float = 0.0):
        hosts = tuple(hosts or ())
        if len(hosts) != 2 or not all(isinstance(h, str) and h
                                      for h in hosts):
            raise ValueError(
                f"link spec needs a pair of host labels, got {hosts!r}")
        if hosts[0] == hosts[1] and hosts[0] != "*":
            raise ValueError(f"link spec pairs a host with itself: "
                             f"{hosts!r} (a host cannot partition from "
                             f"itself)")
        self.hosts = frozenset(hosts)
        # A partition window is declared by PRESENCE of either knob
        # (None = absent), so `for_s=0` keeps its documented meaning —
        # "from after_s until cleared" — instead of silently injecting
        # nothing when after_s is also 0.
        self.has_partition = after_s is not None or for_s is not None
        self.after_s = float(after_s or 0.0)
        self.for_s = float(for_s or 0.0)
        self.latency_s = float(latency_s)
        self.loss = float(loss)
        self.bw_bytes_s = float(bw_bytes_s)
        # Stable per-link loss salt (str.hash is randomized per
        # process and would break cross-fleet seeded determinism);
        # precomputed — the send path must not pay a crc per frame.
        import zlib
        self._loss_salt = zlib.crc32(
            "|".join(sorted(self.hosts)).encode()) & 0xFFFF

    @classmethod
    def from_spec(cls, spec: dict) -> "LinkSpec":
        if not isinstance(spec, dict):
            raise TypeError(f"link spec must be a dict, got "
                            f"{type(spec).__name__}")
        unknown = set(spec) - _LINK_KEYS
        if unknown:
            raise ValueError(f"unknown link spec keys {sorted(unknown)} "
                             f"(known: {sorted(_LINK_KEYS)})")
        return cls(**spec)

    def spec(self) -> dict:
        # None for an undeclared window, so the roundtrip preserves
        # has_partition (0.0 values would re-declare one).
        return {"hosts": sorted(self.hosts),
                "after_s": self.after_s if self.has_partition else None,
                "for_s": self.for_s if self.has_partition else None,
                "latency_s": self.latency_s,
                "loss": self.loss, "bw_bytes_s": self.bw_bytes_s}

    def matches(self, a: str, b: str) -> bool:
        pair = {a, b}
        if "*" in self.hosts:
            other = next(iter(self.hosts - {"*"}), "*")
            return other == "*" or other in pair
        return self.hosts == pair

    def partition_active(self, elapsed_s: float) -> bool:
        """Is the partition window open ``elapsed_s`` seconds after the
        plan was installed?  ``for_s == 0`` with a declared window
        means 'until cleared'."""
        if not self.has_partition or elapsed_s < self.after_s:
            return False
        return not self.for_s or elapsed_s < self.after_s + self.for_s

# A frozen rank must stay frozen long past any watchdog policy window,
# but not forever: the sleep is broken early by the escalation
# ladder's interrupt, and a test that never interrupts still exits.
DEFAULT_FREEZE_S = 3600.0


class FaultPlan:
    """One deterministic chaos schedule.  Thread-safe; counters record
    what actually happened for ``%dist_chaos status`` and assertions."""

    MAX_EVENTS = 4096  # injected-decision log bound (~0.5 MB worst case)

    def __init__(self, *, seed: int = 0, drop: float = 0.0,
                 delay_p: float = 0.0, delay_s: float = 0.02,
                 duplicate: float = 0.0, truncate: float = 0.0,
                 freeze_heartbeat: bool = False,
                 kill_rank: int | None = None, kill_at: int | None = None,
                 freeze_rank: int | None = None,
                 freeze_at: int | None = None,
                 freeze_s: float = DEFAULT_FREEZE_S,
                 links=None, corrupt=None,
                 xfer_drop: float = 0.0, xfer_corrupt: float = 0.0,
                 exempt=DEFAULT_EXEMPT):
        self.seed = int(seed)
        self.drop = float(drop)
        # Chunk-targeted faults (ISSUE 20): applied only to bulk-
        # transfer frames (xfer_chunk requests / chunk-bearing
        # replies), on their own seeded index stream so arming them
        # does not perturb the generic per-frame schedule.
        # ``xfer_corrupt`` flips one byte in the trailing half of the
        # frame — payload bytes, never the JSON header — so the
        # damage is exactly what the per-chunk crc32 exists to catch.
        self.xfer_drop = float(xfer_drop)
        self.xfer_corrupt = float(xfer_corrupt)
        self._xfer_index = 0
        self.delay_p = float(delay_p)
        self.delay_s = float(delay_s)
        self.duplicate = float(duplicate)
        self.truncate = float(truncate)
        self.freeze_heartbeat = bool(freeze_heartbeat)
        if (kill_rank is None) != (kill_at is None):
            # Half a kill spec is silently inert (should_kill would
            # never fire) — the same typo'd-knob failure mode the
            # unknown-key check below exists to prevent.
            raise ValueError(
                f"kill_rank and kill_at must be set together "
                f"(got kill_rank={kill_rank!r}, kill_at={kill_at!r})")
        self.kill_rank = kill_rank
        self.kill_at = kill_at
        if (freeze_rank is None) != (freeze_at is None):
            raise ValueError(
                f"freeze_rank and freeze_at must be set together "
                f"(got freeze_rank={freeze_rank!r}, "
                f"freeze_at={freeze_at!r})")
        self.freeze_rank = freeze_rank
        self.freeze_at = freeze_at
        self.freeze_s = float(freeze_s)
        self._froze = False  # one-shot: the mesh must survive AFTER
        # the hang is broken, so later collectives run clean
        # Per-link (host-pair) shaping: partition windows, latency,
        # loss, bandwidth caps — applied by the transports to frames
        # whose (src, dst) host labels match (ISSUE 6).  The window
        # clock starts when the plan is INSTALLED (this constructor),
        # the same origin kill_at counts messages from.
        self.links = tuple(
            l if isinstance(l, LinkSpec) else LinkSpec.from_spec(l)
            for l in (links or ()))
        # Silent-data-corruption specs (ISSUE 19), consumed by the
        # guarded train step.  One-shot per spec (``_corrupt_done``
        # indexes into the tuple) so a flip fires exactly once even
        # when the step index is consulted every step thereafter.
        self.corrupt = tuple(
            c if isinstance(c, CorruptSpec) else CorruptSpec.from_spec(c)
            for c in (corrupt or ()))
        self._corrupt_done: set[int] = set()
        self._t0 = time.monotonic()
        self.exempt = frozenset(exempt or ())
        self._lock = threading.Lock()
        self._index = 0
        self._link_index: dict[frozenset, int] = {}
        self.counters = {"sent": 0, "dropped": 0, "delayed": 0,
                         "duplicated": 0, "truncated": 0, "exempt": 0,
                         "frozen": 0, "link_dropped": 0,
                         "link_delayed": 0, "corrupted": 0,
                         "xfer_dropped": 0, "xfer_corrupted": 0}
        # Timestamped record of every non-clean decision, bounded, for
        # the observability layer: the merged Chrome trace folds these
        # in as instant events so a chaos run shows WHERE the drops
        # and duplicates landed relative to the requests they afflict.
        self._events: list[dict] = []

    # ------------------------------------------------------------------
    # construction / description

    @classmethod
    def from_spec(cls, spec: dict) -> "FaultPlan":
        """Build from a JSON-able spec dict (the ``%dist_chaos``
        broadcast / ``NBD_FAULT_PLAN`` payload).  Unknown keys are an
        error — a typo'd knob must not silently inject nothing."""
        if not isinstance(spec, dict):
            raise TypeError(f"fault spec must be a dict, got "
                            f"{type(spec).__name__}")
        unknown = set(spec) - _SPEC_KEYS
        if unknown:
            raise ValueError(f"unknown fault spec keys {sorted(unknown)} "
                             f"(known: {sorted(_SPEC_KEYS)})")
        return cls(**spec)

    @classmethod
    def from_env(cls, var: str = "NBD_FAULT_PLAN") -> "FaultPlan | None":
        from ..utils import knobs
        raw = (knobs.get_raw(var) if var in knobs.KNOBS
               else os.environ.get(var))
        spec = json.loads(raw) if raw else None
        if var == "NBD_FAULT_PLAN":
            # Spawn-time SDC injection (ISSUE 19): NBD_CORRUPT_SPEC is
            # a JSON list of corrupt specs folded into the plan, so a
            # chaos test can arm a bit-flip without composing the full
            # fault-plan JSON by hand.
            craw = knobs.get_raw("NBD_CORRUPT_SPEC")
            if craw:
                spec = dict(spec or {})
                spec["corrupt"] = (list(spec.get("corrupt") or ())
                                   + list(json.loads(craw)))
        if not spec:
            return None
        return cls.from_spec(spec)

    def spec(self) -> dict:
        """Round-trippable description (``from_spec(p.spec())`` builds
        an equivalent plan with fresh counters)."""
        return {"seed": self.seed, "drop": self.drop,
                "delay_p": self.delay_p, "delay_s": self.delay_s,
                "duplicate": self.duplicate, "truncate": self.truncate,
                "freeze_heartbeat": self.freeze_heartbeat,
                "kill_rank": self.kill_rank, "kill_at": self.kill_at,
                "freeze_rank": self.freeze_rank,
                "freeze_at": self.freeze_at, "freeze_s": self.freeze_s,
                "links": [l.spec() for l in self.links],
                "corrupt": [c.spec() for c in self.corrupt],
                "xfer_drop": self.xfer_drop,
                "xfer_corrupt": self.xfer_corrupt,
                "exempt": sorted(self.exempt)}

    # ------------------------------------------------------------------
    # per-frame decisions

    def decide(self, index: int) -> list[str]:
        """Actions for frame ``index`` — pure in (seed, index).  Drop
        and truncate are exclusive terminal outcomes; delay and
        duplicate compose with a normal send."""
        rng = random.Random(self.seed * 1_000_003 + index)
        if self.drop and rng.random() < self.drop:
            return ["drop"]
        if self.truncate and rng.random() < self.truncate:
            return ["truncate"]
        acts = []
        if self.delay_p and rng.random() < self.delay_p:
            acts.append("delay")
        if self.duplicate and rng.random() < self.duplicate:
            acts.append("duplicate")
        return acts

    def transmit(self, frame: bytes, send: Callable[[bytes], None], *,
                 kind: str | None = None) -> None:
        """Pass one outgoing frame through the plan.  ``send`` performs
        the actual (whole-frame) write; it may be called 0, 1, or 2
        times, or once with a truncated frame."""
        if kind is not None and kind in self.exempt:
            with self._lock:
                self.counters["exempt"] += 1
            send(frame)
            return
        if ((self.xfer_drop or self.xfer_corrupt)
                and _is_xfer_bulk(kind, len(frame))):
            # Chunk-targeted faults: own seeded index stream, so the
            # generic schedule below is unperturbed by arming these.
            with self._lock:
                xidx = self._xfer_index
                self._xfer_index += 1
            xrng = random.Random(
                (self.seed + 7_777_777) * 1_000_003 + xidx)
            if self.xfer_drop and xrng.random() < self.xfer_drop:
                flightrec.record("fault", actions=["xfer_drop"],
                                 kind=kind, index=xidx)
                with self._lock:
                    self.counters["xfer_dropped"] += 1
                    if len(self._events) < self.MAX_EVENTS:
                        self._events.append(
                            {"ts": time.time(), "index": xidx,
                             "actions": ["xfer_drop"], "kind": kind})
                return
            if self.xfer_corrupt and xrng.random() < self.xfer_corrupt:
                # Flip one bit in the trailing half of the frame —
                # guaranteed payload bytes on a ≥64 KiB bulk frame
                # (the JSON header is a few hundred bytes), so the
                # frame still parses and the per-chunk crc32 is what
                # catches the damage, exercising the refuse-and-
                # resend path rather than tearing the connection.
                flightrec.record("fault", actions=["xfer_corrupt"],
                                 kind=kind, index=xidx)
                mut = bytearray(frame)
                half = len(mut) // 2
                pos = half + xrng.randrange(len(mut) - half)
                mut[pos] ^= 1 << xrng.randrange(8)
                frame = bytes(mut)
                with self._lock:
                    self.counters["xfer_corrupted"] += 1
                    if len(self._events) < self.MAX_EVENTS:
                        self._events.append(
                            {"ts": time.time(), "index": xidx,
                             "actions": ["xfer_corrupt"],
                             "kind": kind})
        with self._lock:
            index = self._index
            self._index += 1
        acts = self.decide(index)
        if acts:
            # Injected decisions also land in the crash-surviving
            # flight ring: the in-memory event log below dies with the
            # process, and "what was chaos doing just before the kill"
            # is a postmortem question by definition.
            flightrec.record("fault", actions=list(acts), kind=kind,
                             index=index)
        with self._lock:
            if acts and len(self._events) < self.MAX_EVENTS:
                self._events.append({"ts": time.time(), "index": index,
                                     "actions": list(acts), "kind": kind})
            if "drop" in acts:
                self.counters["dropped"] += 1
                return
            if "truncate" in acts:
                self.counters["truncated"] += 1
            if "delay" in acts:
                self.counters["delayed"] += 1
            if "duplicate" in acts:
                self.counters["duplicated"] += 1
            self.counters["sent"] += 1
        if "truncate" in acts:
            send(frame[:max(1, len(frame) // 2)])
            return
        if "delay" in acts:
            time.sleep(self.delay_s)
        send(frame)
        if "duplicate" in acts:
            send(frame)

    def events(self) -> list[dict]:
        """Timestamped injected decisions (``{ts, index, actions,
        kind}``) for trace export; JSON-able."""
        with self._lock:
            return [dict(e) for e in self._events]

    # ------------------------------------------------------------------
    # process-level faults (worker loop)

    def heartbeat_frozen(self) -> bool:
        return self.freeze_heartbeat

    def should_kill(self, rank: int, msg_index: int) -> bool:
        """SIGKILL trigger: ``rank`` matches and the worker has received
        at least ``kill_at`` control messages since the plan was
        installed (``>=`` so a skipped index can never disarm it)."""
        return (self.kill_rank == rank and self.kill_at is not None
                and msg_index >= self.kill_at)

    def has_freeze(self) -> bool:
        return self.freeze_rank is not None

    def should_freeze(self, rank: int, collective_seq: int) -> float | None:
        """Collective-freeze trigger (hang watchdog's chaos scenario):
        when ``rank`` matches and the process-global collective
        sequence has reached ``freeze_at``, return the seconds to
        block (ONE-SHOT — the rank wedges inside exactly one
        collective, so after the escalation ladder breaks the hang
        the mesh keeps working); otherwise None.  ``>=`` like
        ``should_kill`` so a skipped index can never disarm it."""
        if (self.freeze_rank != rank or self.freeze_at is None
                or collective_seq < self.freeze_at):
            return None
        with self._lock:
            if self._froze:
                return None
            self._froze = True
            self.counters["frozen"] += 1
        flightrec.record("fault", actions=["freeze"], kind="collective",
                         index=collective_seq)
        return self.freeze_s

    # ------------------------------------------------------------------
    # silent data corruption (guarded train step, ISSUE 19)

    def has_corrupt(self) -> bool:
        return bool(self.corrupt)

    def corrupt_due(self, rank: int, step: int) -> "list[CorruptSpec]":
        """Corrupt specs firing for ``rank`` at guarded-step ``step``
        — ONE-SHOT per spec, ``>=`` on the step index like
        ``should_kill`` so a skipped step can never disarm one.
        Consumed under the lock: a spec fires exactly once."""
        if not self.corrupt:
            return []
        due = []
        with self._lock:
            for i, c in enumerate(self.corrupt):
                if (c.rank == rank and step >= c.step
                        and i not in self._corrupt_done):
                    self._corrupt_done.add(i)
                    due.append(c)
        return due

    def note_corrupt(self, spec: "CorruptSpec", *, step: int,
                     leaf: str = "") -> None:
        """Record an injected corruption in the counters, the bounded
        event log (merged traces / postmortems fold these in beside
        the frame faults), and the crash-surviving flight ring."""
        flightrec.record("fault", actions=["corrupt"], kind=spec.mode,
                         index=step, rank=spec.rank, leaf=leaf)
        with self._lock:
            self.counters["corrupted"] += 1
            if len(self._events) < self.MAX_EVENTS:
                self._events.append(
                    {"ts": time.time(), "index": step,
                     "actions": ["corrupt"], "kind": spec.mode,
                     "rank": spec.rank, "leaf": leaf})

    # ------------------------------------------------------------------
    # per-link shaping (transport hooks, ISSUE 6)

    def has_links(self) -> bool:
        return bool(self.links)

    def link_for(self, src: str | None, dst: str | None) -> "LinkSpec | None":
        """The first link spec matching the (unordered) host pair, or
        None.  Frames that stay on one host never match (a host cannot
        partition from itself)."""
        if not self.links or not src or not dst or src == dst:
            return None
        for link in self.links:
            if link.matches(src, dst):
                return link
        return None

    def link_blocked(self, src: str | None, dst: str | None,
                     now: float | None = None) -> bool:
        """Is the src<->dst link inside an active partition window?
        Consulted by worker send paths (which sever + raise so the
        orphan machinery engages) and by the orphan reconnect loop
        (which must not dial through a down link — locally the connect
        would succeed, voiding the emulation)."""
        link = self.link_for(src, dst)
        if link is None or not link.has_partition:
            return False
        elapsed = (time.monotonic() if now is None else now) - self._t0
        return link.partition_active(elapsed)

    def link_transmit(self, src: str | None, dst: str | None,
                      frame: bytes, send: Callable[[bytes], None], *,
                      kind: str | None = None) -> None:
        """Shape one frame crossing src<->dst, then continue through
        the per-frame faults (:meth:`transmit`).  Partition and loss
        drop the frame silently (the coordinator path — workers check
        :meth:`link_blocked` first and sever instead); latency and the
        bandwidth cap sleep on the caller thread, which is exactly
        where a slow link's backpressure lands."""
        link = self.link_for(src, dst)
        if link is None:
            self.transmit(frame, send, kind=kind)
            return
        if link.has_partition and link.partition_active(
                time.monotonic() - self._t0):
            with self._lock:
                self.counters["link_dropped"] += 1
                if len(self._events) < self.MAX_EVENTS:
                    self._events.append(
                        {"ts": time.time(), "index": -1,
                         "actions": ["link_partition"], "kind": kind,
                         "link": sorted({src, dst})})
            return
        if link.loss:
            pair = frozenset((src, dst))
            with self._lock:
                idx = self._link_index.get(pair, 0)
                self._link_index[pair] = idx + 1
            rng = random.Random((self.seed * 1_000_003 + idx)
                                ^ link._loss_salt)
            if rng.random() < link.loss:
                with self._lock:
                    self.counters["link_dropped"] += 1
                flightrec.record("fault", actions=["link_loss"],
                                 kind=kind, index=idx)
                return
        wait = link.latency_s
        if link.bw_bytes_s:
            wait += len(frame) / link.bw_bytes_s
        if wait > 0:
            with self._lock:
                self.counters["link_delayed"] += 1
            time.sleep(wait)
        self.transmit(frame, send, kind=kind)


# ----------------------------------------------------------------------
# process-wide plan registry (ISSUE 19)
#
# The transports consult the plan through the objects the worker hands
# them, but the guarded train step runs deep inside user cells with no
# worker reference in scope — it reads the plan from here instead.  The
# worker's two plan-install paths (spawn-time NBD_FAULT_PLAN and the
# runtime %dist_chaos arm in _set_fault_plan) both publish through
# set_process_plan, so the data-plane corruption faults always track
# the live control-plane plan.  Single-writer by construction: both
# install paths run on the worker's serial request loop.

_process_plan: "FaultPlan | None" = None


def set_process_plan(plan: "FaultPlan | None") -> None:
    global _process_plan
    _process_plan = plan


def process_plan() -> "FaultPlan | None":
    return _process_plan
