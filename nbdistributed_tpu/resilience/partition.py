"""Host-level failure domains: partition suspicion (ISSUE 6).

With multi-host execution, the liveness signals the stack already
collects — heartbeat staleness, process deaths, transport EOFs — gain
a failure mode single-host worlds cannot produce: **every rank on one
host goes silent at once while the rest of the fleet is fine**.  That
signature is a network partition (or a dead host — indistinguishable
from here until the link heals), and treating it as N independent
worker deaths is exactly wrong: the far side is alive, riding the
orphan machinery, holding namespaces and possibly an in-flight result
that must be delivered exactly once when the link returns.

:class:`PartitionSentry` is the pure state machine both consumers
(``supervisor.py`` defers heals; ``watchdog.py`` suppresses hang
blame) share.  Per host it tracks::

    ok ──all ranks silent/dead while another host is fresh──▶ suspected
    suspected ──any rank fresh again──▶ ok        ("partition healed")
    suspected ──grace expires──▶ expired          (treat host as LOST)

The grace period (``NBD_PARTITION_GRACE_S``, default 30 s) is the
window in which a heal is deferred: shorter than the workers' orphan
TTL (so a healed link finds its orphans still alive), long enough that
a transient link flap never triggers a full respawn.  Transitions are
flight-recorded and counted (``nbd_partition_suspected_total``), so a
flapping DCN link is visible in ``%dist_status``, postmortems, and the
metrics export.

The coordinator's own host is never suspected: every rank sharing its
box going silent is not a *network* event from where we stand (and the
single-host world degenerates to "no host can ever be suspected",
paying nothing).
"""

from __future__ import annotations

import threading
import time

from ..observability import flightrec
from ..observability import metrics as obs_metrics

DEFAULT_PARTITION_GRACE_S = 30.0

OK = "ok"
SUSPECTED = "suspected"
EXPIRED = "expired"


def partition_grace_s(env=None) -> float:
    from ..utils import knobs
    return knobs.get_float("NBD_PARTITION_GRACE_S",
                           float(DEFAULT_PARTITION_GRACE_S), env=env)


def format_link_suffix(host_stats: dict) -> str:
    """``"rtt 2.1ms · hb-age 0.3s · retries 4"`` with None-guards —
    the ONE formatter behind every per-host link-health surface
    (``%dist_status`` host headers, the doctor's hosts/links table,
    postmortem reports), so the rendering and its edge handling can't
    drift apart across them.  ``host_stats`` is one value from
    ``CommunicationManager.link_stats()["hosts"]``."""
    rtt = host_stats.get("rtt_ms")
    hb = host_stats.get("hb_age_s")
    return " · ".join([
        f"rtt {rtt:.1f}ms" if rtt is not None else "rtt ?",
        f"hb-age {hb:.1f}s" if hb is not None else "hb-age -",
        f"retries {host_stats.get('retries', 0)}",
    ])


class PartitionSentry:
    """Tracks per-host partition suspicion from per-rank liveness.

    ``hosts`` maps rank -> host label; ``local_host`` is the
    coordinator's own label (exempt from suspicion).  Thread-safe;
    ``observe`` is the one mutator.  With fewer than two distinct
    remote-capable hosts the sentry is inert (``active`` False) and
    ``observe`` returns nothing.
    """

    def __init__(self, hosts: dict[int, str], *,
                 local_host: str = "local",
                 grace_s: float | None = None,
                 source: str = "supervisor",
                 clock=time.time):
        self.hosts = {int(r): str(h) for r, h in (hosts or {}).items()}
        self.local_host = local_host
        self.grace_s = (partition_grace_s() if grace_s is None
                        else float(grace_s))
        self.source = source
        self._clock = clock
        self._lock = threading.Lock()
        # host -> list of its ranks (suspicion domain: remote hosts only)
        self._domains: dict[str, list[int]] = {}
        for r, h in sorted(self.hosts.items()):
            if h != self.local_host:
                self._domains.setdefault(h, []).append(r)
        # Suspicion needs an "elsewhere is fine" witness, which any
        # OTHER host (including the local one) can provide — but there
        # must be at least one remote domain to suspect.
        self.active = bool(self._domains) and \
            len(set(self.hosts.values())) >= 2
        self._state: dict[str, str] = {h: OK for h in self._domains}
        self._since: dict[str, float] = {}

    # ------------------------------------------------------------------

    def observe(self, silent: set[int], dead: set[int],
                fresh: set[int], now: float | None = None) -> list[dict]:
        """Consume one liveness snapshot; return transition events.

        ``silent``: ranks whose heartbeats are stale; ``dead``: ranks
        whose process is known-exited; ``fresh``: ranks heard from
        recently.  Events are ``{"host", "event": "suspected" |
        "healed" | "expired", "ranks", "ts"}``; counters and flight
        records fire here so both consumers report identically.
        """
        if not self.active:
            return []
        now = self._clock() if now is None else now
        events: list[dict] = []
        with self._lock:
            for host, ranks in self._domains.items():
                gone = all(r in silent or r in dead for r in ranks)
                witness = any(r in fresh for r, h in self.hosts.items()
                              if h != host)
                st = self._state[host]
                if st == OK:
                    if gone and witness:
                        self._state[host] = SUSPECTED
                        self._since[host] = now
                        events.append({"host": host, "event": "suspected",
                                       "ranks": list(ranks), "ts": now})
                elif st == SUSPECTED:
                    if any(r in fresh for r in ranks):
                        self._state[host] = OK
                        self._since.pop(host, None)
                        events.append({"host": host, "event": "healed",
                                       "ranks": list(ranks), "ts": now})
                    elif now - self._since[host] > self.grace_s:
                        self._state[host] = EXPIRED
                        events.append({"host": host, "event": "expired",
                                       "ranks": list(ranks), "ts": now})
                elif st == EXPIRED:
                    # A host can come back even after we gave up on it
                    # (the heal may not have replaced it yet).
                    if any(r in fresh for r in ranks):
                        self._state[host] = OK
                        self._since.pop(host, None)
                        events.append({"host": host, "event": "healed",
                                       "ranks": list(ranks), "ts": now})
        for ev in events:
            flightrec.record(f"partition_{ev['event']}", host=ev["host"],
                             ranks=ev["ranks"], source=self.source)
            if ev["event"] == "suspected":
                obs_metrics.registry().counter(
                    "nbd_partition_suspected_total",
                    "whole-host heartbeat-loss episodes treated as "
                    "suspected partitions",
                    {"source": self.source}).inc()
        return events

    # ------------------------------------------------------------------

    def suspected_hosts(self) -> dict[str, float]:
        """host -> suspected-since timestamp, for hosts currently in
        the SUSPECTED state (grace not yet expired)."""
        with self._lock:
            return {h: self._since[h] for h, s in self._state.items()
                    if s == SUSPECTED}

    def expired_hosts(self) -> list[str]:
        with self._lock:
            return sorted(h for h, s in self._state.items()
                          if s == EXPIRED)

    def suspected_ranks(self) -> set[int]:
        """Every rank on a currently-suspected host — consumers must
        not treat their silence as death (supervisor) or their lag as
        a hang (watchdog) while the grace clock runs."""
        with self._lock:
            sus = {h for h, s in self._state.items() if s == SUSPECTED}
        return {r for r, h in self.hosts.items() if h in sus}

    def state_of(self, host: str) -> str:
        with self._lock:
            return self._state.get(host, OK)

    def describe(self) -> str:
        """One status line for %dist_status / the doctor."""
        with self._lock:
            sus = {h: self._since[h] for h, s in self._state.items()
                   if s == SUSPECTED}
            exp = [h for h, s in self._state.items() if s == EXPIRED]
        if not sus and not exp:
            return ""
        now = self._clock()
        parts = [f"⚡ {h}: suspected partition for {now - t:.0f}s "
                 f"(grace {self.grace_s:.0f}s)" for h, t in sus.items()]
        parts += [f"✖ {h}: partition grace expired — treated as lost"
                  for h in exp]
        return " · ".join(parts)
