"""Retry policy for control-plane requests.

One :class:`RetryPolicy` describes how ``CommunicationManager.
send_to_ranks`` redelivers a request whose responses are slow to
arrive: wait ``attempt_timeout_s``, then resend the SAME message id
(attempt counter bumped) to the ranks that have not answered yet, with
exponential backoff + jitter between redeliveries.  Redelivery is safe
because the worker's :class:`~nbdistributed_tpu.resilience.dedup.
ReplayCache` makes requests idempotent — a duplicate is answered from
the cached reply, never re-executed.

Retries are OFF by default (``attempt_timeout_s=None``): in the
default no-timeout "training mode" a slow cell is indistinguishable
from a lost frame, and worker death already aborts requests via the
death callbacks.  They are switched on per-manager (chaos tests,
flaky-DCN deployments) or fleet-wide via env::

    NBD_RETRY_TIMEOUT_S=5       # per-attempt wait; presence enables
    NBD_RETRY_ATTEMPTS=4        # total deliveries (1 initial + 3 re)

**Per-message-class budgets** (ISSUE 6): on a multi-host link, one
timeout cannot fit both a 200-byte control frame and a multi-GB
``%dist_push`` — a budget tight enough to catch a lost control frame
trips spurious redeliveries on every big transfer crossing a slow
link.  Message types therefore map to classes (``control`` vs
``bulk``), each overridable independently::

    NBD_RETRY_CLASS_BULK_TIMEOUT_S=60   # long-haul budget for
    NBD_RETRY_CLASS_BULK_ATTEMPTS=2     # push/pull/checkpoint frames
    NBD_RETRY_CLASS_CONTROL_TIMEOUT_S=5 # tight budget for the rest

Unset classes inherit the base ``NBD_RETRY_*`` policy, so existing
single-knob deployments behave byte-identically.
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass

# Message types whose payloads scale with user data (array pulls,
# pytree pushes, checkpoint IO): the "bulk" class.  Everything else —
# execute dispatch, status probes, hello/mailbox, chaos control — is
# "control": small frames whose loss should be detected fast.
BULK_TYPES = frozenset({"get_var", "set_var", "checkpoint",
                        # Streaming transfer plane (ISSUE 20): chunk
                        # frames are bulk by construction, and the
                        # begin/commit bookends wait on payload-sized
                        # work (prealloc, device put) at the worker.
                        "xfer_begin", "xfer_chunk", "xfer_commit",
                        "xfer_pull_begin", "xfer_read",
                        "xfer_pull_end"})
RETRY_CLASSES = ("control", "bulk")


def class_of(msg_type: str) -> str:
    return "bulk" if msg_type in BULK_TYPES else "control"


@dataclass(frozen=True)
class RetryPolicy:
    """Redelivery schedule for one request.

    ``attempts`` counts total deliveries (the initial send included).
    ``attempt_timeout_s=None`` disables redelivery entirely — the
    request waits out its caller deadline in one attempt, exactly the
    pre-retry behavior.
    """

    attempts: int = 4
    attempt_timeout_s: float | None = None
    backoff_base_s: float = 0.25
    backoff_factor: float = 2.0
    backoff_max_s: float = 5.0
    jitter: float = 0.25  # fraction of the backoff, symmetric

    def enabled(self) -> bool:
        return self.attempt_timeout_s is not None and self.attempts > 1

    def backoff_s(self, attempt: int, u: float | None = None) -> float:
        """Backoff after delivery ``attempt`` (0-based): exponential,
        capped, with +-``jitter`` fraction of spread.  ``u`` in [0, 1)
        pins the jitter draw for deterministic tests."""
        b = min(self.backoff_max_s,
                self.backoff_base_s * self.backoff_factor ** attempt)
        if self.jitter:
            if u is None:
                u = random.random()
            b *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return b

    def attempt_wait_s(self, attempt: int, u: float | None = None) -> float:
        """How long to wait for responses after delivery ``attempt``
        before redelivering: the per-attempt timeout plus the backoff
        (waiting for the reply IS the backoff opportunity — a response
        arriving during it completes the request immediately)."""
        return (self.attempt_timeout_s or 0.0) + self.backoff_s(attempt, u)

    @classmethod
    def from_env(cls, env=None) -> "RetryPolicy | None":
        from ..utils import knobs
        raw = knobs.get_raw("NBD_RETRY_TIMEOUT_S", env=env)
        if not raw:
            return None
        return cls(attempts=max(1, knobs.get_int("NBD_RETRY_ATTEMPTS",
                                                 4, env=env)),
                   attempt_timeout_s=float(raw))

    @classmethod
    def classes_from_env(cls, base: "RetryPolicy",
                         env=None) -> dict[str, "RetryPolicy"]:
        """Per-class overrides of ``base`` from ``NBD_RETRY_CLASS_*``.
        Only classes with at least one knob set appear in the result;
        a class with only ``_ATTEMPTS`` set inherits the base timeout
        (and stays disabled if the base has none).  Malformed values
        are ignored knob-wise — a typo'd number must not silently turn
        retries off for a whole class."""
        env = os.environ if env is None else env
        out: dict[str, RetryPolicy] = {}
        for klass in RETRY_CLASSES:
            prefix = f"NBD_RETRY_CLASS_{klass.upper()}_"
            timeout = base.attempt_timeout_s
            attempts = base.attempts
            seen = False
            raw = env.get(prefix + "TIMEOUT_S")
            if raw:
                try:
                    timeout = float(raw)
                    seen = True
                except ValueError:
                    pass
            raw = env.get(prefix + "ATTEMPTS")
            if raw:
                try:
                    attempts = max(1, int(raw))
                    seen = True
                except ValueError:
                    pass
            if seen:
                out[klass] = cls(
                    attempts=attempts, attempt_timeout_s=timeout,
                    backoff_base_s=base.backoff_base_s,
                    backoff_factor=base.backoff_factor,
                    backoff_max_s=base.backoff_max_s,
                    jitter=base.jitter)
        return out
