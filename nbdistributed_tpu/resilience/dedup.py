"""Worker-side reply replay cache: idempotent request redelivery.

The retry layer (``RetryPolicy`` in ``CommunicationManager``) resends
a request under the SAME message id when responses are slow — which is
indistinguishable, at the worker, from a duplicated frame on a flaky
link.  Either way the request must not run twice: a redelivered
``execute`` re-running user code would double-apply optimizer steps,
re-append to lists, double-increment counters — silent state
corruption.  The worker therefore remembers the replies it already
sent, keyed by message id, and answers a redelivered request from the
cache.

Bounded three ways:

- **entries** (LRU): retries target recent requests; anything older
  than ``capacity`` requests ago can no longer be in flight.
- **oversized read-only replies** are not cached at all: re-running a
  ``get_var``/``get_status`` on a redelivered frame is semantically
  safe (the handler only reads), so pinning a multi-GB params pull is
  pointless.
- **total bytes**: mutating request types (``execute``, ``set_var``,
  ``checkpoint``, ``sync``) must stay cached whole — re-running them
  is exactly the corruption this cache prevents — but their
  accumulated size (e.g. cells whose last expression reprs to tens of
  MB) is capped by evicting from the LRU end down to
  ``max_total_bytes``, always keeping the ``min_keep`` most recent
  replies (the only ones a live retry can still target).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

# Request types whose handlers only READ state: re-running one on a
# redelivered frame is semantically safe, so their (potentially huge)
# replies may be skipped / evicted by the byte bounds.  ``trace`` and
# ``metrics`` qualify: a dump/snapshot reply can run to megabytes (a
# span dump is bounded only by MAX_SPANS) and re-running either is
# harmless (start/stop replies are tiny, so they stay cached and
# idempotent regardless).  The bulk-transfer pull side (ISSUE 20)
# qualifies too: ``xfer_read`` replies carry whole chunks (pinning
# them would defeat the bounded-memory design), ``xfer_pull_begin``
# may answer inline with the full value, and re-running any of the
# three is safe (chunk reads are pure, a re-begun snapshot is simply
# a fresh one, pull_end is a pop).
_READ_ONLY = frozenset({"get_var", "get_namespace_info", "get_status",
                        "trace", "metrics", "xfer_read",
                        "xfer_pull_begin", "xfer_pull_end"})


def _json_size(v) -> int:
    """Approximate in-memory size of a JSON-able value, recursing into
    containers — a span dump is a deeply nested list of dicts, and
    sizing only top-level strings would account a multi-MB reply as a
    few bytes, making the byte bounds inert."""
    if isinstance(v, (str, bytes)):
        return len(v)
    if isinstance(v, dict):
        return sum(len(k) + _json_size(x) for k, x in v.items()) + 2
    if isinstance(v, (list, tuple)):
        return sum(_json_size(x) for x in v) + 2
    return 8  # number / bool / None


def _reply_bytes(reply) -> int:
    total = 0
    for v in getattr(reply, "bufs", {}).values():
        total += getattr(v, "nbytes", None) or len(v)
    return total + _json_size(getattr(reply, "data", None))


class _Spilled:
    """In-memory stub for a parked reply that lives on disk."""

    __slots__ = ("path", "nbytes", "msg_type")

    def __init__(self, path: str, nbytes: int, msg_type):
        self.path = path
        self.nbytes = nbytes
        self.msg_type = msg_type


class ResultMailbox:
    """Parked replies awaiting redelivery to a FUTURE coordinator.

    When a worker's coordinator dies mid-cell (orphan grace, ISSUE 4)
    the finished cell's reply has nowhere to go: the mailbox keeps it,
    keyed by ``msg_id``, until a reattaching coordinator drains it.
    Claims are destructive — the exactly-once half of redelivery (the
    at-least-once half is the replay cache answering a redelivered
    ``drain`` from its own cache).  Bounded like the replay cache:
    oldest-first eviction by entry count and accumulated bytes, with
    the newest entry always kept (it is the in-flight cell's result —
    the one reattach exists to recover).
    """

    def __init__(self, capacity: int = 32,
                 max_total_bytes: int = 32 << 20,
                 spill_dir: str | None = None,
                 spill_entry_bytes: int = 8 << 20,
                 max_spill_bytes: int = 1 << 30):
        self.capacity = max(1, capacity)
        self.max_total_bytes = max_total_bytes
        # Disk spill (ISSUE 20): with a ``spill_dir``, a reply bigger
        # than ``spill_entry_bytes`` is codec-encoded to a chunk file
        # under the run dir and only a tiny stub stays in memory — a
        # multi-hundred-MB parked result no longer evicts the whole
        # mailbox or blows the 32 MB bound.  Failures are explicit
        # verdict replies (``too_large`` past ``max_spill_bytes``,
        # ``disk_full`` on a write error), never a silent drop.
        self.spill_dir = spill_dir
        self.spill_entry_bytes = spill_entry_bytes
        self.max_spill_bytes = max_spill_bytes
        self._box: OrderedDict[str, object] = OrderedDict()
        self._sizes: dict[str, int] = {}
        self._total = 0
        self.parked = 0      # park() calls accepted (monotonic)
        self.claimed = 0
        self.evicted = 0
        self.spilled = 0     # replies written to disk
        self.spill_verdicts = 0  # too_large / disk_full stubs parked
        # The worker's serial loop is single-threaded, but the GATEWAY
        # parks from serve threads while tenant hellos read ids() on
        # the listener thread — iteration during a concurrent park
        # raised RuntimeError exactly in the crash-recovery window.
        self._mlock = threading.Lock()

    # -- spill plumbing ------------------------------------------------

    def _spill_path(self, msg_id: str) -> str:
        safe = "".join(c for c in msg_id if c.isalnum())[:64] or "reply"
        return os.path.join(self.spill_dir, f"mbox-{safe}.nbd")

    def _verdict(self, reply, verdict: str, size: int):
        """An explicit verdict reply standing in for one that could
        not be parked — the claimant learns WHY the result is gone."""
        from ..messaging.codec import Message
        self.spill_verdicts += 1
        return Message(
            msg_type="response",
            data={"error": f"parked reply unavailable: {verdict}",
                  "verdict": verdict, "nbytes": size,
                  "orig_type": getattr(reply, "msg_type", None)},
            msg_id=getattr(reply, "msg_id", ""),
            rank=getattr(reply, "rank", -1))

    def _spill_or_verdict(self, msg_id: str, reply, size: int):
        """Returns ``(entry, mem_size)`` — a ``_Spilled`` stub after a
        successful disk write, else a verdict reply."""
        from ..messaging.codec import encode
        if size > self.max_spill_bytes:
            return self._verdict(reply, "too_large", size), 256
        path = self._spill_path(msg_id)
        try:
            os.makedirs(self.spill_dir, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(encode(reply))
            os.replace(tmp, path)
        except Exception as e:
            if isinstance(e, OSError):
                return self._verdict(reply, "disk_full", size), 256
            return self._verdict(reply, f"encode_failed: {e}",
                                 size), 256
        self.spilled += 1
        return _Spilled(path, size,
                        getattr(reply, "msg_type", None)), 256

    @staticmethod
    def _load(entry):
        """Materialize a parked entry (reads + decodes a spilled one;
        a lost file becomes an explicit verdict, not a KeyError)."""
        if not isinstance(entry, _Spilled):
            return entry
        from ..messaging.codec import Message, decode
        try:
            with open(entry.path, "rb") as f:
                return decode(f.read())
        except Exception:
            return Message(
                msg_type="response",
                data={"error": "parked reply unavailable: spill_lost",
                      "verdict": "spill_lost",
                      "nbytes": entry.nbytes,
                      "orig_type": entry.msg_type})

    @staticmethod
    def _discard(entry) -> None:
        if isinstance(entry, _Spilled):
            try:
                os.unlink(entry.path)
            except OSError:
                pass

    # -- the mailbox ---------------------------------------------------

    def park(self, msg_id: str, reply) -> bool:
        """Store (or refresh) a reply for later claim.  Oversized
        replies spill to disk when a spill dir is configured."""
        size = _reply_bytes(reply)
        entry: object = reply
        if self.spill_dir is not None and size > self.spill_entry_bytes:
            entry, size = self._spill_or_verdict(msg_id, reply, size)
        with self._mlock:
            self._discard(self._box.get(msg_id))
            self._box[msg_id] = entry
            self._box.move_to_end(msg_id)
            self._total += size - self._sizes.get(msg_id, 0)
            self._sizes[msg_id] = size
            while len(self._box) > 1 and (
                    len(self._box) > self.capacity
                    or self._total > self.max_total_bytes):
                old, gone = self._box.popitem(last=False)
                self._total -= self._sizes.pop(old, 0)
                self._discard(gone)
                self.evicted += 1
            self.parked += 1
        return True

    def claim(self, msg_id: str):
        """Pop one parked reply (None if absent / already claimed)."""
        with self._mlock:
            entry = self._box.pop(msg_id, None)
            if entry is not None:
                self._total -= self._sizes.pop(msg_id, 0)
                self.claimed += 1
        if entry is None:
            return None
        reply = self._load(entry)
        self._discard(entry)
        return reply

    def claim_all(self) -> dict[str, object]:
        """Pop everything, oldest first."""
        with self._mlock:
            entries = dict(self._box)
            self.claimed += len(entries)
            self._box.clear()
            self._sizes.clear()
            self._total = 0
        out = {}
        for msg_id, entry in entries.items():
            out[msg_id] = self._load(entry)
            self._discard(entry)
        return out

    def ids(self) -> list[str]:
        with self._mlock:
            return list(self._box)

    def peek_all(self) -> dict[str, object]:
        """Non-destructive snapshot, oldest first.  Migration export
        reads the parked set WITHOUT claiming it — the destructive
        claim happens once, at the destination pool, so a migration
        that dies between export and import loses nothing.  Spilled
        entries are materialized from disk without deleting them."""
        with self._mlock:
            entries = dict(self._box)
        return {mid: self._load(e) for mid, e in entries.items()}

    def counters(self) -> dict:
        with self._mlock:
            return {"parked": self.parked, "claimed": self.claimed,
                    "evicted": self.evicted, "held": len(self._box),
                    "bytes": self._total, "spilled": self.spilled,
                    "spill_verdicts": self.spill_verdicts}

    def __len__(self) -> int:
        with self._mlock:
            return len(self._box)


class ReplayCache:
    """msg_id -> already-sent reply, bounded LRU.  Single-consumer by
    design: only the worker's serial request loop touches it."""

    def __init__(self, capacity: int = 128,
                 max_buf_bytes: int = 8 << 20,
                 max_total_bytes: int = 64 << 20, min_keep: int = 8):
        self.capacity = capacity
        self.max_buf_bytes = max_buf_bytes
        self.max_total_bytes = max_total_bytes
        self.min_keep = min_keep
        self._cache: OrderedDict[str, object] = OrderedDict()
        self._sizes: dict[str, int] = {}
        self._total = 0
        self.hits = 0       # redeliveries answered from cache
        self.stores = 0

    def get(self, msg_id: str):
        reply = self._cache.get(msg_id)
        if reply is not None:
            self.hits += 1
            self._cache.move_to_end(msg_id)
        return reply

    def put(self, request, reply) -> bool:
        """Record the reply just sent for ``request``.  Returns whether
        it was cached (False only for oversized read-only replies)."""
        size = _reply_bytes(reply)
        if request.msg_type in _READ_ONLY and size > self.max_buf_bytes:
            return False
        self._cache[request.msg_id] = reply
        self._cache.move_to_end(request.msg_id)
        self._total += size - self._sizes.get(request.msg_id, 0)
        self._sizes[request.msg_id] = size
        while (len(self._cache) > self.capacity
               or (self._total > self.max_total_bytes
                   and len(self._cache) > self.min_keep)):
            evicted, _ = self._cache.popitem(last=False)
            self._total -= self._sizes.pop(evicted, 0)
        self.stores += 1
        return True

    @property
    def total_bytes(self) -> int:
        return self._total

    def __len__(self) -> int:
        return len(self._cache)
